"""The inference engine: C++ batcher + JAX paged prefill/decode loop.

Upstream analogue (UNVERIFIED, SURVEY.md §2b "Triton Inference Server" row):
the TPU-native continuous-batching decode server (JetStream-class).  Request
admission, slot lifecycle and KV page accounting live in the C++ core
(core.cc via native.py); this module runs the decode loop on the accelerator:

    loop:
      reap deadline-expired queue entries
      admit queued requests into free slots in QoS policy order
        (scheduler.py decides WHO: priority class / EDF / adapter fair
        share, preempting a lower-priority decode slot when the head is
        blocked; the C++ core decides WHETHER pages fit, all-or-nothing)
      group prefilling slots (short prompts by bucket, long ones by chunk
        offset) -> ONE fused prefill per group -> one fused KV-page scatter
        -> one batched first-token sample per group
      one fused decode_step over ALL slots  (static shapes, no recompiles)
      commit sampled tokens (C++ grows pages; reports finish/OOM)

    Prefill batching (Orca/Sarathi-style iteration-level scheduling): an
    N-way burst of same-bucket prompts costs one [N, bucket] dispatch
    instead of N serialized batch-1 dispatches, and several long prompts
    advance one chunk each in a single call — the TTFT lever under bursty
    load (PAPERS.md).  Observability: stats.prefill_dispatches /
    prefill_rows / prefill_batch_hist.

Continuous batching means a long generation never blocks a short one: slots
free individually and the queue drains into them mid-flight.

Tick pipelining (ISSUE 5, README "Tick pipelining"): with
``pipeline_depth=1`` (the default) the steady-state decode loop is a
one-deep pipeline.  Sampling and the NaN guard are fused into the decode
dispatch (model.decode_step_sample), so each tick returns a guarded-token
DEVICE array the next tick consumes directly — no host upload of tokens
and no blocking readback between steps (seq_lens ride a host shadow
advanced by pure arithmetic, so they too never wait on the device).  Tick
N's
tokens start a non-blocking host copy at dispatch time and are committed to
the C++ batcher while tick N+1 is already running (commit-behind); page
accounting therefore lags one step, covered by a lookahead
``reserve_page`` before each dispatch.  Any roster change — admit, finish,
preempt, NaN-failed row, cancel, watchdog restart — drains the pipeline to
a sync barrier (a "fence") before host mirrors and device state are
rebuilt, replacing the sync loop's "blocking sample is the aliasing fence"
invariant with per-dispatch page-table snapshots.  ``pipeline_depth=0``
keeps the fully synchronous loop as the parity oracle: greedy outputs are
byte-identical between the two modes.

Pipelined speculative decoding (ISSUE 9, README "Speculative decoding"):
``speculative="prompt_lookup"`` now COMPOSES with the pipeline instead of
forcing sync ticks.  Verify + longest-prefix accept/reject + NaN guard
fuse into one dispatch (model.decode_step_verify_sample) returning a
single packed ``[B, K]`` token row per tick; the next dispatch derives its
committed-token feedback from that packed output on device, commit-behind
extends to 1..K tokens per slot per tick (the C++ commits, stream pushes
and TPOT telemetry run while the next verify executes), and the lookahead
reserve covers up to K pages ahead.  The host n-gram index advances from
the async readback between completion and the next dispatch — the only
host work left on the critical path is the draft lookup itself.

Sessions & tiered KV (ISSUE 7, README "Sessions & tiered KV"): requests
carrying a ``session_id`` pin their finished turn's KV pages into the
tiered store (kvstore.py: host RAM aging to checksummed disk page files)
instead of freeing them; the next turn restores the pinned prefix at
admission — byte-identically, verified — and re-prefills only the new
tail.  Every storage failure (torn write, bit flip, missing file, ENOSPC)
degrades transparently to recompute; pinned sessions survive watchdog
restart (host tier, swap cleared + counters reset) and full engine
restart (disk manifest replay, lazy re-adoption).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Iterator, Optional, Tuple

import numpy as np

from ..disagg import HandoffStore, normalize_role
from ..errors import (DeadlineExceeded, EngineOverloaded, EngineShutdown,
                      NonFiniteLogits, RequestError, SessionBusy,
                      TickFailure)
from ..incidents import IncidentConfig, IncidentManager, engine_detectors
from ..kvfabric import FabricStore, fabric_key
from ..slo import SloConfig, SloTracker
from .. import waterfall as waterfall_mod
from ..constrain import ConstraintStall
from .faults import (ChaosInjector, ConstrainChaos, ConstrainFaultConfig,
                     FabricChaos, FabricFaultConfig, FaultConfig,
                     HandoffChaos, HandoffFaultConfig)
from .kvstore import (KVStoreConfig, TieredKVStore, blob_degree,
                      normalize_session_id, pack_frame, pack_sharded_frame,
                      reshard_blob)
from .perf import (CacheStats, FlopsModel, PerfLedger, ProfileStore,
                   TickTimeline, WASTE_REASONS, platform_peak_flops)
from .scheduler import (PRIORITY_RANK, QosScheduler, QueueEntry,
                        SchedulerConfig, normalize_priority)
from .telemetry import (EngineTelemetry, FlightRecorder, RequestSpan,
                        TickProfiler)
from .model import (DecoderConfig, decode_step, decode_step_k,
                    decode_step_sample, decode_step_sample_packed,
                    decode_step_verify_sample, prefill,
                    prefill_chunk, sample_tokens, write_pages)
from .native import NativeBatcher

PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024)

# Written by benchmarks/engine_chip_check.py after the full composed config
# (paged × int8-KV × int8-weights × speculative × prefix-cache) passes its
# oracle comparison ON A REAL TPU.  Its presence flips paged_kernel's
# default to on — but only for TPU backends (CPU runs keep the gather path;
# the Pallas interpreter is a correctness tool, not a fast path), and only
# while the kernel source still hashes to what was validated: an edit to
# paged_attention.py voids the marker rather than riding a stale pass.
_PAGED_VALIDATED_MARKER = os.path.join(os.path.dirname(__file__),
                                       "PAGED_CHIP_VALIDATED")


_PAGED_KERNEL_SRC = os.path.join(os.path.dirname(__file__),
                                 "paged_attention.py")


def _paged_kernel_default() -> bool:
    env = os.environ.get("ENGINE_PAGED_KERNEL")
    if env is not None:
        return env == "1"
    from ...utils.chipmarker import marker_valid

    if not marker_valid(_PAGED_VALIDATED_MARKER, _PAGED_KERNEL_SRC):
        return False
    import jax

    return jax.default_backend() == "tpu"


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_slots: int = 8
    num_pages: int = 512
    page_size: int = 32
    max_pages_per_slot: int = 64
    eos_id: int = -1           # -1: never stop early
    # additional stop ids: multi-EOS checkouts (Llama-3-Instruct declares
    # [128001, 128009] and chat turns end with <|eot_id|>=128009) stop on
    # ANY of eos_id + eos_ids; tuple so the frozen config stays hashable
    eos_ids: Tuple[int, ...] = ()
    temperature: float = 0.0   # 0 = greedy
    seed: int = 0
    # prompts longer than this are prefilled in page-aligned chunks of this
    # size, one chunk per engine tick, so decode steps for active slots
    # interleave with a long prefill instead of stalling behind it
    prefill_chunk: int = 256
    # Pallas paged-attention decode path (paged_attention.py); None defers
    # to ENGINE_PAGED_KERNEL, then to the PAGED_CHIP_VALIDATED marker that
    # benchmarks/engine_chip_check.py writes once the composed config passes
    # its oracle check on a real TPU (default-on for TPU backends from then
    # on). Composes with kv_quant (in-kernel dequant), tensor_parallel
    # (shard_map over the tensor mesh) and speculative (multi-query verify).
    paged_kernel: Optional[bool] = None
    # tensor-parallel degree (sharding.py): >1 places params + KV pool over a
    # 1-D GSPMD mesh so Llama-8B-class models span a slice.
    tensor_parallel: int = 1
    # KV-cache quantization: "int8" stores pool entries as int8 + per-token
    # scales (~52% of the bf16 bytes — near-double servable context); None
    # defers to the ENGINE_KV_QUANT env var.
    kv_quant: Optional[str] = None
    # weight-only quantization: "int8" halves at-rest param HBM (per-output
    # scales, dequant fused into each matmul) — Llama-8B-class weights fit a
    # single 16GB v5e next to the KV pool.  None defers to ENGINE_WEIGHT_QUANT.
    weight_quant: Optional[str] = None
    # decode-loop pipelining: 1 (default) overlaps host orchestration with
    # the device step — sampling fused into the decode dispatch, async
    # token readback, commit-behind with lookahead page reservation; 0 is
    # the fully synchronous loop (the greedy-parity oracle).  Composes
    # with ``speculative``: verify + accept/reject + guard fuse into one
    # dispatch and commits run behind it, 1..K tokens per slot per tick
    # (README "Speculative decoding").
    pipeline_depth: int = 1
    # speculative decoding: "prompt_lookup" drafts the continuation of the
    # last n-gram's previous occurrence in the context and verifies up to
    # spec_max_draft tokens in ONE decode pass (lossless under greedy —
    # accepted tokens are exactly what argmax would have produced). None
    # defers to ENGINE_SPECULATIVE. Requires temperature 0.
    speculative: Optional[str] = None
    spec_max_draft: int = 4
    spec_ngram: int = 2
    # ---- fault tolerance (README "Failure model") -----------------------
    # admission control: submissions past this many queued-unadmitted
    # requests fail fast with EngineOverloaded (0 = unbounded)
    max_queue_depth: int = 0
    # deadline applied to requests that don't pass one explicitly (seconds
    # from submit; None = no deadline).  Expired requests are shed before
    # their first token with DeadlineExceeded.
    default_deadline_s: Optional[float] = None
    # a request whose tick (prefill group / decode step) raises is retried
    # in place; after this many CONSECUTIVE failures it is rejected with
    # TickFailure instead (a successful commit resets the count)
    max_consecutive_failures: int = 3
    # watchdog supervisor: checks the loop thread every interval; a loop
    # that died (escaped exception) has its in-flight futures failed and —
    # when watchdog_restart — is restarted with a fresh decode state.  A
    # loop stuck inside one tick longer than hang_timeout_s is DEGRADED,
    # then epoch-fenced and restarted the same way.  hang_timeout_s must
    # dwarf worst-case jit compile time: the first tick of a new shape
    # legitimately blocks for minutes on a cold cache.
    watchdog_interval_s: float = 0.5
    hang_timeout_s: float = 300.0
    watchdog_restart: bool = True
    # stop(): how long the graceful drain waits for in-flight slots to
    # finish before failing them with EngineShutdown
    drain_timeout_s: float = 10.0
    # verify per-row logit finiteness before committing sampled tokens
    # (costs one extra [B]-bool device fetch per tick; a NaN row fails only
    # its own slot with NonFiniteLogits instead of emitting garbage)
    logit_guard: bool = True
    # ---- observability (README "Observability") -------------------------
    # master switch for the telemetry layer: lifecycle spans, latency
    # histograms, and the flight recorder.  Off = the loop pays one boolean
    # check per hook (serving_bench --obs measures the on-cost)
    telemetry: bool = True
    # flight recorder: ring capacity (structured tick events kept for
    # postmortem dumps) and where JSONL dumps land (None: ENGINE_FLIGHT_DIR
    # env, else <tmpdir>/engine_flightrec)
    flight_recorder_capacity: int = 256
    flight_dir: Optional[str] = None
    # completed request spans kept for Engine.trace(rid) after the request
    # resolves (live requests are always traceable).  Budgeted in BOTH
    # entries and approximate bytes (spans vary in size with prefill
    # chunks, preemption cycles, links): a long-lived fleet replica must
    # not grow span history without bound.  Evictions count in
    # engine_trace_evictions_total.
    trace_history: int = 512
    trace_history_bytes: int = 1_000_000
    # per-class SLO targets/windows (serving/slo.py; engine.json "slo"
    # block).  None = SloConfig() defaults — tracking runs whenever
    # telemetry does, so slo_attainment_ratio{class,metric} always exports
    slo: "Optional[SloConfig]" = None
    # ---- performance introspection (README "Performance introspection") -
    # FLOPs/MFU accounting, goodput attribution, tick-phase timeline and
    # cache analytics (perf.py).  None = follows ``telemetry``; the bench
    # flips it independently to measure the plane's own overhead honestly.
    perf: Optional[bool] = None
    # rolling window the MFU / goodput gauges derive over
    perf_window_s: float = 60.0
    # per-tick phase-timeline ring capacity (bounded like the flight
    # recorder: a long soak keeps the last N ticks, not all of them)
    perf_timeline_capacity: int = 256
    # managed jax.profiler artifact store (POST /engine/profile): capture
    # dirs live under profile_dir (None: ENGINE_PROFILE_DIR env, else a
    # per-pid tempdir), capped in count AND bytes with oldest-first
    # eviction, and removed on stop() — profiles must not accumulate
    # across engine lifecycles
    profile_dir: Optional[str] = None
    profile_max_runs: int = 8
    profile_max_bytes: int = 256 << 20
    # deterministic chaos injection (faults.py) — test/bench substrate
    chaos: Optional[FaultConfig] = None
    # ---- QoS scheduling (README "Scheduling & QoS") ---------------------
    # per-tick admission policy + preemption knobs (scheduler.py).  None =
    # SchedulerConfig() — priority classes / EDF / fair share, preemption
    # on.  SchedulerConfig(policy="fifo", preemption=False) restores the
    # pre-QoS submission-order behavior (the SLO bench baseline).
    scheduler: Optional[SchedulerConfig] = None
    # ---- tiered KV store / sessions (README "Sessions & tiered KV") ----
    # host-RAM + disk tier budgets and placement for preemption swap and
    # pinned session KV (kvstore.py).  None = KVStoreConfig with the
    # scheduler's swap_max_bytes as the host budget and a fresh private
    # disk dir (tiering works, but sessions only survive a full engine
    # restart when disk_dir points somewhere stable).
    kv_store: Optional[KVStoreConfig] = None
    # ---- disaggregated serving (README "Disaggregated serving") --------
    # the replica's declared role: "prefill" | "decode" | "unified".
    # Advisory at engine level (any engine can export or import handoffs);
    # the service proxy reads the matching pod annotation for placement —
    # engine.json carries this so the engine and its pod cannot silently
    # disagree in a hand-rolled deployment.
    role: str = "unified"
    # exported-KV handle lifetime + byte budget (disagg.HandoffStore): an
    # orphaned export (decode replica died before pulling) expires instead
    # of pinning pool-sized blobs in host RAM; budget overruns evict
    # oldest-first and that export degrades to the unified path
    handoff_ttl_s: float = 60.0
    handoff_max_bytes: int = 256 << 20
    # deterministic handoff-fault injection (faults.HandoffFaultConfig):
    # torn/slow/dead-link pulls, pre-expired exports — every one must
    # degrade to re-prefill, never fail a request
    handoff_chaos: Optional[HandoffFaultConfig] = None
    # ---- fleet KV fabric (README "Fleet KV fabric") ---------------------
    # when on, every finishing request's committed full-page prefix is
    # published (keyed by its context chain hashes) into a fleet-
    # addressable FabricStore other replicas pull from — multi-reader,
    # TTL'd, byte-budgeted.  Off by default: publishing snapshots device
    # pages to host per finish, a cost only shared-prefix fleets should
    # pay.  fabric_min_pages gates tiny prefixes out (one page of shared
    # KV is not worth a frame).
    fabric: bool = False
    fabric_ttl_s: float = 120.0
    fabric_max_bytes: int = 256 << 20
    fabric_min_pages: int = 1
    # deterministic fabric-fault injection (faults.FabricFaultConfig):
    # torn/flipped/slow/dead-link pulls, pre-expired publishes — every
    # one must degrade to re-prefill, never fail a request
    fabric_chaos: Optional[FabricFaultConfig] = None
    # ---- structured output (README "Structured output") -----------------
    # deterministic constrained-decoding fault injection
    # (faults.ConstrainFaultConfig): corrupted token-map cache reads must
    # degrade to a counted re-compile (never an invalid output); forced
    # zero-legal-token masks must fail ONLY the stalled slot and feed the
    # incident plane's constraint_stall detector
    constrain_chaos: Optional[ConstrainFaultConfig] = None
    # ---- incident plane (README "Incident plane") -----------------------
    # background fault-detection + evidence-correlation manager
    # (serving/incidents.py): watchdog trips, tick-deadline overruns,
    # NaN-guard trips, storage/handoff/fabric degradation outcomes,
    # SLO burn-threshold crossings and admission rejections open
    # classified postmortem bundles served as GET /engine/incidents.
    # Off by default: the manager runs a polling thread per engine — a
    # cost only deployments that want self-diagnosis should pay (the
    # raw signals are all exported regardless).
    incidents: bool = False
    # where postmortem bundles land (None: <tmpdir>/engine_incidents)
    incident_dir: Optional[str] = None
    # cascading symptoms within this window of an open incident's LAST
    # symptom coalesce into its causal chain instead of alert-storming
    incident_debounce_s: float = 5.0
    # this much symptom-free quiet resolves an open incident (must be
    # >= debounce or one burst could bridge straight through resolution)
    incident_resolve_s: float = 15.0
    # incident-manager processing/polling cadence (the SLO burn detector
    # reads rolling windows nothing events on)
    incident_poll_s: float = 0.25
    # a WORK tick slower than this feeds a tick_overrun signal (0 = off;
    # the watchdog hang detector still covers the pathological case —
    # this catches the chronic-slow-tick regime below hang_timeout_s)
    incident_tick_overrun_s: float = 0.0


@dataclasses.dataclass
class _Pending:
    tokens: list          # prompt token ids
    max_new_tokens: int
    future: Future
    generated: list = dataclasses.field(default_factory=list)
    submitted_at: float = 0.0
    first_token_at: float = 0.0
    # chain hashes for the prompt's full pages (prefix-cache identity)
    page_hashes: "np.ndarray" = None
    # streaming: every committed token is also pushed here, then a final
    # (None, result) sentinel (generate_stream consumes it)
    stream: "queue.Queue" = None
    # set by Engine.cancel(); the loop finishes the request at its next tick
    cancelled: bool = False
    # adapter id (0 = base model) — resolved from the name at submit time
    adapter_id: int = 0
    # committed context (prompt + generated, one list — no per-tick concat)
    # plus the incrementally-built n-gram index for prompt-lookup drafting:
    # maps n-gram -> most recent start strictly before the final n-gram, so
    # each position is indexed once per request instead of rescanned per tick
    context: list = None
    ngram_index: dict = dataclasses.field(default_factory=dict)
    ngram_p: int = 0
    # absolute perf_counter deadline (None = none); expired requests are
    # shed with DeadlineExceeded before their first token
    deadline: Optional[float] = None
    # consecutive tick failures while this request was in the offending
    # group; reset on every successful commit, rejected at the config cap
    failures: int = 0
    # lifecycle span (telemetry.RequestSpan; None when telemetry is off)
    span: "RequestSpan" = None
    # perf_counter of the most recent committed token (TPOT numerator)
    last_token_at: float = 0.0
    # ---- QoS scheduling state (scheduler.py) ---------------------------
    # priority class + its admission rank (interactive=0 < batch <
    # best_effort); preemption only evicts strictly larger ranks
    priority: str = "interactive"
    rank: int = 0
    # times this request was preempted out of its decode slot
    preemptions: int = 0
    # swap-preempted: KV pages live in the tiered KV store under this rid;
    # resume_len is the committed context length to restore (seq_len at
    # eviction — KV coverage and decode input reconstruct from it exactly)
    swapped: bool = False
    resume_len: int = 0
    # ---- sessions (README "Sessions & tiered KV") ----------------------
    # own request id (set at submit; the session-busy release key)
    rid: int = -1
    # conversation pin: a finished turn's KV pages park in the tiered
    # store under this id instead of vanishing with the slot, and the
    # next turn restores them instead of re-prefilling
    session_id: "Optional[str]" = None
    # how this turn's prefix was recovered — None until the first
    # admission, then host|disk|cache|cold|degraded (degraded = the store
    # had the session but verification failed; fell back to re-prefill)
    session_restore: "Optional[str]" = None
    # ---- disaggregated serving (README "Disaggregated serving") --------
    # prefill phase: export this request's committed KV pages into the
    # handoff store at finish (the decode replica pulls them by handle)
    handoff: bool = False
    # decode phase: the prompt's KV arrived as a verified handoff blob
    # (parked in the tiered store under this rid; scattered at admission
    # via the swap-resume path).  Any import failure degrades to plain
    # re-prefill — this flag routes that degradation instead of _fail_slot
    handoff_import: bool = False
    # ---- fleet KV fabric (README "Fleet KV fabric") --------------------
    # a verified remote prefix frame awaiting admission: (blob, frame
    # chain hashes, nbytes).  Held on the pending record (not the tiered
    # store — a prefix import needs no budget and must not interact with
    # swap accounting); the admission path scatters the hash-verified
    # prefix pages and re-prefills only the tail.  Cleared at admission.
    fabric_import: "Optional[tuple]" = None
    # how the fabric import resolved — None (no import), then
    # hit|local|degraded; reported in the result dict's "fabric" block
    fabric_restore: "Optional[str]" = None
    # ---- perf introspection (README "Performance introspection") -------
    # when set, this request's NEXT prefill is recomputing work that was
    # already done somewhere (preempt_recompute / handoff_degraded /
    # failover_reprefill): the perf ledger charges those prefill FLOPs as
    # waste under this reason instead of goodput.  Decode commits after
    # the prefill are fresh work and ignore it.
    waste_reason: "Optional[str]" = None
    # ---- ingress brownout (README "Overload control") ------------------
    # degradation stage the ingress admitted this request under: >= 2
    # disables speculation drafting for it (verify dispatches are the
    # first quality-not-availability cost to drop under load), >= 3
    # additionally defers the fleet-fabric publish at finish (publishing
    # snapshots device pages to host — deferrable work by definition)
    brownout: int = 0
    # ---- structured output (README "Structured output") ----------------
    # grammar constraint (serving/constrain.py GrammarConstraint) gating
    # every token this request samples; None = unconstrained.  The
    # automaton advances exactly once per committed token (in _commit),
    # host-side, off the device critical path; preemption snapshots its
    # configuration set so resume restores it byte-exact, like KV.
    constrain: "Optional[object]" = None
    # automaton snapshot taken at preemption (GrammarConstraint.snapshot
    # dict); restored + cleared when the request is re-admitted
    constrain_snap: "Optional[dict]" = None


class _StaleThread(BaseException):
    """Raised inside a superseded loop thread at its first state-mutation
    attempt after an epoch-fenced restart.  BaseException so the isolation
    boundaries (which catch Exception) can't contain it: the stale thread
    exits instead of committing tokens into a slot the restarted loop may
    have reassigned."""


class _StreamHandle:
    """Iterator over streamed tokens + the request's ``future`` (the handle
    ``Engine.cancel`` takes when a streaming client disconnects)."""

    def __init__(self, it, future: Future):
        self._it = it
        self.future = future

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)


class Engine:
    """Continuous-batching generation engine over one jit'd model."""

    def __init__(self, params, config: DecoderConfig, engine_config: EngineConfig = EngineConfig(),
                 lora=None):
        import jax
        import jax.numpy as jnp

        self.params = params
        self.config = config
        self.ec = engine_config
        # full stop set: primary eos_id (if any) plus the multi-EOS extras
        self._stop_ids = frozenset(
            i for i in (engine_config.eos_id,) + tuple(engine_config.eos_ids)
            if i >= 0)
        # multi-LoRA: ``lora`` = (stacked adapter pytree, {name: id}) from
        # lora.load_adapters — id 0 is the reserved zero adapter, so the
        # per-slot id table below makes every decode row pick its own
        # adapter with no branching (lora.py module docstring)
        self._lora = lora[0] if lora else None
        self.adapters = dict(lora[1]) if lora else {}
        self._aid_host = np.zeros((engine_config.max_slots,), np.int32)
        self.batcher = NativeBatcher(
            engine_config.max_slots, engine_config.num_pages,
            engine_config.page_size, engine_config.max_pages_per_slot,
        )
        c = config
        shape = (c.n_layers, engine_config.num_pages, c.n_kv_heads,
                 engine_config.page_size, c.head_dim)
        self._paged = (engine_config.paged_kernel if engine_config.paged_kernel is not None
                       else _paged_kernel_default())
        self._kv_quant = (engine_config.kv_quant if engine_config.kv_quant is not None
                          else os.environ.get("ENGINE_KV_QUANT") or None)
        wq = (engine_config.weight_quant if engine_config.weight_quant is not None
              else os.environ.get("ENGINE_WEIGHT_QUANT") or None)
        if wq not in (None, "int8"):
            raise ValueError(f"unsupported weight_quant {wq!r}")
        if wq == "int8":
            from .model import quantize_weights_int8

            # host-side, chunked (numpy leaves out) — the dense model never
            # hits the accelerator; quantize BEFORE TP sharding so each chip
            # receives int8 shards.  Single-chip placement happens below once
            # (TP placement is shard_params' job).
            self.params = quantize_weights_int8(self.params)
            if engine_config.tensor_parallel <= 1:
                self.params = jax.device_put(self.params)
        self._weight_quant = wq
        self._spec = (engine_config.speculative if engine_config.speculative is not None
                      else os.environ.get("ENGINE_SPECULATIVE") or None)
        if self._spec is not None and self._spec != "prompt_lookup":
            raise ValueError(f"unsupported speculative mode {self._spec!r}")
        if self._spec and engine_config.temperature > 0:
            raise ValueError("speculative decoding requires temperature 0 "
                             "(greedy acceptance is what makes it lossless)")
        if self._spec and (engine_config.spec_max_draft < 1
                           or engine_config.spec_ngram < 1):
            raise ValueError("spec_max_draft and spec_ngram must be >= 1")
        from .model import make_kv_pool

        self._mesh = None
        if engine_config.tensor_parallel > 1:
            from .sharding import alloc_pool, shard_params, tensor_mesh, validate_config

            mesh = tensor_mesh(engine_config.tensor_parallel)
            validate_config(c, mesh)
            self._mesh = mesh
            # pools are allocated sharded-direct and params stream per-leaf to
            # their shards (pass host/numpy arrays for models that don't fit
            # one chip — that's the whole point of TP serving)
            self.params = shard_params(self.params, mesh)
            self.k_pool = alloc_pool(shape, mesh, quant=self._kv_quant)
            self.v_pool = alloc_pool(shape, mesh, quant=self._kv_quant)
        else:
            self.k_pool = make_kv_pool(shape, self._kv_quant)
            self.v_pool = make_kv_pool(shape, self._kv_quant)
        if engine_config.prefill_chunk % engine_config.page_size != 0:
            raise ValueError("prefill_chunk must be a multiple of page_size")
        self._requests: dict[int, _Pending] = {}
        self._slot_req: dict[int, int] = {}
        self._prefilling: dict[int, int] = {}  # slot -> next prompt offset
        # Host-side mirrors of the C++ slot state, grown incrementally
        # (slot_pages row at admission + commit_token_ex page grants) so the
        # decode loop never re-snapshots max_slots x max_pages from C per
        # tick.  Invariant: rows/lens are LIVE only for decode-ready slots —
        # they stay zero (trash page, len 0) while a slot is prefilling, so
        # the decode step's unconditional KV write cannot touch its pages.
        self._pt_host = np.zeros(
            (engine_config.max_slots, engine_config.max_pages_per_slot), np.int32)
        self._len_host = np.zeros((engine_config.max_slots,), np.int32)
        self._prefill_rows: dict[int, "np.ndarray"] = {}  # slot -> page row
        # ---- pipelined decode state (README "Tick pipelining") ----------
        if engine_config.pipeline_depth not in (0, 1):
            raise ValueError("pipeline_depth must be 0 (sync) or 1")
        self._pipe_depth = engine_config.pipeline_depth
        # the one uncommitted in-flight tick: {"sampled": dev guarded-token
        # array, "slots": tuple, "rids": {slot: rid}} — committed behind
        # the NEXT dispatch, or at a fence
        self._inflight: Optional[dict] = None
        # device-resident token array feeding the next dispatch (the
        # previous tick's guarded sample — the feedback edge that keeps
        # host round-trips off the steady-state path); None = rebuild from
        # host mirrors before dispatching
        self._dec_state = None
        # host shadow of the seq_lens the NEXT dispatch will use (committed
        # length + in-flight lag) — advanced by pure arithmetic (never read
        # back), uploaded per dispatch, and drives the lookahead page
        # reservation.  Rebound, never mutated in place: the in-flight
        # dispatch may alias it zero-copy on CPU backends.
        self._dec_lens_shadow = np.zeros((engine_config.max_slots,), np.int32)
        # any roster change (activate/release/preempt/restart) flips this:
        # the next pipelined dispatch drains + rebuilds first; the reason
        # labels the fence in engine_pipeline_fences_total
        self._roster_dirty = True
        self._dirty_reason: Optional[str] = None
        # double-buffered page-table snapshots: commit-behind mutates
        # _pt_host while a dispatch is in flight, so each dispatch gets its
        # own stable copy (the sync loop's blocking sample made the raw
        # mirror safe; the pipeline must not rely on that)
        self._pt_dispatch = [np.zeros_like(self._pt_host) for _ in range(2)]
        self._pt_flip = 0
        # steady-state host caches (invalidated on roster changes): last
        # committed token per slot — the sync decode input, maintained by
        # _commit instead of a per-tick Python scatter over all slots —
        # and the request-id-per-row list _guard_logits consumes
        self._tok_host = np.zeros((engine_config.max_slots,), np.int32)
        self._row_rids_c: Optional[list] = None
        self._fences = 0
        self._fence_reasons: dict[str, int] = {}
        # (tick, perf_counter) of the last decode dispatch completion —
        # consecutive-tick gaps land in engine_dispatch_gap_seconds
        self._dispatch_mark: Optional[tuple] = None
        # copy_to_host_async is a real D2H DMA kickoff on accelerators but
        # BLOCKS until the computation completes on the CPU backend (there
        # is nothing to overlap with) — measured 15% per-tick regression at
        # 1 slot; the commit-behind np.asarray handles CPU readiness fine
        self._async_readback = jax.default_backend() != "cpu"
        self._next_id = 0
        self._lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._key = jax.random.PRNGKey(engine_config.seed)
        self._sample_calls = 0
        # O(1) cancel: future -> rid, maintained at submit/finish so a
        # cancel storm never scans _requests under the lock
        self._future_rid: dict[Future, int] = {}  # guarded-by: _lock
        # prefill batching counters (stats): fused dispatches issued, total
        # prompt rows they carried, and a batch-size histogram
        self._prefill_dispatches = 0
        self._prefill_rows_total = 0
        self._prefill_batch_hist: dict[int, int] = {}
        self._spec_proposed = 0
        self._spec_accepted = 0
        # ---- QoS scheduling state (scheduler.py) ------------------------
        # submissions land in the host-side scheduler queue, NOT the C++
        # queue: each tick drains it in policy order (priority/EDF/fair
        # share) via submit-then-admit, so the C++ FIFO only ever holds the
        # entry being admitted right now (or a rare failed-admit leftover)
        self._scfg = (engine_config.scheduler
                      if engine_config.scheduler is not None
                      else SchedulerConfig())
        weights: dict = {}
        for name, w in self._scfg.adapter_weights:
            if name not in self.adapters:
                raise ValueError(f"adapter_weights names unknown adapter "
                                 f"{name!r} (loaded: {sorted(self.adapters)})")
            weights[self.adapters[name]] = float(w)
        self._sched = QosScheduler(self._scfg, weights)
        self._preemptions = 0
        # ---- sessions (ISSUE 7) -----------------------------------------
        # session id -> rid of its one queued/in-flight turn: a session's
        # KV timeline is serial, so a second concurrent turn is refused
        # with SessionBusy (HTTP 409).  Guarded by self._lock.
        self._session_active: dict[str, int] = {}  # guarded-by: _lock
        # ---- fault tolerance state --------------------------------------
        self._chaos = (ChaosInjector(engine_config.chaos)
                       if engine_config.chaos is not None else None)
        self._draining = False
        self._stopped = False
        # epoch fence: a restarted loop bumps this; a stale (previously
        # hung) thread that wakes up sees the mismatch and exits without
        # touching engine state
        self._epoch = 0
        self._last_tick_ts = time.monotonic()
        self._ticks = 0
        self._ticks_failed = 0
        self._requests_shed = 0      # deadline expiry before first token
        self._requests_rejected = 0  # EngineOverloaded at submit
        self._requests_failed = 0    # TickFailure / NonFiniteLogits / shutdown
        self._nan_rows = 0
        self._restarts = 0
        # count of in-flight requests with failures > 0, so health() reads
        # DEGRADED without an O(requests) scan under the hot-loop lock
        self._retrying = 0
        # ---- observability (telemetry.py) -------------------------------
        # per-engine registry (TTFT/TPOT/queue-wait/tick histograms + KV
        # gauges), tick-event ring for postmortems, completed-span history
        # for trace(rid), and the on-demand jax.profiler capture hook
        self.telemetry = EngineTelemetry(
            enabled=engine_config.telemetry,
            slo=(SloTracker(engine_config.slo or SloConfig())
                 if engine_config.telemetry else None))
        # tiered KV backing store (kvstore.py): preemption swap blobs +
        # pinned session KV over host RAM aging to checksummed disk page
        # files; a stable disk_dir makes pinned sessions survive a full
        # engine restart (the store replays its manifest here, re-adopting
        # pages lazily on first touch)
        kvcfg = (engine_config.kv_store if engine_config.kv_store is not None
                 else KVStoreConfig(host_max_bytes=self._scfg.swap_max_bytes))
        self._kv = TieredKVStore(kvcfg, on_event=self.telemetry.count_kv_event)
        # ---- disaggregated serving (README "Disaggregated serving") -----
        # exported-KV handle registry (prefill side) + the handoff chaos
        # injector the decode side's pull path consults (serve.py)
        normalize_role(engine_config.role)
        self._handoffs = HandoffStore(
            ttl_s=engine_config.handoff_ttl_s,
            max_bytes=engine_config.handoff_max_bytes)
        self._handoff_chaos = (HandoffChaos(engine_config.handoff_chaos)
                               if engine_config.handoff_chaos is not None
                               else None)
        # ---- fleet KV fabric (README "Fleet KV fabric") ------------------
        # published-prefix registry (multi-reader, TTL'd, byte-budgeted;
        # served to remote pullers via GET /engine/kv_fabric/<key>) + the
        # fabric chaos injector the pulling side's serve layer consults.
        # fabric_fingerprinter is wired by JetStreamModel (it owns the
        # tokenizer): tokens -> the text fingerprint ladder the router's
        # placement scorer matches request prompts against; without it
        # publishes carry no fps (direct pulls by key still work).
        self._fabric = (FabricStore(ttl_s=engine_config.fabric_ttl_s,
                                    max_bytes=engine_config.fabric_max_bytes)
                        if engine_config.fabric else None)
        self._fabric_chaos = (FabricChaos(engine_config.fabric_chaos)
                              if engine_config.fabric_chaos is not None
                              else None)
        # ---- structured output (README "Structured output") --------------
        # constrained-decoding chaos (zero-legal-mask forcing consulted by
        # _build_grammar_masks; the registry's cache-read corruption hook
        # is wired by serve.py, which owns the ConstrainRegistry) plus the
        # subsystem's loop-side counters
        self._constrain_chaos = (ConstrainChaos(engine_config.constrain_chaos)
                                 if engine_config.constrain_chaos is not None
                                 else None)
        self._constrained_requests = 0
        self._constraint_stalls = 0
        self.fabric_fingerprinter = None
        # model identity stamped into every published frame (wired by
        # JetStreamModel alongside the fingerprinter): two same-shape
        # models can produce identical chain hashes for a shared prompt
        # — the chain seeds on tokens + adapter, not weights — so the
        # pulling side must match THIS too, or model A's KV scatters
        # into model B's pool and decodes silently wrong
        self.fabric_model_id = None
        # ---- performance introspection plane (perf.py, ISSUE 11) --------
        # analytical FLOPs model + goodput ledger (charged at dispatch,
        # attributed at commit), per-tick phase timeline, prefix-cache
        # analytics, and the managed profiler artifact store.  The plane
        # follows the telemetry switch unless overridden — the bench
        # measures its own overhead by flipping `perf` alone.
        self._perf_on = (engine_config.perf if engine_config.perf is not None
                         else engine_config.telemetry)
        plat, peak = platform_peak_flops(
            jax.default_backend(),
            getattr(jax.devices()[0], "device_kind", ""),
            max(1, engine_config.tensor_parallel))
        self._fm = FlopsModel(c, lora=self._lora)
        self.perf = PerfLedger(
            peak, plat, window_s=engine_config.perf_window_s,
            on_charge=(self.telemetry.count_flops if self._perf_on
                       else None))
        self.timeline = TickTimeline(
            capacity=engine_config.perf_timeline_capacity)
        self.cache_analytics = CacheStats()
        self.profiles = ProfileStore(
            parent=engine_config.profile_dir,
            max_runs=engine_config.profile_max_runs,
            max_bytes=engine_config.profile_max_bytes)
        self.flight = FlightRecorder(
            capacity=engine_config.flight_recorder_capacity,
            dump_dir=engine_config.flight_dir)
        self._trace_ring: "dict[int, RequestSpan]" = {}  # guarded-by: _lock
        # retained-size accounting for the trace ring (trace_history_bytes
        # budget; sizes cached per rid so evict decrements exactly what
        # archive charged)
        self._trace_ring_bytes = 0
        self._trace_sizes: dict[int, int] = {}  # guarded-by: _lock
        # trace id -> flight-recorder dump paths referencing it (bounded):
        # a failover postmortem finds the dying replica's flight dump from
        # the assembled trace tree instead of grepping the flight dir
        self._trace_dumps: "dict[str, list[str]]" = {}  # guarded-by: _lock
        # session id -> (trace_id, span_id) of its most recent terminal
        # turn, so turn N+1's span links turn N (bounded alongside
        # _trace_dumps by _TRACE_REF_CAP)
        self._session_spans: "dict[str, tuple[str, str]]" = {}  # guarded-by: _lock
        self._nan_dump_tick = -1  # last tick that produced a NaN dump
        # ---- incident plane (serving/incidents.py, README "Incident
        # plane") --------------------------------------------------------
        # background fault correlator: hot paths only ever feed() it (an
        # O(1) append); detection, evidence snapshots, classification and
        # bundle writes run on ITS thread, never the loop's.  The burn
        # detector is a poller (rolling-window burn rates are computed,
        # not evented); _burn_above edge-triggers it per (class, metric).
        self.incidents: Optional[IncidentManager] = None
        self._burn_above: set = set()
        if engine_config.incidents:
            self.incidents = IncidentManager(
                scope="engine",
                config=IncidentConfig(
                    debounce_s=engine_config.incident_debounce_s,
                    resolve_s=engine_config.incident_resolve_s,
                    poll_interval_s=engine_config.incident_poll_s,
                    bundle_dir=engine_config.incident_dir),
                detectors=engine_detectors(),
                evidence=self._incident_evidence,
                dump=self._incident_dump,
                on_firing=self.telemetry.count_incident_firing,
                on_resolve=self.telemetry.count_incident,
                on_open_count=self.telemetry.set_incidents_open)
            self.incidents.add_poller(self._incident_poll)
        self._profiler = TickProfiler()
        # capture completion (loop thread) closes out the ProfileStore run
        # record: artifacts get sized, count/byte caps evict oldest-first
        self._profiler.on_complete = self._profile_complete
        self._wd_stop = threading.Event()
        self._wd_thread: Optional[threading.Thread] = None
        # loop threads record their epoch here; state-mutation points check
        # it so a stale (superseded) thread dies instead of writing
        self._tls = threading.local()
        self._jax = jax
        self._jnp = jnp

    # ---------------------------------------------------------------- public

    def start(self) -> None:
        if (self._running and self._thread is not None
                and self._thread.is_alive()):
            # idempotent: serve.py's load() starts the engine it was handed,
            # which a caller may already have started — a second loop
            # thread on the same pools would race every dispatch's
            # buffer-donation contract (two ticks donating the same
            # k_pool/v_pool = "buffer has been deleted or donated" chaos)
            return
        self._running = True
        self._draining = False
        self._last_tick_ts = time.monotonic()
        self._thread = threading.Thread(
            target=self._loop, args=(self._epoch,), daemon=True)
        self._thread.start()
        if self.ec.watchdog_interval_s > 0 and self._wd_thread is None:
            self._wd_stop.clear()
            self._wd_thread = threading.Thread(target=self._watchdog,
                                               daemon=True)
            self._wd_thread.start()
        if self.incidents is not None:
            self.incidents.start()  # idempotent, like this method

    def begin_drain(self) -> None:
        """Enter DRAINING without stopping: new submissions are refused with
        EngineShutdown, but queued and in-flight requests keep running to
        completion — the fleet-level graceful-drain handshake (the router
        stops routing to a DRAINING replica on its next health probe; the
        operator calls ``stop()`` once ``stats['active_slots']`` and
        ``stats['queue_depth']`` reach zero, or lets the drain timeout
        force it)."""
        with self._lock:
            self._draining = True

    def cancel_drain(self) -> None:
        """Abort an in-progress ``begin_drain`` (scale-down was cancelled):
        the engine resumes accepting submissions.  No-op after stop()."""
        with self._lock:
            if not self._stopped:
                self._draining = False

    def stop(self, drain: bool = True) -> None:
        """Graceful drain then hard stop.

        New submissions are refused (EngineShutdown) immediately; requests
        still queued behind the slots are failed with EngineShutdown (never
        silently stranded); in-flight slots get up to ``drain_timeout_s``
        to finish, then are failed too.  ``drain=False`` skips the wait."""
        with self._lock:  # atomic with generate_async's shutdown check
            self._draining = True  # generate_async refuses; health DRAINING
        # retire the watchdog FIRST: joining it fences any _supervise in
        # flight, so self._thread cannot be swapped for a restarted loop
        # between the join and batcher.close() below
        self._wd_stop.set()
        if self._wd_thread is not None:
            self._wd_thread.join(timeout=5)
            self._wd_thread = None
        # fail queued-unadmitted work NOW: no slot will ever free it if the
        # drain below times out, and the C++ queue entries are reaped at
        # admission (pending gone -> slot released untouched)
        self._fail_unassigned(EngineShutdown("engine stopping"))
        t = self._thread
        if drain and t is not None and t.is_alive():
            deadline = time.monotonic() + self.ec.drain_timeout_s
            while self._slot_req and time.monotonic() < deadline:
                time.sleep(0.01)
        self._running = False
        self._wake.set()
        if t is not None:
            t.join(timeout=10)
        # anything still in flight after the hard timeout: fail, don't hang
        # (the loop is joined: an uncommitted pipeline tick is dropped with
        # its requests, never committed into a closing batcher)
        if self._inflight is not None:
            self._charge_dropped(self._inflight, "tick_retry")
        self._inflight = None
        self._dec_state = None
        for slot in list(self._slot_req):
            self._fail_slot(slot, EngineShutdown("engine stopped"))
        self._fail_unassigned(EngineShutdown("engine stopped"))
        # retire the incident manager BEFORE the batcher closes: its final
        # processing pass may snapshot evidence through self.stats, which
        # reads the C core
        if self.incidents is not None:
            self.incidents.stop()
        self.batcher.close()
        # release the tiered KV store: an ephemeral (auto-tempdir) store
        # deletes its page files — nothing could ever recover them; an
        # explicit disk_dir keeps the session manifest for the next engine
        self._kv.close()
        # managed profiler artifacts die with the engine (perf.py): scratch
        # diagnostics nothing would ever reap once the process moves on
        self.profiles.close()
        # exported-but-unpulled handoff frames die with the engine: their
        # handles are only routable to THIS process — and so do published
        # fabric frames (a pull would route to this process's port)
        self._handoffs.clear()
        if self._fabric is not None:
            self._fabric.clear()
        self._stopped = True
        self._draining = False  # drain is over: health reports DEAD now

    def health(self) -> dict:
        """Engine health state machine (README "Failure model").

        SERVING   — loop thread alive, no distress signals
        DEGRADED  — alive but a request is mid-retry after tick failures,
                    or the current tick has been stuck past hang_timeout_s
        DRAINING  — stop() in progress
        DEAD      — loop thread not running (never started, stopped, or
                    died with restart disabled)
        """
        if self._draining:
            state = "DRAINING"
        else:
            t = self._thread
            age = time.monotonic() - self._last_tick_ts
            if not self._running or t is None or not t.is_alive():
                state = "DEAD"
            elif (self._slot_req or self._requests) and age > self.ec.hang_timeout_s:
                state = "DEGRADED"
            else:
                # O(1) gauge, no lock: _retrying tracks requests mid-retry
                # (maintained by _note_group_failure/_reset_failures) so a
                # /metrics scrape never scans a deep backlog under the
                # hot-loop lock
                state = "DEGRADED" if self._retrying > 0 else "SERVING"
        return {
            "state": state,
            "role": self.ec.role,
            "last_tick_age_s": round(time.monotonic() - self._last_tick_ts, 4),
            "ticks": self._ticks,
            "ticks_failed": self._ticks_failed,
            "restarts": self._restarts,
        }

    def generate_async(self, tokens: list[int], max_new_tokens: int = 32,
                       stream: Optional["queue.Queue"] = None,
                       adapter: Optional[str] = None,
                       deadline: Optional[float] = None,
                       priority: Optional[str] = None,
                       session_id: Optional[str] = None,
                       handoff: bool = False,
                       kv_import=None,
                       fabric_import=None,
                       trace=None,
                       links: Optional[list] = None,
                       waste_hint: Optional[str] = None,
                       brownout: int = 0,
                       pre_hints: Optional[dict] = None,
                       constrain=None) -> Future:
        """Submit a prompt; the Future resolves to a result dict.

        ``stream``: optional queue that receives each token id as it is
        committed, then a final ``(None, result)`` sentinel (or ``(None,
        exc)`` if the request failed).  ``adapter``: name of a loaded LoRA
        adapter to decode this request with (None = base model; unknown
        names raise).  ``deadline``: seconds from now; if the request has
        not produced its first token by then it is shed with
        DeadlineExceeded (defaults to ``default_deadline_s``).
        ``priority``: QoS class — "interactive" (default) | "batch" |
        "best_effort" — deciding admission order and preemption standing
        (scheduler.py; unknown classes raise RequestError).
        ``session_id``: conversation pin (README "Sessions & tiered KV"):
        the finished turn's KV pages park in the tiered store under this
        id and the NEXT turn with the same id — whose prompt must extend
        this turn's context — restores them instead of re-prefilling;
        a second turn while one is in flight raises SessionBusy (409).
        ``trace``: a ``core.tracing.TraceContext`` to adopt — the
        request's span joins that trace as a child (the ingress relay
        passes the hop context here via the ``traceparent`` header); a
        fresh trace is minted when absent.  ``links``: cross-trace span
        links (e.g. the failed relay hop a re-admission resumes from);
        a ``session_prev`` link to the session's previous turn is added
        automatically.
        ``handoff``: disaggregated PREFILL phase (README "Disaggregated
        serving") — at finish the request's committed KV pages are
        exported into the handoff store and the result dict carries a
        ``handoff`` block with the one-shot pull handle.
        ``kv_import``: disaggregated DECODE phase — a verified
        ``(blob, nbytes, resume_len)`` of KV pages covering the prompt
        (which must already include the prefill phase's first token);
        the admission path scatters them into a fresh slot row and decode
        starts without re-prefilling.  Any import problem — budget
        rejection here, blob lost or scatter failure later — silently
        degrades to a plain (prefix-cache-assisted) re-prefill.
        ``fabric_import``: fleet KV fabric prefix fault-in (README "Fleet
        KV fabric") — a verified ``(blob, hashes, nbytes)`` remote PREFIX
        frame: at admission the frame's chain hashes are matched against
        this prompt's and every verified page the local device cache did
        not already cover is scattered into the slot row; prefill resumes
        at the first uncovered position.  Unlike ``kv_import`` the frame
        need not cover the whole prompt.  Any mismatch or failure
        degrades to plain re-prefill (attributed ``fabric_degraded``).
        ``waste_hint``: perf-ledger attribution (README "Performance
        introspection") — the caller knows this request's prefill
        recomputes work already done elsewhere (``failover_reprefill``
        for an ingress failover re-admission, ``handoff_degraded`` for a
        disaggregation import that fell back before submit); the charged
        prefill FLOPs land under that waste reason instead of goodput.
        ``brownout``: ingress degradation stage (README "Overload
        control") — 0 = normal; >= 2 disables speculation drafting for
        this request; >= 3 additionally defers the fleet-fabric publish
        at finish.  Quality degrades, never correctness.
        ``pre_hints``: latency-attribution walls the serve layer spent
        on this request BEFORE submit (``{"fabric_pull": s}`` /
        ``{"handoff_import": s}`` — README "Latency attribution"); they
        ride the request's span so the waterfall can attribute the relay
        hop's lead-in instead of leaving it unaccounted.
        ``constrain``: a ``serving.constrain.GrammarConstraint`` (README
        "Structured output") gating every sampled token of this request —
        built per request by the serve layer from its registry (grammar
        compile + tokenizer map both happen OFF the tick loop, at
        admission).  The engine advances it once per committed token and
        ships its legal-token mask into the fused sampler as one extra
        masked-logits op; the constraint's token table must be mapped for
        THIS model's vocab or the submit raises.
        Raises EngineOverloaded when the queue is at ``max_queue_depth``
        and EngineShutdown once stop() has begun."""
        if not tokens:
            raise RequestError("empty prompt")
        if waste_hint is not None and waste_hint not in WASTE_REASONS:
            raise RequestError(f"unknown waste_hint {waste_hint!r} "
                               f"(known: {WASTE_REASONS})")
        prio = normalize_priority(priority)
        if session_id is not None:
            session_id = normalize_session_id(session_id)
        if constrain is not None:
            tv = getattr(getattr(constrain, "table", None), "vocab_size", None)
            if tv != self.config.vocab_size:
                # a mask sized for another vocab would silently mis-gate
                # every token — the one constraint shape bug admission
                # CAN catch cheaply, so it must
                raise RequestError(
                    f"constraint token table maps vocab {tv}, model vocab "
                    f"is {self.config.vocab_size}")
        if self._draining or self._stopped:
            # fast-path: also keeps the overload check below from touching
            # a closed batcher (RuntimeError) after stop(); the locked
            # check further down is the authoritative one
            raise EngineShutdown("engine is stopping")
        # capacity check (the old C++ submit-time -1): a request that can
        # never fit must fail HERE, not head-of-line-block the scheduler
        if (self._pages_for(len(tokens) + max_new_tokens)
                > self.ec.max_pages_per_slot
                or self._pages_for(len(tokens)) >= self.ec.num_pages):
            raise RequestError(
                f"prompt+generation ({len(tokens)}+{max_new_tokens}) exceeds engine capacity "
                f"({self.ec.max_pages_per_slot * self.ec.page_size} tokens/slot)"
            )
        depth = len(self._sched) + self.batcher.queue_depth
        if self.ec.max_queue_depth > 0 and depth >= self.ec.max_queue_depth:
            self._requests_rejected += 1
            if self.incidents is not None:
                # capacity signal (README "Incident plane"): admission-
                # queue growth past the bound with no replica-health
                # evidence is the classifier's "capacity" shape; a
                # rejection storm coalesces into one incident inside the
                # debounce window.  Trace-id sampling only happens with
                # the plane ON — a plane-off rejection must stay free.
                self.incidents.feed("queue_growth", queue_depth=depth,
                                    rejected=1,
                                    trace_ids=self._live_trace_ids())
            exc = EngineOverloaded(
                f"queue depth {depth} >= "
                f"max_queue_depth {self.ec.max_queue_depth}")
            # load-proportional retry hint (README "Overload control"):
            # the deeper the queue relative to the slots draining it,
            # the longer a client should back off.  The HTTP layer
            # surfaces it as Retry-After; the ingress retry loop honors
            # it (jittered) instead of re-pick hammering the next
            # replica.
            exc.retry_after_s = round(min(
                10.0, 0.25 + 0.1 * depth / max(1, self.ec.max_slots)), 3)
            raise exc
        if deadline is None:
            deadline = self.ec.default_deadline_s
        aid = 0
        if adapter is not None:
            if adapter not in self.adapters:
                raise RequestError(f"unknown adapter {adapter!r} "
                                   f"(loaded: {sorted(self.adapters)})")
            aid = self.adapters[adapter]
        fut: Future = Future()
        hashes = self._page_hashes(tokens, aid)
        now = time.perf_counter()
        with self._lock:
            # shutdown check is atomic with registration: stop() flips
            # _draining under this lock BEFORE failing unassigned work, so
            # a racing submitter either raises here or registers in time
            # for stop()'s sweep to fail its future — never stranded
            if self._draining or self._stopped:
                raise EngineShutdown("engine is stopping")
            if session_id is not None and session_id in self._session_active:
                raise SessionBusy(
                    f"session {session_id!r} already has request "
                    f"{self._session_active[session_id]} in flight")
            rid = self._next_id
            self._next_id += 1
            span = None
            if self.ec.telemetry:
                span = RequestSpan(rid, trace=trace, links=links, cls=prio)
                if pre_hints:
                    # serve-layer walls spent on this request BEFORE the
                    # span's clock started (fabric/handoff pulls): the
                    # fleet waterfall carves them out of the relay hop's
                    # lead-in (waterfall.PRE_HINT_SEGMENTS)
                    for k, v in pre_hints.items():
                        span.hint(f"pre_{k}", float(v))
                if session_id is not None:
                    prev = self._session_spans.get(session_id)
                    if prev is not None:
                        # turn N+1 links turn N: a session's timeline stays
                        # navigable even though each turn is its own trace
                        span.links.append({"type": "session_prev",
                                           "trace_id": prev[0],
                                           "span_id": prev[1]})
            pending = self._requests[rid] = _Pending(
                tokens=list(tokens), max_new_tokens=max_new_tokens,
                future=fut, submitted_at=now, page_hashes=hashes,
                stream=stream, context=list(tokens), adapter_id=aid,
                deadline=(now + deadline if deadline is not None else None),
                span=span,
                priority=prio, rank=PRIORITY_RANK[prio],
                rid=rid, session_id=session_id, handoff=handoff,
                waste_reason=waste_hint,
                brownout=max(0, min(3, int(brownout))),
                constrain=constrain,
            )
            if constrain is not None:
                self._constrained_requests += 1
            if session_id is not None:
                self._session_active[session_id] = rid
            self._future_rid[fut] = rid
        if kv_import is not None:
            # park the pulled blob in the tiered store under this rid; the
            # admission path then takes the swap-resume scatter verbatim.
            # resume_len must equal the submitted token count — the blob's
            # KV covers positions [0, len(tokens)-2] and the first decode
            # step writes position len(tokens)-1 (serve.py validated the
            # frame; this is the engine-side backstop)
            blob, nbytes, resume_len = kv_import
            ok = False
            if int(resume_len) == len(tokens):
                try:
                    ok = self._kv.put_swap(rid, blob, int(nbytes),
                                           count=False)
                except Exception:  # noqa: BLE001 — import must degrade
                    ok = False
            if ok:
                pending.swapped = True
                pending.resume_len = int(resume_len)
                pending.handoff_import = True
                self.telemetry.count_handoff("import")
                self.telemetry.count_handoff_bytes("in", int(nbytes))
            else:
                # the decode replica will re-prefill work the prefill
                # replica already did: waste, attributed
                pending.waste_reason = "handoff_degraded"
                self.telemetry.count_handoff("degraded")
                self._note_degradation("handoff", "park_failed", pending)
        if fabric_import is not None and kv_import is None:
            # a verified remote prefix frame rides the pending record
            # (not the tiered store: it is freed with the record, so no
            # reap path can ever leak it); admission matches hashes and
            # scatters.  Parked bytes are still BUDGETED — a burst of
            # hinted requests against a backed-up queue must degrade to
            # re-prefill, not accumulate unaccounted host RAM (the same
            # rule put_swap enforces for handoff imports).  fabric_max_
            # bytes doubles as the parking budget; the O(requests) scan
            # runs once per HINTED submit, never on the tick loop.
            try:
                blob, fhashes, fnbytes = fabric_import
                fnbytes = int(fnbytes)
                fh = np.asarray(fhashes, np.uint64)
                with self._lock:
                    # check + reserve atomically: two concurrent hinted
                    # submits must not both observe the pre-park total
                    # and overshoot the budget together
                    parked = sum(p.fabric_import[2]
                                 for p in self._requests.values()
                                 if p.fabric_import is not None)
                    if parked + fnbytes > self.ec.fabric_max_bytes:
                        raise MemoryError(
                            "fabric parking budget exhausted")
                    pending.fabric_import = (blob, fh, fnbytes)
                self.telemetry.count_fabric("import")
                self.telemetry.count_fabric_bytes("in", fnbytes)
            except Exception:  # noqa: BLE001 — import must degrade
                pending.fabric_import = None
                pending.fabric_restore = "degraded"
                pending.waste_reason = "fabric_degraded"
                self.telemetry.count_fabric("degraded")
                self._note_degradation("fabric", "park_failed", pending)
        if waste_hint in ("handoff_degraded", "fabric_degraded"):
            # the serve layer degraded the import BEFORE submit (pull
            # failed verification/timeout): same incident signal as an
            # engine-side degrade — the fault story must not depend on
            # WHERE along the pull path the fault landed
            self._note_degradation(waste_hint.split("_", 1)[0],
                                   "pre_submit", pending)
        # the request now waits in the HOST scheduler queue; the engine
        # loop submits it to the C++ core only when the policy admits it
        # (per-tick admission — the Orca iteration-level scheduling point)
        self._sched.push(self._entry_for(rid, pending))
        self._wake.set()
        return fut

    def _entry_for(self, rid: int, pending: _Pending) -> QueueEntry:
        return QueueEntry(
            rid=rid, rank=pending.rank, deadline=pending.deadline,
            submitted_at=pending.submitted_at,
            adapter_id=pending.adapter_id,
            pages=self._pages_for(len(pending.tokens)))

    def _page_hashes(self, tokens: list[int], adapter_id: int = 0) -> "np.ndarray":
        """Chain hashes for each FULL prompt page: hash(page i) folds in
        hash(page i-1), so a match means an identical token prefix at
        identical positions. 0 is reserved as the no-parent sentinel.

        The adapter id seeds the chain: a LoRA adapter changes the KV a
        prompt produces, so identical prompts under different adapters must
        NEVER share prefix-cache pages."""
        import hashlib

        ps = self.ec.page_size
        n = len(tokens) // ps
        out = np.zeros((n,), np.uint64)
        prev = adapter_id.to_bytes(4, "little") if adapter_id else b""
        for i in range(n):
            page = np.asarray(tokens[i * ps:(i + 1) * ps], np.int32).tobytes()
            digest = hashlib.blake2b(prev + page, digest_size=8).digest()
            out[i] = max(1, int.from_bytes(digest, "little"))  # 0 = sentinel
            prev = digest
        return out

    def generate(self, tokens: list[int], max_new_tokens: int = 32, timeout: float = 300.0,
                 adapter: Optional[str] = None,
                 deadline: Optional[float] = None,
                 priority: Optional[str] = None,
                 session_id: Optional[str] = None,
                 handoff: bool = False, kv_import=None, fabric_import=None,
                 trace=None, links: Optional[list] = None,
                 waste_hint: Optional[str] = None,
                 brownout: int = 0,
                 pre_hints: Optional[dict] = None,
                 constrain=None) -> dict:
        fut = self.generate_async(tokens, max_new_tokens, adapter=adapter,
                                  deadline=deadline, priority=priority,
                                  session_id=session_id, handoff=handoff,
                                  kv_import=kv_import,
                                  fabric_import=fabric_import, trace=trace,
                                  links=links, waste_hint=waste_hint,
                                  brownout=brownout, pre_hints=pre_hints,
                                  constrain=constrain)
        try:
            return fut.result(timeout=timeout)
        except FutureTimeoutError:
            # the caller is gone but the request would keep its slot and KV
            # pages to the token budget: cancel so the engine reaps it at
            # its next tick (the queued case frees immediately)
            self.cancel(fut)
            raise

    def cancel(self, future: Future) -> bool:
        """Cancel the request behind a generate_async future (client went
        away). A request still waiting in the queue resolves IMMEDIATELY
        (``cancelled: True``, no tokens); one already in a slot is finished
        by the engine loop at its next tick, keeping whatever tokens were
        committed, and its slot/pages free right after. Returns False if the
        request already finished."""
        with self._lock:
            # O(1) future -> rid index (maintained at submit/finish): cancel
            # storms from disconnecting clients don't scan _requests under
            # the lock the hot loop takes
            rid = self._future_rid.get(future)
            pending = self._requests.get(rid) if rid is not None else None
            if pending is None:
                return False
            pending.cancelled = True
            queued = rid not in self._slot_req.values()
            if queued:
                # still queued: resolve now — no slot will free it for us.
                # (the C++ queue entry is reaped at admission: pending gone
                # -> the slot is released untouched).  A preempted request
                # keeps the tokens it committed before eviction.
                self._requests.pop(rid)
                self._future_rid.pop(future, None)
        if queued:
            # resolve OUTSIDE the lock (same split _finish uses): a Future
            # done-callback may re-enter the engine and take _lock
            self._sched.remove(rid)
            self._kv.discard_swap(rid)
            self._archive_span(pending, "cancelled")
            result = self._cancelled_result(rid, pending)
            pending.future.set_result(result)
            if pending.stream is not None:
                pending.stream.put((None, result))
            return True
        self._wake.set()
        return True

    def _cancelled_result(self, rid: int, pending: _Pending) -> dict:
        """The result dict a cancelled-while-queued request resolves to —
        same schema as _finish's (a preempted request keeps its committed
        tokens, preemption count and original TTFT)."""
        return {
            "rid": rid,
            "tokens": pending.generated,
            "num_tokens": len(pending.generated),
            "truncated": False,
            "cancelled": True,
            "preemptions": pending.preemptions,
            "ttft_s": (pending.first_token_at - pending.submitted_at
                       if pending.first_token_at else 0.0),
            "latency_s": time.perf_counter() - pending.submitted_at,
        }

    def _resolve_queued_cancel(self, rid: int, pending: _Pending) -> bool:
        """Loop-side twin of cancel()'s queued branch: pop a cancelled
        queued request and resolve its future with the tokens it kept.
        False when another path (cancel() itself) won the race and already
        resolved it."""
        with self._lock:
            if self._requests.get(rid) is not pending:
                return False
            self._requests.pop(rid, None)
            self._future_rid.pop(pending.future, None)
        self._sched.remove(rid)
        self._kv.discard_swap(rid)
        self._archive_span(pending, "cancelled")
        result = self._cancelled_result(rid, pending)
        try:
            pending.future.set_result(result)
        except Exception:  # already resolved (lost a race with cancel)
            pass
        if pending.stream is not None:
            pending.stream.put((None, result))
        return True

    def generate_stream(self, tokens: list[int], max_new_tokens: int = 32,
                        timeout: float = 300.0,
                        adapter: Optional[str] = None,
                        deadline: Optional[float] = None,
                        priority: Optional[str] = None,
                        session_id: Optional[str] = None,
                        kv_import=None,
                        fabric_import=None,
                        trace=None,
                        links: Optional[list] = None,
                        waste_hint: Optional[str] = None,
                        brownout: int = 0,
                        pre_hints: Optional[dict] = None,
                        constrain=None) -> Iterator:
        """Yield token ids as they are committed, then a final result dict.

        The last item yielded is the same dict ``generate`` returns (so
        callers get ttft/latency/truncated without a second call).  The
        prompt is submitted NOW (plain method returning an iterator), so the
        request runs even if the caller delays iteration.  ``timeout``
        bounds the wait for EACH next token (a stall), not the whole
        generation — a healthy long run streams for as long as it needs.
        The returned iterator exposes ``.future`` so a disconnected client
        can be reaped via ``Engine.cancel(stream.future)``."""
        q: queue.Queue = queue.Queue()
        fut = self.generate_async(tokens, max_new_tokens, stream=q,
                                  adapter=adapter, deadline=deadline,
                                  priority=priority, session_id=session_id,
                                  kv_import=kv_import,
                                  fabric_import=fabric_import,
                                  trace=trace, links=links,
                                  waste_hint=waste_hint,
                                  brownout=brownout, pre_hints=pre_hints,
                                  constrain=constrain)

        def _iter():
            while True:
                try:
                    item = q.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError(
                        f"generation stalled past {timeout}s") from None
                if isinstance(item, tuple) and item[0] is None:
                    if isinstance(item[1], BaseException):
                        raise item[1]  # typed engine fault (shed/failed)
                    yield item[1]  # final result dict
                    return
                yield item

        return _StreamHandle(_iter(), fut)

    @property
    def stats(self) -> dict:
        # snapshot under the engine lock: atomic with respect to the
        # _lock-guarded request paths (submit/cancel/finish registration),
        # so a scrape never interleaves with a request being moved between
        # queue and slot.  Loop-side counters are plain monotonic ints
        # mutated lock-free on the hot path (individually never torn under
        # the GIL); the lock does NOT freeze those or the C batcher
        # mid-tick — cross-field skew of one tick is acceptable in a
        # metrics read and not worth serializing the decode loop for
        with self._lock:
            return {
                "active_slots": self.batcher.num_active,
                # host scheduler queue + the (normally empty) C++ queue
                "queue_depth": len(self._sched) + self.batcher.queue_depth,
                "free_pages": self.batcher.free_pages,
                "preemptions": self._preemptions,
                "scheduler": self._sched.snapshot(),
                **self._kv.stats(),
                "spec_proposed": self._spec_proposed,
                "spec_accepted": self._spec_accepted,
                "prefill_dispatches": self._prefill_dispatches,
                "prefill_rows": self._prefill_rows_total,
                "prefill_batch_hist": dict(self._prefill_batch_hist),
                "pipeline_depth": self._pipe_depth,
                "pipeline_fences": self._fences,
                "pipeline_fence_reasons": dict(self._fence_reasons),
                "ticks": self._ticks,
                "ticks_failed": self._ticks_failed,
                "requests_shed": self._requests_shed,
                "requests_rejected": self._requests_rejected,
                "requests_failed": self._requests_failed,
                "nan_rows": self._nan_rows,
                "restarts": self._restarts,
                "constrained_requests": self._constrained_requests,
                "constraint_stalls": self._constraint_stalls,
                "trace_history_entries": len(self._trace_ring),
                "trace_history_bytes": self._trace_ring_bytes,
                "role": self.ec.role,
                "handoff": self._handoffs.stats(),
                **({"fabric": self._fabric.stats()}
                   if self._fabric is not None else {}),
                **({"handoff_chaos": self._handoff_chaos.stats()}
                   if self._handoff_chaos is not None else {}),
                **({"fabric_chaos": self._fabric_chaos.stats()}
                   if self._fabric_chaos is not None else {}),
                **({"constrain_chaos": self._constrain_chaos.stats()}
                   if self._constrain_chaos is not None else {}),
                **({"slo": self.telemetry.slo.snapshot()}
                   if self.telemetry.slo is not None else {}),
                **({"incidents": self.incidents.stats()}
                   if self.incidents is not None else {}),
                **({"chaos": self._chaos.stats()} if self._chaos else {}),
                **self.batcher.cache_stats(),
            }

    # ---------------------------------------------------------- sessions API

    def sessions(self) -> dict:
        """Pinned sessions in the tiered KV store: id -> {nbytes, version,
        tiers, context_len, pages}.  Surviving entries from a previous
        engine run (manifest replay) appear here before first touch."""
        return self._kv.session_list()

    def drop_session(self, session_id: str) -> bool:
        """Unpin a session: its KV leaves both tiers and the manifest.
        False if no such session.  In-flight turns are unaffected (their
        pin at finish simply re-creates the entry)."""
        return self._kv.drop_session(session_id)

    # ------------------------------------------------ incident plane API

    def incident_list(self) -> list:
        """Incidents this engine's manager holds (open first), served as
        ``GET /engine/incidents``.  Empty when the plane is off."""
        return self.incidents.list() if self.incidents is not None else []

    def incident_get(self, incident_id: str) -> Optional[dict]:
        return (self.incidents.get(incident_id)
                if self.incidents is not None else None)

    def incident_open_count(self) -> int:
        return (self.incidents.open_count()
                if self.incidents is not None else 0)

    def _incident_event(self, kind: str, **attrs) -> None:
        """The ONE incident-plane call the hot paths make: O(1) append
        into the manager's intake deque, no-op when the plane is off."""
        if self.incidents is not None:
            self.incidents.feed(kind, **attrs)

    def _note_degradation(self, source: str, outcome: str,
                          pending: "Optional[_Pending]" = None) -> None:
        """Degradation-outcome signal (README "Incident plane"): a
        storage-fault recompute, handoff re-prefill, or fabric degraded
        pull completed the request the slow way.  One call per degraded
        request at the site that counted the telemetry outcome."""
        if self.incidents is None:
            return
        tids = ([pending.span.trace_id]
                if pending is not None and pending.span is not None else [])
        self.incidents.feed("degradation", source=source, outcome=outcome,
                            rid=getattr(pending, "rid", None),
                            trace_ids=tids)

    def _live_trace_ids(self, cap: int = 4) -> list:
        """Trace ids of a few live requests — the correlation evidence
        for signals that concern the ENGINE rather than one request
        (burn crossings, queue pressure).  Falls back to the most recent
        ARCHIVED spans when nothing is in flight: a burn detected just
        after the offending burst drained must still cite resolvable
        traces (``/engine/trace/<id>`` serves the history ring too).
        Best-effort: called from the manager/caller threads, never worth
        blocking the loop over."""
        if not self.ec.telemetry:
            return []  # no spans exist to find: don't scan for them
        out: list = []
        try:
            with self._lock:
                # bounded iteration, never a full copy of the request
                # table: a rejection storm calls this at exactly the
                # moment the table is at its largest
                for p in self._requests.values():
                    if p.span is not None:
                        out.append(p.span.trace_id)
                        if len(out) >= cap:
                            break
                if not out:
                    for s in reversed(self._trace_ring.values()):
                        out.append(s.trace_id)
                        if len(out) >= cap:
                            break
            return out
        except Exception:  # noqa: BLE001 — evidence is best-effort
            return out

    def _incident_evidence(self) -> dict:
        """Evidence snapshot for a newly opened incident (manager
        thread): the metrics window, the health state, and the SLO burn
        series — the correlated cross-signal view a responder otherwise
        stitches together by hand."""
        out: dict = {}
        try:
            s = self.stats
            out["metrics"] = {k: s.get(k) for k in (
                "active_slots", "queue_depth", "free_pages", "ticks",
                "ticks_failed", "requests_shed", "requests_rejected",
                "requests_failed", "nan_rows", "restarts", "preemptions")}
            if "slo" in s:
                out["slo"] = s["slo"]
        except Exception:  # noqa: BLE001 — engine may be stopping
            pass
        try:
            out["health"] = self.health()
        except Exception:  # noqa: BLE001
            pass
        return out

    def _incident_dump(self, first_event: dict) -> Optional[str]:
        """Flight-recorder dump for a new incident: reuse the dump the
        triggering signal already produced (watchdog/NaN paths dump at
        the fault site — the recorder's capped lifetime dump budget must
        not be spent twice per fault), else force one now while the ring
        still holds the faulting ticks."""
        path = first_event.get("dump")
        if path:
            return path
        return self.flight.dump(
            "incident_open",
            extra={"kind": first_event.get("kind"),
                   "trace_ids": list(first_event.get("trace_ids") or ())})

    def _incident_poll(self) -> None:
        """SLO burn-threshold detector (manager thread): edge-triggered
        per (class, metric) against the tracker's OWN snapshot — the same
        burn values and thresholds ``/fleet/incidents`` evidence and
        ``Engine.stats['slo']`` report, one source of truth.  Re-arms at
        half the threshold so a rate hovering at the line doesn't flap."""
        slo = self.telemetry.slo
        if slo is None:
            return
        try:
            snap = slo.snapshot()
        except Exception:  # noqa: BLE001
            return
        seen: set = set()
        for cls, metrics in snap.items():
            for metric, rec in metrics.items():
                thr = rec.get("burn_threshold")
                burn = (rec.get("burn") or {}).get(rec.get("burn_window"))
                key = (cls, metric)
                seen.add(key)
                if burn is None or (thr and burn < thr * 0.5):
                    # re-arm BEFORE any other gate: a burn that cooled
                    # off (or drained below the sample floor) must be
                    # detectable again next episode
                    self._burn_above.discard(key)
                    continue
                if (rec.get("burn_samples") or 0) \
                        < (rec.get("burn_min_samples") or 0):
                    # statistical floor: burn over a handful of samples
                    # (one cold-compile miss out of five) must not page
                    continue
                if thr and burn >= thr and key not in self._burn_above:
                    self._burn_above.add(key)
                    try:
                        queue_depth = (len(self._sched)
                                       + self.batcher.queue_depth)
                    except Exception:  # noqa: BLE001
                        queue_depth = 0
                    self._incident_event(
                        "slo_burn", cls=cls, metric=metric,
                        burn=round(burn, 3), threshold=thr,
                        window=rec.get("burn_window"),
                        queue_depth=queue_depth,
                        # the Sarathi-Serve discriminator: slots
                        # mid-chunked-prefill while decode burns
                        prefill_active=len(self._prefilling),
                        # waterfall-backed attribution (ISSUE 18): the
                        # segment dominating the burning class's TTFT
                        # budget — quantitative backing for the
                        # prefill_interference classification
                        dominant_segment=self._dominant_segment(cls),
                        trace_ids=self._live_trace_ids())
        # a series whose samples aged out of EVERY window vanishes from
        # the snapshot entirely — the latch must re-arm then too, or the
        # first burn of an engine's lifetime would be the only one the
        # plane ever detects after a full-drain quiet gap
        self._burn_above &= seen

    # ------------------------------------------------ perf introspection API

    def _kv_fragmentation(self) -> tuple:
        """(owned_pages, committed_tokens, internal-fragmentation ratio)
        over live decode slots: 1 - tokens / (pages * page_size).  High
        fragmentation = many part-filled last pages — the page-geometry
        signal the fleet KV fabric's placement will weigh."""
        owned = int(np.count_nonzero(self._pt_host))
        toks = int(self._len_host.sum())
        if owned <= 0:
            return 0, toks, 0.0
        frag = 1.0 - toks / (owned * self.ec.page_size)
        return owned, toks, max(0.0, min(1.0, frag))

    def perf_snapshot(self) -> dict:
        """The performance-introspection snapshot (``GET /engine/perf``):
        the FLOPs/goodput ledger with exact waste attribution, windowed
        MFU/goodput ratios, cache analytics (hit/miss by reason, page
        occupancy + fragmentation, per-prefix reuse), the tick-phase
        timeline tail, and the profiler run registry."""
        snap = self.perf.snapshot()
        snap["enabled"] = self._perf_on
        owned, toks, frag = self._kv_fragmentation()
        try:
            cs = self.batcher.cache_stats()
            free = self.batcher.free_pages
        except RuntimeError:  # engine stopped
            cs, free = {}, 0
        total = max(1, self.ec.num_pages - 1)  # page 0 is the trash page
        snap["cache"] = {
            **self.cache_analytics.snapshot(),
            **cs,
            "free_pages": free,
            "occupancy": round((total - free) / total, 6),
            "owned_pages": owned,
            "committed_tokens": toks,
            "fragmentation": round(frag, 6),
            # fleet KV fabric (README "Fleet KV fabric"): the published-
            # prefix listing the router's cache-aware placement matches
            # request fingerprints against, via /fleet/cache
            "fabric": self.fabric_view(),
        }
        snap["timeline"] = self.timeline.snapshot()
        snap["profiler"] = {
            "active": self._profiler.active,
            "captures": self._profiler.captures,
            "last_error": self._profiler.last_error,
            "runs": self.profiles.snapshot(),
        }
        snap["spec"] = {"proposed": self._spec_proposed,
                        "accepted": self._spec_accepted}
        return snap

    def refresh_perf_metrics(self) -> None:
        """Scrape-time refresh of the derived perf gauges (MFU, goodput
        ratio, KV fragmentation) — same right-when-read discipline as the
        KV occupancy and SLO gauges (serve.metrics_text calls this)."""
        if not self._perf_on:
            return
        _, _, frag = self._kv_fragmentation()
        self.telemetry.set_perf(self.perf.mfu(), self.perf.goodput_ratio(),
                                frag, self.perf.platform)

    # ---------------------------------------------------------- tracing API

    def trace(self, rid: int) -> Optional[dict]:
        """Lifecycle trace for a request id: live requests come from their
        in-flight span, resolved ones from the bounded trace history.  None
        when telemetry is off or the rid fell out of the history ring."""
        with self._lock:
            pending = self._requests.get(rid)
            span = pending.span if pending is not None else self._trace_ring.get(rid)
        return span.to_dict() if span is not None else None

    # bound on the auxiliary trace-reference maps (flight-dump refs,
    # session last-span links): small, fixed, oldest-out — these are
    # debugging breadcrumbs, not the span history itself
    _TRACE_REF_CAP = 256

    def trace_by_id(self, trace_id: str) -> dict:
        """Every span this engine holds for one distributed trace id —
        live requests and the bounded history — plus the flight-recorder
        dump paths that reference it.  The service proxy's
        ``GET /debug/trace/<id>`` fans this out across replicas and
        assembles the hop tree; an O(history) scan is fine on a debug
        path."""
        with self._lock:
            spans = [p.span for p in self._requests.values()
                     if p.span is not None and p.span.trace_id == trace_id]
            seen = {id(s) for s in spans}
            spans += [s for s in self._trace_ring.values()
                      if s.trace_id == trace_id and id(s) not in seen]
            dumps = list(self._trace_dumps.get(trace_id, ()))
        return {"trace_id": trace_id,
                "spans": [s.to_dict() for s in spans],
                "flight_dumps": dumps}

    # ------------------------------------------- latency attribution plane

    def waterfall(self, rid: int) -> Optional[dict]:
        """Engine-local latency waterfall for one request id (README
        "Latency attribution", ``GET /engine/waterfall/<rid>``): the
        span's phase marks partitioned into attributed segments whose
        sum equals the span wall by construction, the spec-verify carve,
        and the critical path against the pipelined loop's overlapped
        host phases.  None when telemetry is off or the rid aged out —
        assembly runs on the caller's (handler) thread, never the loop."""
        with self._lock:
            pending = self._requests.get(rid)
            span = pending.span if pending is not None \
                else self._trace_ring.get(rid)
        if span is None:
            return None
        t0 = span.events[0][1]
        t_end = span.events[-1][1]
        overlays = waterfall_mod.overlays_from_timeline(
            self.timeline.snapshot(last=128), t0, t_end)
        return waterfall_mod.build_engine_waterfall(span.to_dict(),
                                                    overlays=overlays)

    # recent archived spans per latency_budget() read: enough for stable
    # per-class p95s, bounded so the under-lock ref copy stays cheap
    _BUDGET_SCAN_CAP = 512

    def latency_budget(self) -> dict:
        """Per-class latency-budget samples from the recent span history
        (``GET /engine/latency`` — the replica-local half; the service
        proxy merges samples fleet-wide and computes the quantiles).
        Returns ``{"classes": {...}, "samples": {cls: [...]}}``; empty
        when telemetry is off.  O(recent history), caller thread only."""
        if not self.ec.telemetry:
            return {"classes": {}, "samples": {}}
        with self._lock:
            spans = list(self._trace_ring.values())[-self._BUDGET_SCAN_CAP:]
        by_cls: dict = {}
        for span in spans:
            sample = waterfall_mod.span_budget_sample(span.to_dict())
            if sample is None:
                continue
            bucket = by_cls.setdefault(sample.pop("cls"), [])
            bucket.append(sample)
            if len(bucket) > waterfall_mod.BUDGET_SAMPLE_CAP:
                bucket.pop(0)
        return {"classes": waterfall_mod.class_budgets(by_cls),
                "samples": by_cls}

    def _dominant_segment(self, cls: str) -> Optional[dict]:
        """The segment dominating ``cls``'s recent TTFT budget — the
        waterfall-backed evidence an SLO-burn incident cites (manager
        thread, bounded scan, best-effort)."""
        try:
            samples = self.latency_budget()["samples"].get(cls)
            return waterfall_mod.dominant_segment(samples) \
                if samples else None
        except Exception:  # noqa: BLE001 — evidence is best-effort
            return None

    def _note_dump(self, path: Optional[str], trace_ids) -> None:
        """Remember which traces a flight dump concerns, so the assembled
        trace tree can point an incident responder at the postmortem file
        on the replica that produced it."""
        if path is None:
            return
        with self._lock:
            for tid in trace_ids:
                if tid is None:
                    continue
                paths = self._trace_dumps.setdefault(tid, [])
                if path not in paths:
                    paths.append(path)
            while len(self._trace_dumps) > self._TRACE_REF_CAP:
                self._trace_dumps.pop(next(iter(self._trace_dumps)))

    def _slot_trace_ids(self, slots: list) -> list:
        """Trace id per slot (None for unbound rows) — the flight-event /
        dump correlation key.  Loop-thread only."""
        out = []
        for s in slots:
            p = self._requests.get(self._slot_req.get(s))
            out.append(p.span.trace_id
                       if p is not None and p.span is not None else None)
        return out

    def trace_n_ticks(self, n: int, trace_dir: Optional[str] = None) -> str:
        """Capture a jax.profiler (XLA) trace of the next ``n`` live engine
        ticks into ``trace_dir``.  Start/stop run on the loop thread at tick
        boundaries; returns immediately — poll ``profiler_active`` (or just
        wait) for completion.  Raises if a capture is already in flight.

        ``trace_dir=None`` (the ``POST /engine/profile`` path) captures
        into a MANAGED dir from the ProfileStore: artifacts are byte+entry
        capped with oldest-first eviction and removed on ``stop()``.
        Explicit dirs stay caller-owned (recorded in the run history,
        never deleted)."""
        if self._stopped or not self._running:
            # a dead loop never reaches a tick boundary: arming would
            # wedge profiler_active True forever and leak the managed dir
            # past the stop()-time cleanup that already ran
            raise RuntimeError("engine is not running")
        managed = trace_dir is None
        if managed:
            trace_dir = self.profiles.new_dir()
        # register BEFORE arming and carry the record THROUGH the profiler
        # as its ctx: a capture can start/complete on the loop thread the
        # instant request() lands, and a side field would race it
        rec = self.profiles.begin(trace_dir, n, managed)
        try:
            self._profiler.request(n, trace_dir, ctx=rec)
        except BaseException:
            self.profiles.discard(rec)  # refused: no orphan run record
            raise
        self._wake.set()  # an idle loop still ticks; make sure it wakes now
        return trace_dir

    def _profile_complete(self, error: Optional[str], rec) -> None:
        """TickProfiler completion hook (loop thread): size the capture's
        artifacts and apply the store's count/byte caps."""
        if rec is not None:
            self.profiles.complete(rec, error=error)

    @property
    def profiler_active(self) -> bool:
        return self._profiler.active

    def _archive_span(self, pending: "_Pending", outcome: str) -> None:
        """Terminal-mark a request's span, count the outcome, and retire the
        span into the bounded trace history (oldest evicted first).

        Also the ONE session-busy release point: every terminal path —
        finish, fail, shed, cancel (both races), reap, drain — funnels
        through here exactly once per request, so a session can never be
        left permanently "in flight" by a missed edge case."""
        sid = pending.session_id
        if sid is not None:
            with self._lock:
                if self._session_active.get(sid) == pending.rid:
                    del self._session_active[sid]
        self.telemetry.count_outcome(outcome)
        span = pending.span
        if span is None:
            return
        if span.outcome is None:
            span.mark(outcome)
        evicted = 0
        with self._lock:
            if sid is not None:
                # the NEXT turn's span links this one (session_prev);
                # pop-then-insert keeps active sessions at the LRU tail —
                # plain reassignment would leave them at their original
                # position and evict the LONGEST-LIVED session first
                self._session_spans.pop(sid, None)
                self._session_spans[sid] = (span.trace_id, span.span_id)
                while len(self._session_spans) > self._TRACE_REF_CAP:
                    self._session_spans.pop(next(iter(self._session_spans)))
            nb = span.nbytes()
            self._trace_ring[span.rid] = span
            self._trace_sizes[span.rid] = nb
            self._trace_ring_bytes += nb
            # dual budget (ISSUE 8 satellite): entries AND bytes — a fleet
            # soak of span-heavy requests (long prefills, preemption
            # cycles) must not grow history past the byte cap even while
            # under the entry cap
            while (self._trace_ring
                   and (len(self._trace_ring) > self.ec.trace_history
                        or self._trace_ring_bytes
                        > self.ec.trace_history_bytes)):
                old_rid = next(iter(self._trace_ring))
                if old_rid == span.rid:
                    break  # never evict the span being archived
                self._trace_ring.pop(old_rid)
                self._trace_ring_bytes -= self._trace_sizes.pop(old_rid, 0)
                evicted += 1
        self.telemetry.count_trace_evictions(evicted)

    # ------------------------------------------------------------------ loop

    def _bucket(self, n: int) -> int:
        for b in PREFILL_BUCKETS:
            if n <= b:
                return b
        # past the largest static bucket (prefill_chunk may exceed it):
        # round up to the page grid so the single-shot path still covers the
        # whole prompt — silently reusing PREFILL_BUCKETS[-1] would truncate
        # a 1025-token prompt to 1024 (regression-tested at that boundary)
        ps = self.ec.page_size
        return -(-n // ps) * ps

    def _next_key(self):
        if self.ec.temperature <= 0.0:
            # greedy sampling never reads the key: don't pay a per-tick
            # fold_in dispatch (a real host-latency tax at chip decode
            # speeds) for a value argmax ignores
            return self._key
        self._sample_calls += 1
        return self._jax.random.fold_in(self._key, self._sample_calls)

    def _count_prefill(self, rows: int) -> None:
        """One fused prefill dispatch carrying ``rows`` prompt rows."""
        self._prefill_dispatches += 1
        self._prefill_rows_total += rows
        self._prefill_batch_hist[rows] = self._prefill_batch_hist.get(rows, 0) + 1
        self.telemetry.observe_prefill_batch(rows)

    # ------------------------------------------------ perf-ledger charging
    # (perf.py, README "Performance introspection"): analytical FLOPs are
    # charged where the dispatch OUTCOME is known, attributed goodput or
    # waste in the same call — goodput + waste == dispatched is the
    # ledger's construction, not a reconciliation.

    def _charge_prefill_rows(self, slots: list, lens, off: int,
                             ok, finishing=None) -> None:
        """One fused prefill dispatch: each row charged at its REAL
        position count this chunk (min(chunk, plen-off); padding lanes
        are machine work, not requested work).  A row whose request
        carries a waste_reason (preempt/handoff/failover recompute) lands
        under that reason; a NaN-guarded FINISHING row's work is
        discarded -> tick_retry."""
        C = self.ec.prefill_chunk
        for i, slot in enumerate(slots):
            pending = self._requests.get(self._slot_req.get(slot))
            if pending is None:
                continue
            toks = max(0, min(C, int(lens[i]) - off))
            if toks <= 0:
                continue
            bad = (ok is not None and not ok[i]
                   and (finishing is None or i in finishing))
            self.perf.charge(
                "prefill", self._fm.prefill_row(toks, off), toks,
                "tick_retry" if bad else pending.waste_reason)

    def _charge_dropped(self, rec: dict, reason: str) -> None:
        """A pipelined tick whose results are being discarded wholesale
        (watchdog restart / stop): its dispatched FLOPs were real device
        work that produced nothing — waste under ``reason``."""
        kind = "verify" if rec.get("kind") == "spec" else "decode"
        for slot, f in (rec.get("flops") or {}).items():
            if isinstance(f, tuple):
                f, k = f
            else:
                k = 1
            self.perf.charge(kind, f, k, reason)

    def _guard_logits(self, logits, row_rids, phase: str = "decode"):
        """Chaos NaN injection + the sample-path logit guard.

        ``row_rids``: request id per leading logits row (-1 = inactive).
        Returns (logits, ok) where ok is a device [B]-bool — True iff every
        logit in that row (all trailing axes) is finite — or None when the
        guard is disabled.  The caller fetches ok alongside the sampled
        tokens and fails non-finite rows instead of committing them."""
        jnp = self._jnp
        if self._chaos is not None:
            for row in self._chaos.nan_rows(row_rids, phase):
                logits = logits.at[row].set(jnp.nan)
        if not self.ec.logit_guard:
            return logits, None
        ok = jnp.isfinite(logits).all(
            axis=tuple(range(1, logits.ndim)))
        return logits, ok

    def _prefill_short_group(self, slots: list, bucket: int) -> None:
        """ONE fused dispatch for every same-bucket short prompt: a
        [B, bucket] prefill, one write_pages scatter of all rows' owned
        pages (unowned tails route to the trash page 0), and one batched
        first-token sample — a single blocking transfer instead of B
        round-trips, which also preserves the host-mirror aliasing fence
        (_activate_decode mutations happen only after it returns)."""
        jnp = self._jnp
        ps = self.ec.page_size
        B = len(slots)
        n_pages = bucket // ps
        toks = np.zeros((B, bucket), np.int32)
        lens = np.zeros((B,), np.int32)
        rows = np.zeros((B, n_pages), np.int32)
        aids = np.zeros((B,), np.int32)
        for i, slot in enumerate(slots):
            pending = self._requests[self._slot_req[slot]]
            if pending.span is not None:
                pending.span.mark("prefill")
            plen = len(pending.tokens)
            toks[i, :plen] = pending.tokens
            lens[i] = plen
            aids[i] = pending.adapter_id
            # prefill produces bucket/page_size pages per row; the slot owns
            # ceil(plen/page_size) — the tail stays 0 (trash page)
            owned = self._pages_for(plen)
            rows[i, :owned] = self._prefill_rows[slot][:owned]
        self._check_epoch()  # last fence before touching device pools
        logits, pk, pv = prefill(
            self.params, self.config, jnp.asarray(toks), jnp.asarray(lens), ps,
            lora_params=self._lora,
            adapter_ids=(jnp.asarray(aids) if self._lora is not None else None),
        )
        self._count_prefill(B)
        self.k_pool, self.v_pool = write_pages(
            self.k_pool, self.v_pool, pk, pv, jnp.asarray(rows))
        logits, ok_dev = self._guard_logits(
            logits, [self._slot_req[s] for s in slots], phase="prefill")
        cmask, cstall, cdone = self._prefill_masks(slots)
        if cmask is not None:
            # first-token grammar mask, AFTER the guard read raw logits
            logits = jnp.where(jnp.asarray(cmask), logits,
                               jnp.float32(-1e30))
        sampled = np.asarray(
            sample_tokens(logits, self._next_key(), self.ec.temperature))
        ok = np.asarray(ok_dev) if ok_dev is not None else None
        if self._perf_on:
            # charge per ROW at the real prompt length (padding lanes are
            # not work the request asked for); a recompute prefill
            # (preempt/handoff/failover) lands under its waste reason, a
            # NaN-tripped row's work is discarded -> tick_retry
            self._charge_prefill_rows(slots, lens, 0, ok)
        now = time.perf_counter()
        for i, slot in enumerate(slots):
            if ok is not None and not ok[i]:
                self._fail_nan(slot, "prefill sample row")
                continue
            if i in cstall:
                self._fail_constraint_stall(slot)
                continue
            pending = self._requests[self._slot_req[slot]]
            del self._prefilling[slot]
            plen = int(lens[i])
            if i in cdone:
                # a recompute-resumed automaton already at a closed
                # grammar: nothing may follow — finish with the kept
                # tokens instead of sampling (outcome "valid")
                self._activate_decode(slot, plen, self._pages_for(plen),
                                      self._prefill_rows[slot])
                self._finish(slot, self._slot_req[slot], truncated=False)
                continue
            self._mark_first_token(pending, now)
            self._activate_decode(slot, plen, self._pages_for(plen),
                                  self._prefill_rows[slot])
            self._commit(slot, int(sampled[i]))

    def _prefill_masks(self, slots: list, only=None) -> tuple:  # graftlint: hot-path
        """First-token grammar masks for one fused prefill sample, in ROW
        order (``mask[i]`` gates ``slots[i]`` — README "Structured
        output"); the prompt never advances the automaton, so a fresh
        request masks from the grammar's start state and a recompute
        resume from its restored snapshot.  ``only`` restricts the build
        to those row indices (the chunked group's finishing rows — mid-
        prompt rows don't sample, so their walks would be pure waste).
        Returns ``(mask_or_None, stalled_rows, closed_rows)``: stalled
        rows (non-accepting, zero legal tokens) keep an all-True mask and
        the caller fails them; closed rows (a restored automaton already
        at a complete utterance with nothing allowed to follow) finish
        gracefully with their kept tokens instead of committing."""
        mask = None
        stall = set()
        done = set()
        t0 = time.perf_counter()
        for i, slot in enumerate(slots):
            if only is not None and i not in only:
                continue
            pending = self._requests.get(self._slot_req.get(slot))
            if pending is None or pending.constrain is None:
                continue
            ts = time.perf_counter()
            row = self._grammar_row(pending.constrain)
            forced = (self._constrain_chaos is not None
                      and self._constrain_chaos.stall_mask())
            if forced:
                row = np.zeros_like(row)
            if pending.span is not None:
                pending.span.hint("grammar_advance",
                                  time.perf_counter() - ts)
            if not row.any():
                if not forced and pending.constrain.accepting():
                    done.add(i)
                else:
                    stall.add(i)
                continue
            if mask is None:
                mask = np.ones((len(slots), self.config.vocab_size),
                               np.bool_)
            mask[i, :] = row
        if mask is not None or stall or done:
            self.telemetry.observe_grammar_mask(time.perf_counter() - t0)
        return mask, stall, done

    def _mark_first_token(self, pending: "_Pending", now: float) -> None:
        if pending.first_token_at:
            # resume prefill after a drop-preempt: the first token left
            # long ago — TTFT and the span mark must not move
            return
        pending.first_token_at = now
        if pending.span is not None:
            pending.span.mark("first_token")
        self.telemetry.observe_ttft(now - pending.submitted_at,
                                    pending.priority)

    def _prefill_chunk_group(self, slots: list, off: int) -> None:
        """ONE fused chunked-prefill dispatch for every long/cache-resumed
        prompt at the same chunk offset (same static hist geometry): each
        row advances one page-aligned chunk; rows whose chunk completes the
        prompt sample their first token from the shared batched sample."""
        jnp = self._jnp
        ps = self.ec.page_size
        C = self.ec.prefill_chunk
        B = len(slots)
        first_page = off // ps
        n_chunk = C // ps
        n_hist = first_page + n_chunk
        toks = np.zeros((B, C), np.int32)
        lens = np.zeros((B,), np.int32)
        aids = np.zeros((B,), np.int32)
        chunk_ids = np.zeros((B, n_chunk), np.int32)
        hist_ids = np.zeros((B, n_hist), np.int32)
        table_rows = {}
        for i, slot in enumerate(slots):
            pending = self._requests[self._slot_req[slot]]
            if pending.span is not None:
                pending.span.mark("prefill")
            plen = len(pending.tokens)
            chunk = pending.tokens[off:off + C]
            toks[i, :len(chunk)] = chunk
            lens[i] = plen
            aids[i] = pending.adapter_id
            owned = self._pages_for(plen)
            table_rows[slot] = row = self._prefill_rows[slot]
            # pages past the owned range (final-chunk padding) scatter into
            # the reserved trash page 0; reads past `length` are masked
            real = max(0, min(owned - first_page, n_chunk))
            chunk_ids[i, :real] = row[first_page:first_page + real]
            hreal = min(owned, n_hist)
            hist_ids[i, :hreal] = row[:hreal]
        self._check_epoch()  # last fence before rebinding device pools
        logits, self.k_pool, self.v_pool = prefill_chunk(
            self.params, self.config, jnp.asarray(toks), jnp.int32(off),
            jnp.asarray(lens), jnp.asarray(chunk_ids), jnp.asarray(hist_ids),
            self.k_pool, self.v_pool, ps,
            lora_params=self._lora,
            adapter_ids=(jnp.asarray(aids) if self._lora is not None else None),
        )
        self._count_prefill(B)
        finishing = [i for i in range(B) if off + C >= int(lens[i])]
        ok = None
        cstall = set()
        cdone = set()
        if finishing:
            logits, ok_dev = self._guard_logits(
                logits, [self._slot_req[s] for s in slots], phase="prefill")
            cmask, cstall, cdone = self._prefill_masks(
                slots, only=set(finishing))
            if cmask is not None:
                logits = jnp.where(jnp.asarray(cmask), logits,
                                   jnp.float32(-1e30))
            # rows mid-prompt get sampled too (greedy ignores the key; their
            # values are simply unused) — still one blocking transfer total
            sampled = np.asarray(
                sample_tokens(logits, self._next_key(), self.ec.temperature))
            ok = np.asarray(ok_dev) if ok_dev is not None else None
            now = time.perf_counter()
        if self._perf_on:
            # each row advances min(C, plen-off) real positions attending
            # over `off` of history; the NaN guard only adjudicates
            # FINISHING rows here (mid-prompt rows fail at their final
            # chunk), so only those can charge tick_retry
            self._charge_prefill_rows(slots, lens, off, ok,
                                      finishing=set(finishing))
        for i, slot in enumerate(slots):
            if i not in finishing:
                self._prefilling[slot] = off + C
                # an advanced chunk IS progress: without this reset a long
                # prompt under intermittent faults would accumulate
                # non-consecutive failures across successful chunks
                self._reset_failures(self._requests[self._slot_req[slot]])
                continue
            if ok is not None and not ok[i]:
                self._fail_nan(slot, "chunked-prefill sample row")
                continue
            if i in cstall:
                self._fail_constraint_stall(slot)
                continue
            pending = self._requests[self._slot_req[slot]]
            del self._prefilling[slot]
            plen = int(lens[i])
            if i in cdone:
                # closed restored automaton — same graceful finish as the
                # short-prefill group
                self._activate_decode(slot, plen, self._pages_for(plen),
                                      table_rows[slot])
                self._finish(slot, self._slot_req[slot], truncated=False)
                continue
            self._mark_first_token(pending, now)
            self._activate_decode(slot, plen, self._pages_for(plen),
                                  table_rows[slot])
            self._commit(slot, int(sampled[i]))

    def _loop(self, epoch: int) -> None:
        # ENGINE_TICK_FLOOR_S: minimum wall time per engine tick that did
        # work.  A simulator knob for router/scheduler tests on CPU: on a
        # real TPU the host thread is idle while the chip runs the step, so
        # N replicas on N chips scale; on the 1-core test box the tick is
        # pure host compute and replicas only time-slice.  The floor
        # restores the device-bound regime (host sleeps the remainder of
        # the simulated step), letting multi-replica scheduling behavior be
        # asserted without chips.  Unset/0 (the default) is a no-op.
        #
        # ``epoch`` fences restarted loops: the watchdog bumps self._epoch
        # before reviving a hung/dead loop, so a stale thread that wakes up
        # mid-sleep exits here without touching engine state.
        tick_floor = float(os.environ.get("ENGINE_TICK_FLOOR_S", "0") or 0)
        self._tls.epoch = epoch
        while self._running and self._epoch == epoch:
            if self._chaos is not None:
                # may sleep (slow-tick) or raise ChaosThreadDeath — a
                # BaseException, so none of the isolation boundaries below
                # can swallow it; it terminates this thread for the
                # watchdog to find (caught here only to keep pytest's
                # unhandled-thread-exception hook quiet)
                try:
                    self._chaos.on_tick()
                except BaseException:
                    return  # thread dies; state stays as-is, like a crash
                if self._epoch != epoch:
                    return  # supervisor replaced us while we were stalled
            obs = self.ec.telemetry
            overrun_s = self.ec.incident_tick_overrun_s \
                if self.incidents is not None else 0.0
            tick_t0 = time.perf_counter() \
                if (tick_floor or obs or overrun_s > 0) else 0.0
            self._ticks += 1
            self._last_tick_ts = time.monotonic()
            self._profiler.on_tick_start(self._ticks)
            did_work = False
            try:
                did_work = self._tick()
            except _StaleThread:
                return  # superseded after a hang: exit without a trace
            except Exception as exc:  # noqa: BLE001 — loop must survive
                # backstop for host-side faults escaping the per-phase
                # isolation boundaries: charge every in-flight request
                # (K-cap rejects repeat offenders) and keep serving
                try:
                    self._note_group_failure(list(self._slot_req), "tick", exc)
                except _StaleThread:
                    return  # the "fault" was our own supersession
                if obs:
                    # failed ticks belong in the duration histogram too —
                    # the slowest, most diagnostic ticks are often exactly
                    # the ones that end in an escaped exception
                    self.telemetry.observe_tick(time.perf_counter() - tick_t0)
                time.sleep(0.005)
                continue
            finally:
                # work ticks only: the capture window must not be consumed
                # by idle 20ms waits (a failed tick counts — it dispatched)
                self._profiler.on_tick_end(self._ticks, did_work
                                           or bool(self._slot_req))
            if obs and did_work:
                # tick-duration histogram: work ticks only — idle 20ms waits
                # would swamp the distribution with scheduler noise
                self.telemetry.observe_tick(time.perf_counter() - tick_t0)
            if overrun_s > 0 and did_work:
                # tick-deadline overrun (README "Incident plane"): a WORK
                # tick past the configured budget is the chronic-slowness
                # signal below the watchdog's hang threshold
                dur = time.perf_counter() - tick_t0
                if dur > overrun_s:
                    self._incident_event(
                        "tick_overrun", duration_s=round(dur, 4),
                        threshold_s=overrun_s,
                        trace_ids=self._slot_trace_ids(
                            list(self._slot_req)))
            if did_work and tick_floor:
                pad = tick_floor - (time.perf_counter() - tick_t0)
                if pad > 0:
                    time.sleep(pad)
            if not did_work:
                self._wake.wait(timeout=0.02)
                self._wake.clear()

    def _tick(self) -> bool:
        """One engine tick: reap expired, admit (policy order, preempting
        when a higher class is blocked), prefill groups, decode.  Each
        compute phase runs inside its own isolation boundary (_isolated):
        an exception fails only the slots in the offending group — at worst
        after max_consecutive_failures retries — and the tick sequence
        continues."""
        self._check_epoch()
        now = time.perf_counter()
        did_work = False
        # per-tick phase timeline (perf.py): admit covers reap + leftover
        # drain + chaos/pool preemption + scheduler admission; the
        # dispatch segments cover the fused device calls (the pipelined
        # paths add readback/commit_behind/drain sub-segments from inside)
        tl = self.timeline if self._perf_on else None
        tp = now

        # --- eager queue reaping: deadline-expired queued requests shed
        # NOW, not when they reach the admission head — they were holding
        # queue-depth budget for work nobody is waiting for
        did_work |= self._reap_expired_queue(now)

        # --- drain C++-queued leftovers (an admit that failed after its
        # submit last tick — rare; the scheduler queue is the real queue)
        while True:
            admitted = self.batcher.admit()
            if admitted is None:
                break
            did_work = True
            self._install_admitted(admitted)

        # --- chaos: forced preemption storms (faults.py)
        if (self._chaos is not None and self._chaos.should_preempt()):
            victim = self._pick_victim(max_rank=-1)
            if victim is not None:
                did_work = True
                self._preempt_slot(victim, "chaos")

        # --- pool-pressure relief: below the free-page watermark, evict a
        # strictly lower-priority decode slot before growth OOM-truncates a
        # higher-priority one (off unless min_free_pages is set)
        if self._scfg.preemption and self._scfg.min_free_pages > 0:
            free = self.batcher.free_pages + self.batcher.reclaimable()
            if free < self._scfg.min_free_pages:
                ranks = [self._requests[r].rank
                         for s, r in self._slot_req.items()
                         if s not in self._prefilling and r in self._requests]
                if len(ranks) > 1:
                    victim = self._pick_victim(max_rank=min(ranks))
                    if victim is not None:
                        did_work = True
                        self._preempt_slot(victim, "pool")

        # --- scheduler admission: drain the host queue in policy order,
        # preempting a lower-priority decode slot when the head is blocked
        did_work |= self._admit_from_scheduler()
        if tl is not None and (did_work or self._prefilling):
            t = time.perf_counter()
            tl.note(self._ticks, "admit", t - tp)
            tp = t

        # --- fused prefill: group prefilling slots (short prompts by
        # bucket, long/cache-resumed ones by chunk offset) and issue ONE
        # dispatch per group instead of one per slot — an N-way burst of
        # same-bucket prompts is a single [N, bucket] prefill
        shorts: dict[int, list] = {}
        chunked: dict[int, list] = {}
        for slot in list(self._prefilling):
            did_work = True
            pending = self._requests.get(self._slot_req.get(slot))
            if pending is None:  # failed out from under us: reclaim
                self._fail_slot(slot, TickFailure("orphaned prefill slot"))
                continue
            if pending.cancelled:
                # mid-prefill cancel: pool pages are partially written —
                # free them WITHOUT caching
                del self._prefilling[slot]
                self._finish(slot, self._slot_req[slot], truncated=False,
                             cancelled=True, cache_ok=False)
                continue
            if (pending.deadline is not None and not pending.first_token_at
                    and time.perf_counter() > pending.deadline):
                # shed-before-prefill also covers a chunked prefill whose
                # deadline lapsed mid-prompt; once the first token is out
                # the request runs to completion (cancel covers the rest)
                self._fail_slot(slot, DeadlineExceeded(
                    "deadline expired before first token"), shed=True)
                continue
            off = self._prefilling[slot]
            plen = len(pending.tokens)
            if off == 0 and plen <= self.ec.prefill_chunk:
                shorts.setdefault(self._bucket(plen), []).append(slot)
            else:
                chunked.setdefault(off, []).append(slot)
        for bucket in sorted(shorts):
            self._isolated("prefill", shorts[bucket],
                           self._prefill_short_group, shorts[bucket], bucket,
                           shape={"rows": len(shorts[bucket]),
                                  "bucket": bucket})
        for off in sorted(chunked):
            self._isolated("prefill_chunk", chunked[off],
                           self._prefill_chunk_group, chunked[off], off,
                           shape={"rows": len(chunked[off]), "offset": off,
                                  "chunk": self.ec.prefill_chunk})
        if tl is not None and (shorts or chunked):
            t = time.perf_counter()
            tl.note(self._ticks, "prefill_dispatch", t - tp)
            tp = t

        # --- one decode step over slots whose prefill is complete
        # (_slot_req membership == slot active; no C snapshot needed)
        decode_ready = [
            s for s in self._slot_req
            if s not in self._prefilling
        ]
        for slot in list(decode_ready):
            pending = self._requests.get(self._slot_req.get(slot))
            if pending is None:
                did_work = True
                decode_ready.remove(slot)
                self._fail_slot(slot, TickFailure("orphaned decode slot"))
            elif pending.cancelled:
                did_work = True
                decode_ready.remove(slot)
                # prompt KV is complete: its pages are safe to cache
                self._finish(slot, self._slot_req[slot], truncated=False,
                             cancelled=True)
        if decode_ready:
            did_work = True
            # --- structured output (README "Structured output"): grammar
            # masks are only valid relative to the automaton state AFTER
            # the last committed token, so a constrained tick must never
            # dispatch over an uncommitted in-flight token.  Fence first
            # (the "constrain" fence — pipelining depth is the price of
            # validity; the mask itself still rides the FUSED dispatch),
            # then build this tick's mask.  The drain's commits may
            # finish/fail rows; a detected stall fails its slot here.
            gmask = None
            if any(self._constraint_for(s) is not None
                   for s in decode_ready):
                if self._inflight is not None:
                    self._drain_pipeline("constrain")
                    decode_ready = self._ready_now()
                if decode_ready:
                    decode_ready, gmask = self._build_grammar_masks(
                        decode_ready)
                if not decode_ready:
                    return did_work
            if self._pipe_depth > 0 and not (gmask is not None
                                             and self._spec is not None):
                if self._spec is not None:
                    # speculative ticks no longer force the sync loop: the
                    # fused verify dispatch (ISSUE 9) keeps drafts on the
                    # pipelined path, 1..K tokens committing behind it
                    self._isolated("verify", decode_ready,
                                   self._decode_tick_spec_pipelined,
                                   decode_ready,
                                   shape={"rows": len(decode_ready),
                                          "speculative": True,
                                          "pipelined": True,
                                          "k": 1 + self.ec.spec_max_draft})
                else:
                    self._isolated("decode", decode_ready,
                                   self._decode_tick_pipelined, decode_ready,
                                   gmask,
                                   shape={"rows": len(decode_ready),
                                          "pipelined": True,
                                          "constrained": gmask is not None})
                if tl is not None:
                    tl.note(self._ticks, "decode_dispatch",
                            time.perf_counter() - tp)
                return did_work
            # host mirrors ARE the decode view: mid-prefill slots hold
            # len 0 / trash rows by construction (_activate_decode).
            # Constrained speculative ticks land here at EVERY depth: the
            # verify mask composes with this tick's drafts (position j's
            # rows assume drafts 0..j-1 accepted), which only the sync
            # draft walk has in hand before dispatch.
            seq_lens = self._len_host
            page_table = self._pt_host
            drafts = {slot: self._draft_for(slot, seq_lens[slot])
                      for slot in decode_ready} if self._spec else {}
            if any(drafts.values()):
                self._isolated("decode", decode_ready,
                               self._decode_tick_speculative, decode_ready,
                               drafts, seq_lens, page_table, gmask,
                               shape={"rows": len(decode_ready),
                                      "speculative": True,
                                      "constrained": gmask is not None,
                                      "k": 1 + self.ec.spec_max_draft})
            else:
                self._isolated("decode", decode_ready,
                               self._decode_tick_single, decode_ready,
                               seq_lens, page_table, gmask,
                               shape={"rows": len(decode_ready),
                                      "constrained": gmask is not None})
            if tl is not None:
                tl.note(self._ticks, "decode_dispatch",
                        time.perf_counter() - tp)
        elif self._inflight is not None:
            # the roster drained to empty behind the last dispatch (every
            # row finished at commit-behind): retire the in-flight tick —
            # its tokens belong to already-resolved requests and discard
            did_work = True
            self._drain_pipeline("idle")
        return did_work

    # ------------------------------------------- QoS admission / preemption

    def _install_admitted(self, admitted) -> None:
        """Bookkeeping for one C++ admission: bind the slot, then route to
        swap-resume (restore KV, straight to decode) or prefill (fresh or
        prefix-cache-resumed — the recompute path after a drop-preempt
        lands here too, usually re-adopting its own cached pages)."""
        slot, rid, plen, _, cached = admitted
        # fetch + slot assignment are one atomic step vs cancel():
        # once _slot_req holds rid, cancel defers to this loop; a
        # queued cancel that popped the request first lands in the
        # pending-None branch
        with self._lock:
            pending = self._requests.get(rid)
            if pending is not None:
                self._slot_req[slot] = rid
                self._aid_host[slot] = pending.adapter_id
        if pending is None:
            self.batcher.release(slot)
            self._kv.discard_swap(rid)
            return
        if pending.span is not None:
            now = pending.span.mark(
                "admitted" if not pending.preemptions else "readmitted")
            if not pending.preemptions:
                self.telemetry.observe_queue_wait(
                    now - pending.submitted_at, pending.priority)
        if pending.cancelled:  # cancelled between submit and admit
            self._kv.discard_swap(rid)
            self._finish(slot, rid, truncated=False,
                         cancelled=True, cache_ok=False)
            return
        if pending.constrain_snap is not None:
            # preempt-resume (README "Structured output"): restore the
            # automaton byte-exact from its preemption snapshot BEFORE any
            # first-token mask is built — swap-resume and drop-recompute
            # both sample their next token from exactly this state
            pending.constrain.restore(pending.constrain_snap)
            pending.constrain_snap = None
        if (pending.deadline is not None and not pending.first_token_at
                and time.perf_counter() > pending.deadline):
            # deadline expired while queued: shed before spending any
            # prefill compute on a request nobody is waiting for (never
            # after the first token — a preempted request always resumes)
            self._fail_slot(slot, DeadlineExceeded(
                "deadline expired after "
                f"{time.perf_counter() - pending.submitted_at:.3f}s "
                "in queue"), shed=True)
            return
        if self._perf_on and not pending.swapped:
            # cache analytics (perf.py): admission is the one point where
            # requested vs granted prefix-cache pages are both known.
            # Reuse keys on the deepest matched chain hash — a unique
            # identity for the whole reused prefix.
            n_lookup = min(max(0, (plen - 1) // self.ec.page_size),
                           len(pending.page_hashes))
            if n_lookup > 0:
                key = (int(pending.page_hashes[cached - 1])
                       if cached > 0 else None)
                self.cache_analytics.note_lookup(n_lookup, cached, key)
                self.telemetry.count_cache_pages(n_lookup,
                                                 min(cached, n_lookup))
        if pending.swapped:
            item = self._kv.pop_swap(rid, count=not pending.handoff_import)
            if item is not None:
                try:
                    self._resume_swapped(slot, pending, item)
                    return
                except _StaleThread:
                    raise
                except Exception as exc:  # noqa: BLE001
                    if pending.handoff_import:
                        # a handoff blob that survived CRC verification
                        # but failed the scatter (shape skew the serve
                        # layer's check missed): degrade to a plain
                        # re-prefill below — the slot's pages are owned
                        # and prefill overwrites whatever the partial
                        # scatter touched.  "Never a failed request."
                        pending.swapped = False
                        pending.waste_reason = "handoff_degraded"
                        self.telemetry.count_handoff("degraded")
                        self._note_degradation("handoff", "scatter_failed",
                                               pending)
                        if self.ec.telemetry:
                            self._flight_event(
                                "handoff_import", [slot], None,
                                time.perf_counter(), "error",
                                error=f"{type(exc).__name__}: {exc}")
                    else:
                        # never leave the slot half-installed (len 0, no
                        # prefill) for the decode step to feed garbage
                        err = TickFailure(
                            f"swap-in failed: {type(exc).__name__}: {exc}")
                        err.__cause__ = exc
                        self._fail_slot(slot, err)
                        return
            else:
                # blob lost (store cleared under us): degrade to recompute
                # — tokens already hold the full context, pages were
                # released uncached so this is a cold re-prefill, but
                # still correct
                pending.swapped = False
                if pending.handoff_import:
                    pending.waste_reason = "handoff_degraded"
                    self.telemetry.count_handoff("degraded")
                    self._note_degradation("handoff", "blob_lost", pending)
                else:
                    # the cold re-prefill below recomputes positions this
                    # engine already computed once — same attribution as
                    # the drop-preempt path, and it matters most exactly
                    # when swap pressure is evicting blobs
                    pending.waste_reason = "preempt_recompute"
        # cache-hit pages already hold the prefix KV: prefill resumes
        # at the first uncovered position.  A session's FIRST admission
        # additionally restores pinned prefix pages from the tiered store
        # (host/disk) past whatever the device cache covered; any store
        # failure degrades to exactly this cache offset
        off = cached * self.ec.page_size
        if pending.session_id is not None and pending.session_restore is None:
            off = self._restore_session(slot, pending, cached)
        if pending.fabric_import is not None:
            # fleet KV fabric fault-in (README "Fleet KV fabric"): scatter
            # whatever verified remote prefix pages the device cache and
            # session restore did NOT already cover; prefill starts at the
            # deepest covered position either way
            off = max(off, self._restore_fabric(
                slot, pending, off // self.ec.page_size))
        self._prefilling[slot] = off
        self._prefill_rows[slot] = self.batcher.slot_pages(slot)

    def _snapshot_pages(self, pages: np.ndarray) -> tuple:
        """Host snapshot of the pools' ``pages`` -> ``(blob, nbytes)`` —
        the ONE device->host primitive behind swap park, session pin,
        handoff export and fabric publish.  TP=1 returns the legacy
        unified ``(k, v)`` tuple.  TP>1 returns a per-shard LIST of
        ``(k, v)`` pytrees in kv-head order: each shard's pages snapshot
        from that shard's OWN addressable data, so the device->host copy
        moves one shard's bytes per chip and no pool-sized gathered
        buffer (and no cross-chip collective) ever materializes."""
        tree = self._jax.tree_util
        if self._mesh is None:
            fetch = lambda leaf: np.asarray(leaf[:, pages])  # noqa: E731
            blob = (tree.tree_map(fetch, self.k_pool),
                    tree.tree_map(fetch, self.v_pool))
            return blob, sum(leaf.nbytes for leaf in tree.tree_leaves(blob))
        from .sharding import snapshot_shards

        tp = self.ec.tensor_parallel
        k_leaves, k_def = tree.tree_flatten(self.k_pool)
        v_leaves, v_def = tree.tree_flatten(self.v_pool)
        k_blocks = [snapshot_shards(leaf, pages) for leaf in k_leaves]
        v_blocks = [snapshot_shards(leaf, pages) for leaf in v_leaves]
        blob = [(k_def.unflatten([b[i] for b in k_blocks]),
                 v_def.unflatten([b[i] for b in v_blocks]))
                for i in range(tp)]
        nbytes = sum(leaf.nbytes for leaf in tree.tree_leaves(blob))
        self.telemetry.count_kv_shard_bytes("export", nbytes)
        return blob, nbytes

    def _scatter_pages(self, pages: np.ndarray, blob, lo: int,
                       hi: int) -> None:
        """Write a host KV blob's page range ``[lo, hi)`` into the pools
        at device ``pages`` — the ONE host->device primitive behind every
        restore.  Layout contract: a blob whose mesh degree matches this
        engine scatters shard-to-shard (each block device_puts straight
        to its shard); a mismatched degree is resharded host-side first —
        the EXPLICIT slow path, counted under engine_kv_reshard_total,
        never silent garbage."""
        tree = self._jax.tree_util
        tp = 1 if self._mesh is None else self.ec.tensor_parallel
        if blob_degree(blob) != tp:
            blob = reshard_blob(blob, tp)
            self.telemetry.count_reshard("reshard")
        elif tp > 1:
            self.telemetry.count_reshard("match")
        if tp == 1:
            if isinstance(blob, list):  # degree-1 shard list: unwrap
                blob = blob[0]
            jnp = self._jnp
            put = lambda pool, host: pool.at[:, pages].set(  # noqa: E731
                jnp.asarray(np.ascontiguousarray(host[:, lo:hi])))
            blob_k, blob_v = blob
            self.k_pool = tree.tree_map(put, self.k_pool, blob_k)
            self.v_pool = tree.tree_map(put, self.v_pool, blob_v)
            return
        from .sharding import scatter_shards

        k_host = [tree.tree_flatten(shard[0])[0] for shard in blob]
        v_host = [tree.tree_flatten(shard[1])[0] for shard in blob]
        nbytes = 0
        for pool_attr, host in (("k_pool", k_host), ("v_pool", v_host)):
            leaves, treedef = tree.tree_flatten(getattr(self, pool_attr))
            out = []
            for li, leaf in enumerate(leaves):
                blocks = [host[s][li][:, lo:hi] for s in range(tp)]
                nbytes += sum(b.nbytes for b in blocks)
                out.append(scatter_shards(leaf, pages, blocks, self._mesh))
            setattr(self, pool_attr, treedef.unflatten(out))
        self.telemetry.count_kv_shard_bytes("restore", nbytes)

    def _scatter_prefix(self, slot: int, blob, covered: int,
                        usable: int) -> None:
        """Scatter a verified host KV blob's pages ``[covered, usable)``
        into the slot's freshly-allocated page row — the device-side
        restore primitive behind session restore and fabric fault-in
        (both verify hashes first; this is the part that rebinds pools).
        The slot owns every page in the row, so the ``.set`` can never
        write a shared prefix-cache page."""
        row = self.batcher.slot_pages(slot)
        pages = np.ascontiguousarray(row[covered:usable])
        self._check_epoch()  # last fence before rebinding device pools
        self._scatter_pages(pages, blob, covered, usable)

    def _restore_session(self, slot: int, pending: _Pending,
                         cached: int) -> int:
        """Session-turn prefix restore (README "Sessions & tiered KV"):
        fetch the session's pinned KV pages from the tiered store, verify
        (the store checksums every restore), match the stored chain hashes
        against this prompt's, and scatter the pages the device prefix
        cache did NOT already cover into the slot's freshly-allocated
        row.  Returns the prefill offset (tokens already covered).

        Degrades, never fails: a miss, a checksum/torn-write/missing-file
        verification failure, a prompt that does not extend the pinned
        context, or any unexpected error here falls back to the plain
        prefix-cache offset — the turn re-prefills and still completes.
        ``pending.session_restore`` records the outcome for the result
        dict and the engine_session_restores_total metric."""
        ps = self.ec.page_size
        t0 = time.perf_counter()
        try:
            outcome, payload = self._kv.restore_session(pending.session_id)
            if payload is None:
                pending.session_restore = ("degraded" if outcome == "corrupt"
                                           else "cold")
                self.telemetry.count_session_restore(pending.session_restore)
                if pending.session_restore == "degraded":
                    # the store HAD the session but verification failed
                    # (torn write / bit flip / missing file): the
                    # storage-fault signal; a plain miss is not one
                    self._note_degradation("storage", outcome, pending)
                return cached * ps
            blob, nbytes, meta = payload
            stored = np.asarray(meta.get("hashes", ()), np.uint64)
            own = pending.page_hashes
            plen = len(pending.tokens)
            # the restore ceiling: full pages only, and at least ONE prompt
            # position must remain uncovered so prefill computes the final
            # logits the first sampled token comes from
            limit = min(len(stored), len(own), max(0, (plen - 1) // ps))
            usable = 0
            while usable < limit and own[usable] == stored[usable]:
                usable += 1
            if usable <= cached:
                # device prefix cache already covers everything the store
                # could offer (or the prompt diverged from the pinned
                # context before the cache frontier)
                pending.session_restore = "cache" if cached > 0 else "cold"
                self.telemetry.count_session_restore(pending.session_restore)
                return cached * ps
            self._scatter_prefix(slot, blob, cached, usable)
            pending.session_restore = outcome  # "host" | "disk"
            self.telemetry.count_session_restore(outcome)
            if pending.span is not None:
                pending.span.mark("session_restore")
            if self.ec.telemetry:
                self._flight_event(
                    "session_restore", [slot],
                    {"tier": outcome, "pages": int(usable - cached),
                     "cached": cached, "bytes": nbytes}, t0, "ok")
            return usable * ps
        except Exception as exc:  # noqa: BLE001 — restore must degrade
            pending.session_restore = "degraded"
            self.telemetry.count_session_restore("degraded")
            self._note_degradation("storage", "restore_error", pending)
            if self.ec.telemetry:
                self._flight_event("session_restore", [slot], None, t0,
                                   "error",
                                   error=f"{type(exc).__name__}: {exc}")
            return cached * ps

    def _restore_fabric(self, slot: int, pending: _Pending,
                        covered: int) -> int:
        """Fleet-fabric prefix fault-in (README "Fleet KV fabric"):
        match the pulled frame's chain hashes against this prompt's,
        scatter the verified pages past what the device cache (and any
        session restore) already ``covered``, and return the prefill
        offset in tokens.  The scatter is the session-restore pattern
        verbatim — freshly-owned slot pages, never shared cache pages.

        Degrades, never fails: a hash mismatch from page 0 (stale or
        wrong frame — the router's text fingerprints are a heuristic,
        THIS check is the correctness gate), a frame the local state
        already covers, or any scatter error falls back to the plain
        prefill offset; the recomputed prefix is fleet-level waste,
        attributed ``fabric_degraded``.  ``pending.fabric_restore``
        records the outcome for the result dict and
        engine_kv_fabric_total."""
        ps = self.ec.page_size
        blob, fhashes, nbytes = pending.fabric_import
        pending.fabric_import = None  # freed either way — blobs are MBs
        t0 = time.perf_counter()
        try:
            own = pending.page_hashes
            plen = len(pending.tokens)
            limit = min(len(fhashes), len(own), max(0, (plen - 1) // ps))
            usable = 0
            while usable < limit and own[usable] == fhashes[usable]:
                usable += 1
            if usable == 0:
                # the frame shares nothing with this prompt: the pull was
                # wasted and the whole prefix recomputes locally
                pending.fabric_restore = "degraded"
                pending.waste_reason = (pending.waste_reason
                                        or "fabric_degraded")
                self.telemetry.count_fabric("degraded")
                self._note_degradation("fabric", "hash_mismatch", pending)
                return covered * ps
            if usable <= covered:
                # local state (device cache / session restore) already
                # reaches at least as deep — nothing to scatter, nothing
                # recomputed: not a degrade, just a no-op import
                pending.fabric_restore = "local"
                self.telemetry.count_fabric("local")
                return covered * ps
            self._scatter_prefix(slot, blob, covered, usable)
            pending.fabric_restore = "hit"
            self.telemetry.count_fabric("hit")
            if pending.span is not None:
                pending.span.mark("fabric_restore")
            if self.ec.telemetry:
                self._flight_event(
                    "fabric_restore", [slot],
                    {"pages": int(usable - covered), "covered": covered,
                     "bytes": nbytes}, t0, "ok")
            return usable * ps
        except _StaleThread:
            raise
        except Exception as exc:  # noqa: BLE001 — restore must degrade
            pending.fabric_restore = "degraded"
            pending.waste_reason = pending.waste_reason or "fabric_degraded"
            self.telemetry.count_fabric("degraded")
            self._note_degradation("fabric", "restore_error", pending)
            if self.ec.telemetry:
                self._flight_event("fabric_restore", [slot], None, t0,
                                   "error",
                                   error=f"{type(exc).__name__}: {exc}")
            return covered * ps

    def _resume_swapped(self, slot: int, pending: _Pending, item) -> None:
        """Swap-in: scatter the evicted KV pages from the host store into
        the slot's freshly allocated pages and rebind the host mirrors —
        the slot rejoins decode exactly where it left off (seq_len, page
        row, last committed token), byte-identical under greedy."""
        blob, nbytes = item
        L = pending.resume_len
        owned = self._pages_for(L)
        # the blob's own page count may run ONE page short of owned for a
        # disaggregation import whose prompt ended exactly on a page
        # boundary (the finishing commit grants no next page, so the
        # export couldn't include it) — scatter what the blob covers; the
        # submit allocated the full row, and position L-1's KV is written
        # by the first decode step before anything reads it
        first_k = blob[0][0] if isinstance(blob, list) else blob[0]
        nblob = int(next(iter(self._jax.tree_util.tree_leaves(first_k)))
                    .shape[1])
        cov = min(owned, nblob)
        # swap submits carry no prefix hashes, so every page here is
        # freshly owned by this slot — the scatter can never write a
        # shared prefix-cache page
        row = self.batcher.slot_pages(slot)
        pages = np.ascontiguousarray(row[:cov])
        self._check_epoch()  # last fence before rebinding device pools
        self._scatter_pages(pages, blob, 0, cov)
        pending.swapped = False
        if pending.handoff_import:
            if pending.span is not None:
                pending.span.mark("handoff_import")
            if self.ec.telemetry:
                self._flight_event("handoff_import", [slot],
                                   {"pages": cov, "bytes": nbytes},
                                   time.perf_counter(), "ok")
        else:
            self.telemetry.count_swap("in", nbytes)
            if pending.span is not None:
                pending.span.mark("resumed")
            if self.ec.telemetry:
                self._flight_event("swap_in", [slot],
                                   {"pages": cov, "bytes": nbytes},
                                   time.perf_counter(), "ok")
        self._activate_decode(slot, L, owned, row)

    def _reap_expired_queue(self, now: float) -> bool:
        """Shed every queued request whose deadline lapsed — every tick,
        not at the admission head, so an expired entry stops holding its
        queue-depth budget the moment it is dead.  Preempted requests
        (first token already out) are never shed; they resume."""
        did = False
        for entry in self._sched.expired(now):
            with self._lock:
                pending = self._requests.get(entry.rid)
                if pending is None:
                    self._sched.remove(entry.rid)
                    continue
                if pending.first_token_at or pending.cancelled:
                    continue  # resumes / resolves via its own path
                self._requests.pop(entry.rid)
                self._future_rid.pop(pending.future, None)
            self._sched.remove(entry.rid)
            # a handoff-imported request parks its pulled blob in the
            # tiered store at SUBMIT; reaping it before admission must
            # release that budget (pre-disagg, swapped implied preempted
            # implied first_token_at — unreachable from here)
            self._kv.discard_swap(entry.rid)
            self._sched.reaped += 1
            self._requests_shed += 1
            did = True
            self._archive_span(pending, "shed")
            self._resolve_exception(pending, DeadlineExceeded(
                "deadline expired after "
                f"{now - pending.submitted_at:.3f}s in queue (reaped)"))
        return did

    def _admit_from_scheduler(self) -> bool:
        """Drain the host scheduler queue in policy order: submit-then-admit
        one request at a time while slots and pages allow; when the head is
        blocked, preempt a strictly lower-priority decode slot (at most
        max_preemptions_per_tick) and retry — otherwise stop (strict
        priority: never bypass a blocked head for a lower class)."""
        did = False
        budget = (self._scfg.max_preemptions_per_tick
                  if self._scfg.preemption else 0)
        while True:
            entry = self._sched.peek()
            if entry is None:
                break
            rid = entry.rid
            pending = self._requests.get(rid)
            if pending is None:
                self._sched.remove(rid)  # resolved out from under the queue
                continue
            if pending.cancelled:
                # queued cancel that landed after the preempt re-queue:
                # resolve here, don't burn an admission on it
                if self._resolve_queued_cancel(rid, pending):
                    did = True
                else:
                    self._sched.remove(rid)  # cancel() already resolved it
                continue
            plen = len(pending.tokens)
            need = self._pages_for(plen)
            have_slot = self.batcher.free_slots > 0
            # min_free_pages doubles as an ADMISSION reserve: a slot
            # evicted for pool pressure must not be readmitted while the
            # pool is still below the watermark (same-tick readmission
            # would otherwise thrash a full swap-out/in every tick)
            have_pages = (self.batcher.free_pages + self.batcher.reclaimable()
                          >= need + self._scfg.min_free_pages)
            if not (have_slot and have_pages):
                if budget > 0:
                    victim = self._pick_victim(max_rank=entry.rank)
                    if victim is not None:
                        budget -= 1
                        did = True
                        self._preempt_slot(
                            victim, "pages" if have_slot else "priority")
                        continue  # re-evaluate the head with freed capacity
                break
            self._sched.pop(entry)
            did = True
            mnew = max(1, pending.max_new_tokens - len(pending.generated))
            lookup = None
            if not pending.swapped:
                # lookup eligibility stops one page short of the prompt
                # end: prefill must compute at least the final token to
                # produce the logits the first sampled token comes from
                n_lookup = (plen - 1) // self.ec.page_size
                lookup = pending.page_hashes[:n_lookup]
            if not self.batcher.submit(rid, plen, mnew, lookup):
                # defensive: capacity was validated at generate_async
                with self._lock:
                    self._requests.pop(rid, None)
                    self._future_rid.pop(pending.future, None)
                self._kv.discard_swap(rid)
                self._requests_failed += 1
                self._archive_span(pending, "failed")
                self._resolve_exception(pending, RequestError(
                    f"prompt+generation ({plen}+{mnew}) exceeds engine "
                    "capacity"))
                continue
            admitted = self.batcher.admit()
            if admitted is None:
                break  # stays at the C++ queue head; drained next tick
            self._install_admitted(admitted)
        return did

    def _pick_victim(self, max_rank: int) -> Optional[int]:
        """The decode-ready slot to preempt: rank strictly greater than
        ``max_rank`` (pass -1 for "any"), preferring the lowest class,
        then the latest deadline (no deadline = latest), then the most
        recent submission (least queue investment lost).  None when no
        eligible victim exists — equals never preempt equals."""
        best, best_key = None, None
        for slot, rid in self._slot_req.items():
            if slot in self._prefilling:
                continue  # mid-prefill KV is incomplete; not preemptible
            p = self._requests.get(rid)
            if p is None or p.cancelled or p.rank <= max_rank:
                continue
            key = (p.rank,
                   p.deadline if p.deadline is not None else float("inf"),
                   p.submitted_at)
            if best_key is None or key > best_key:
                best, best_key = slot, key
        return best

    def _preempt_slot(self, slot: int, reason: str) -> None:
        """Evict one decoding slot: its KV pages are swapped to the host
        store (restored byte-identically on resume) or dropped into the
        prefix cache (re-prefill recovers them — usually as cache hits on
        the very same pages), the slot/pages free, and the request
        re-queues with its class, deadline and original submit time.  Under
        greedy sampling resume is byte-identical either way: swap restores
        the exact KV state; recompute re-derives it from the full committed
        context (prompt + generated so far)."""
        self._check_epoch()
        # pipeline fence BEFORE reading the victim's mirrors: the in-flight
        # tick's commit lands first, so the swap/recompute snapshot captures
        # every committed token (the not-yet-dispatched one is re-derived on
        # resume, byte-identical under greedy).  The drain may finish or
        # fail the victim — re-validate below.
        self._drain_pipeline("preempt")
        rid = self._slot_req.get(slot)
        pending = self._requests.get(rid) if rid is not None else None
        if pending is None:
            return
        if pending.cancelled:  # cancel raced the eviction: just finish it
            self._finish(slot, rid, truncated=False, cancelled=True)
            return
        ps = self.ec.page_size
        L = int(self._len_host[slot])
        # committed KV covers positions [0, L-2] (the last token's KV is
        # written by its NEXT decode step); pages_for(L) bounds the pages
        # that hold it — a speculative reserve page past that is garbage
        # and simply frees with the slot
        owned = self._pages_for(L)
        row = self._pt_host[slot, :owned].copy()
        mode = self._scfg.swap_policy
        if mode == "auto":
            mode = "swap" if L >= self._scfg.swap_min_tokens else "recompute"
        t0 = time.perf_counter()
        nbytes = 0
        if mode == "swap" and owned > 0:
            pages = np.ascontiguousarray(row)
            blob, nbytes = self._snapshot_pages(pages)
            if self._kv.put_swap(rid, blob, nbytes):
                self.telemetry.count_swap("out", nbytes)
            else:
                mode, nbytes = "recompute", 0  # over budget: drop instead
        release_hashes = None
        if mode == "swap":
            pending.swapped = True
            pending.resume_len = L
            pending.tokens = list(pending.context)
        else:
            # drop-and-recompute: the resume prompt is the full committed
            # context; its completed full pages go to the prefix cache so
            # the re-prefill usually re-adopts them instead of recomputing
            pending.swapped = False
            pending.tokens = list(pending.context)
            pending.page_hashes = self._page_hashes(
                pending.context, pending.adapter_id)
            release_hashes = pending.page_hashes[:max(0, (L - 1) // ps)]
            # the resume re-prefill recomputes positions this engine
            # already computed once — waste, attributed (the cache-hit
            # share of the re-prefill is never dispatched, so only the
            # genuinely recomputed positions get charged)
            pending.waste_reason = "preempt_recompute"
        if pending.constrain is not None:
            # the automaton state rides the slot like KV (README
            # "Structured output"): the "preempt" drain above landed every
            # committed token's advance, so this snapshot covers the full
            # committed generation; re-admission restores it byte-exact
            pending.constrain_snap = pending.constrain.snapshot()
        pending.preemptions += 1
        self._preemptions += 1
        self._reset_failures(pending)
        # the requeue gap is queue wait, not decode speed: without this
        # reset the first post-resume commit would record the whole
        # preemption pause as one TPOT observation
        pending.last_token_at = 0.0
        if pending.span is not None:
            pending.span.mark("preempted")
        self.telemetry.count_preemption(reason, mode)
        if self.ec.telemetry:
            self._flight_event(
                "preempt", [slot],
                {"reason": reason, "mode": mode, "pages": owned,
                 "bytes": nbytes, "seq_len": L},
                t0, "ok")
        with self._lock:
            self._slot_req.pop(slot, None)
        self._release_slot_state(slot)
        self.batcher.release(slot, release_hashes)
        self._sched.push(self._entry_for(rid, pending))
        if pending.cancelled:
            # cancel() landed during the swap-out window (it saw the slot
            # still bound and deferred to us): resolve NOW — a cancelled
            # entry must not sit in the queue until it reaches the policy
            # head, holding queue-depth budget with a waiting caller
            self._resolve_queued_cancel(rid, pending)

    # ------------------------------------------------------ fault handling

    def _isolated(self, phase: str, slots: list, fn, *args,  # graftlint: hot-path
                  shape: Optional[dict] = None) -> bool:
        """Isolation boundary around one tick phase: an exception fails only
        ``slots`` (the offending group), and only after the per-request
        consecutive-failure cap — a transient fault retries in place next
        tick.  Retry is sound because a failed dispatch committed nothing:
        prefill offsets/host mirrors only advance on success, and greedy
        decode re-produces byte-identical tokens from unchanged state.
        ChaosThreadDeath (BaseException) deliberately passes through.

        Every guarded dispatch also leaves a flight-recorder event (tick,
        phase, slot set, dispatch shape, duration, outcome) — the raw
        material of the postmortem dumps."""
        obs = self.ec.telemetry
        t0 = time.perf_counter() if obs else 0.0
        try:
            if self._chaos is not None:
                self._chaos.maybe_dispatch_error(phase)
            fn(*args)
            if obs:
                self._flight_event(phase, slots, shape, t0, "ok")
                # latency attribution (ISSUE 18): accumulate this
                # dispatch's wall onto each participant's span — the
                # waterfall's spec_verify carve and the pipelined-decode
                # host/device split read these totals off the hot path.
                # Loop-thread only, O(1) per slot, same cost class as the
                # flight event above.
                dur = time.perf_counter() - t0
                for s in slots:
                    p = self._requests.get(self._slot_req.get(s))
                    if p is not None and p.span is not None:
                        p.span.hint(phase, dur)
            return True
        except Exception as exc:  # noqa: BLE001 — the boundary's whole job
            if obs:
                self._flight_event(phase, slots, shape, t0, "error",
                                   error=f"{type(exc).__name__}: {exc}")
            self._note_group_failure(slots, phase, exc)
            return False

    def _flight_event(self, phase: str, slots: list, shape: Optional[dict],
                      t0: float, outcome: str, **extra) -> None:
        # tick events carry BOTH correlation keys (ISSUE 8 satellite):
        # request ids for engine-local digging, trace ids so a fleet-wide
        # trace assembly can cite the exact tick events of any hop
        self.flight.record(
            tick=self._ticks, phase=phase, slots=list(slots),
            rids=[self._slot_req.get(s) for s in slots],
            trace_ids=self._slot_trace_ids(slots),
            shape=shape, duration_s=round(time.perf_counter() - t0, 6),
            outcome=outcome, **extra)

    def _note_group_failure(self, slots: list, phase: str, exc: Exception) -> None:
        self._ticks_failed += 1
        cap = self.ec.max_consecutive_failures
        escalated = []
        escalated_tids = []
        for slot in list(slots):
            rid = self._slot_req.get(slot)
            pending = self._requests.get(rid) if rid is not None else None
            if pending is None:
                continue
            pending.failures += 1
            if pending.failures == 1:
                self._retrying += 1
            if pending.failures >= cap:
                err = TickFailure(
                    f"rejected after {pending.failures} consecutive "
                    f"{phase} failures (last: {type(exc).__name__}: {exc})")
                err.__cause__ = exc
                escalated.append(rid)
                if pending.span is not None:
                    escalated_tids.append(pending.span.trace_id)
                self._fail_slot(slot, err)
        if escalated and self.ec.telemetry:
            # a request crossed the consecutive-failure cap: that is a
            # postmortem-worthy event — persist the tick-event ring now,
            # while the failing tick's records are still in it
            path = self.flight.dump(
                "tick_failure_escalation",
                extra={"phase": phase, "rids": escalated, "tick": self._ticks,
                       "trace_ids": escalated_tids,
                       "error": f"{type(exc).__name__}: {exc}"})
            self._note_dump(path, escalated_tids)

    def _fail_nan(self, slot: int, where: str) -> None:
        """NaN-guard trip: fail the poisoned slot with NonFiniteLogits and
        dump the flight recorder — numerically diverged model state is the
        canonical "what was the engine doing?" postmortem case.  One dump
        per TICK, not per row: a whole poisoned batch is one incident, and
        per-row dumps would burn the recorder's lifetime dump cap on
        near-identical postmortems."""
        self._nan_rows += 1
        self._mark_roster_change("nan")  # before the release's "finish"
        tids: list = []
        path = None
        if self.ec.telemetry:
            tids = self._slot_trace_ids([slot])
            self._flight_event("nan_guard", [slot], None,
                               time.perf_counter(), "nan",
                               error=f"non-finite logits in {where}")
            if self._nan_dump_tick != self._ticks:
                self._nan_dump_tick = self._ticks
                path = self.flight.dump(
                    "nan_guard_trip",
                    extra={"slot": slot, "rid": self._slot_req.get(slot),
                           "trace_ids": tids,
                           "where": where, "tick": self._ticks})
                self._note_dump(path, tids)
        # incident signal: classifies "unknown" on its own (a lone NaN is
        # numeric divergence, not a taxonomy shape) but joins the causal
        # chain when a bigger incident is open; carries the dump so an
        # incident it OPENS cites this postmortem instead of forcing a
        # second one
        self._incident_event("nan_guard", where=where,
                             rid=self._slot_req.get(slot),
                             trace_ids=tids, dump=path)
        self._fail_slot(slot, NonFiniteLogits(
            f"non-finite logits in {where}"))

    # ------------------------------------- structured output (constrain.py)

    def _constraint_for(self, slot: int) -> "Optional[object]":
        p = self._requests.get(self._slot_req.get(slot))
        return p.constrain if p is not None else None

    def _grammar_row(self, c) -> "np.ndarray":  # graftlint: hot-path
        """One automaton state's legal-token mask for the NEXT sampled
        token: the trie-walk token mask with the stop ids composed from
        acceptance — eos is legal exactly when the generated text so far
        is a complete grammar-valid utterance, and once the grammar can
        only END, eos is the sole legal token (the mask FORCES termination
        instead of sampling garbage past a closed grammar)."""
        row = c.token_mask()
        acc = c.accepting()
        for t in self._stop_ids:
            if 0 <= t < row.shape[0]:
                row[t] = acc
        return row

    def _build_grammar_masks(self, slots: list) -> tuple:  # graftlint: hot-path
        """Build this tick's [max_slots, V] boolean token mask from each
        constrained slot's automaton (host-side — JetStream's orchestration
        stays off the device critical path; the device only sees one extra
        where() in the fused sampler).  Unconstrained rows stay all-True,
        so their sampling is bit-identical to an unmasked dispatch.

        MUST run with no dispatch in flight (the _tick "constrain" fence
        guarantees it): a mask is only valid relative to the automaton
        state AFTER the last committed token.

        Two zero-legal-row cases, told apart by acceptance: a CLOSED
        grammar (accepting, nothing may follow — e.g. no eos id
        configured to express "stop") finishes the slot gracefully with
        the tokens it has (outcome "valid"); a non-accepting empty row —
        chaos-forced or a real compile/token-map bug — fails ONLY that
        slot (ConstraintStall + the incident plane's constraint_stall
        signal).  Both drop the slot from the returned ready list.
        Returns ``(surviving_slots, mask)`` with mask None when no
        surviving slot is constrained."""
        t0 = time.perf_counter()
        mask = None
        stalled = []
        closed = []
        for slot in slots:
            rid = self._slot_req.get(slot)
            pending = self._requests.get(rid) if rid is not None else None
            if pending is None or pending.constrain is None:
                continue
            ts = time.perf_counter()
            row = self._grammar_row(pending.constrain)
            forced = (self._constrain_chaos is not None
                      and self._constrain_chaos.stall_mask())
            if forced:
                row = np.zeros_like(row)
            if pending.span is not None:
                # per-request share of this tick's automaton wall — the
                # waterfall's grammar_advance segment reads these totals
                pending.span.hint("grammar_advance",
                                  time.perf_counter() - ts)
            if not row.any():
                if not forced and pending.constrain.accepting():
                    closed.append(slot)
                else:
                    stalled.append(slot)
                continue
            if mask is None:
                mask = np.ones((self.ec.max_slots, self.config.vocab_size),
                               np.bool_)
            mask[slot, :] = row
        self.telemetry.observe_grammar_mask(time.perf_counter() - t0)
        for slot in closed:
            self._finish(slot, self._slot_req[slot], truncated=False)
        for slot in stalled:
            self._fail_constraint_stall(slot)
        if stalled or closed:
            gone = set(stalled) | set(closed)
            slots = [s for s in slots if s not in gone]
        return slots, mask

    def _fail_constraint_stall(self, slot: int) -> None:
        """A constrained slot's mask has zero legal tokens: a grammar
        compile or token-map bug — NEVER the client's fault (their spec
        compiled and passed admission validation).  Fail ONLY this slot
        with ConstraintStall, count the outcome, and feed the incident
        plane's constraint_stall detector (faults.py pins the chaos class
        -> cause -> playbook contract)."""
        self._constraint_stalls += 1
        self.telemetry.count_constrain("stall")
        tids: list = []
        if self.ec.telemetry:
            tids = self._slot_trace_ids([slot])
            self._flight_event("constraint_stall", [slot], None,
                               time.perf_counter(), "stall",
                               error="zero legal tokens under grammar mask")
        self._incident_event("constraint_stall",
                             rid=self._slot_req.get(slot), trace_ids=tids)
        self._fail_slot(slot, ConstraintStall(
            "constrained decode reached a state with zero legal tokens"))

    def _check_epoch(self) -> None:
        """Die (via _StaleThread, uncatchable by the isolation boundaries)
        if this loop thread was superseded by a watchdog restart — the
        restarted loop may have reassigned our slots, so any further host
        mutation would corrupt a fresh request.  Threads with no recorded
        epoch (watchdog, stop(), callers' threads) always pass."""
        e = getattr(self._tls, "epoch", None)
        if e is not None and e != self._epoch:
            raise _StaleThread(f"epoch {e} superseded by {self._epoch}")

    def _reset_failures(self, pending: _Pending) -> None:
        """Any forward progress (a committed token, a completed prefill
        chunk) makes the failure cap consecutive again."""
        if pending.failures:
            pending.failures = 0
            self._retrying -= 1

    def _release_slot_state(self, slot: int) -> None:
        """Zero one slot's host mirrors (page row, length, adapter id,
        cached decode token, prefill row).  Every release path — finish,
        fail, orphan-reap — funnels here so a future per-slot field can't
        be forgotten in one of them.  A release is a roster change: the
        decode pipeline fences before its next dispatch."""
        self._pt_host[slot, :] = 0
        self._len_host[slot] = 0
        self._aid_host[slot] = 0
        self._tok_host[slot] = 0
        self._prefill_rows.pop(slot, None)
        self._mark_roster_change("finish")

    def _fail_slot(self, slot: int, exc: Exception, shed: bool = False) -> None:
        """Fail ONE slot's request with a typed error and free its
        slot/pages; the rest of the engine is untouched.  Pages are never
        handed to the prefix cache — failed state is suspect by definition."""
        self._check_epoch()
        with self._lock:
            rid = self._slot_req.pop(slot, None)
            pending = self._requests.pop(rid, None) if rid is not None else None
            if pending is not None:
                self._future_rid.pop(pending.future, None)
        self._release_slot_state(slot)
        self._prefilling.pop(slot, None)
        self.batcher.release(slot)
        if pending is None:
            return
        # a deadline shed at admission can hit a swapped request whose
        # blob was never popped (handoff imports have no first token yet):
        # release the parked bytes — no-op for everyone else
        self._kv.discard_swap(rid)
        if pending.failures:
            self._retrying -= 1  # no longer mid-retry: it's terminal now
        if shed:
            self._requests_shed += 1
        else:
            self._requests_failed += 1
        self._archive_span(pending, "shed" if shed else "failed")
        self._resolve_exception(pending, exc)

    def _fail_unassigned(self, exc: Exception) -> None:
        """Fail every request NOT holding a slot (still queued).  Their C++
        queue entries are reaped at admission: pending gone -> slot released
        untouched (same path a queued cancel takes)."""
        with self._lock:
            held = set(self._slot_req.values())
            victims = [(rid, p) for rid, p in self._requests.items()
                       if rid not in held]
            for rid, p in victims:
                del self._requests[rid]
                self._future_rid.pop(p.future, None)
        for rid, p in victims:
            self._sched.remove(rid)
            self._kv.discard_swap(rid)
            self._requests_failed += 1
            self._archive_span(p, "failed")
            self._resolve_exception(p, exc)

    def _resolve_exception(self, pending: _Pending, exc: Exception) -> None:
        # outside _lock (same split _finish uses): done-callbacks may
        # re-enter the engine
        try:
            pending.future.set_exception(exc)
        except Exception:  # already resolved (lost race with cancel)
            pass
        if pending.stream is not None:
            pending.stream.put((None, exc))

    def _watchdog(self) -> None:
        """Supervisor: detects a dead loop thread (escaped exception /
        injected death) or one hung inside a single tick past
        hang_timeout_s, fails the in-flight futures, and — when
        watchdog_restart — revives the loop with a fresh decode state."""
        while not self._wd_stop.wait(self.ec.watchdog_interval_s):
            if not self._running or self._draining:
                continue
            t = self._thread
            if t is None:
                continue
            if not t.is_alive():
                self._supervise("loop thread died")
            elif ((self._slot_req or self._requests)
                  and time.monotonic() - self._last_tick_ts
                  > self.ec.hang_timeout_s):
                self._supervise(
                    f"loop hung > {self.ec.hang_timeout_s}s inside one tick")

    def _supervise(self, reason: str) -> None:
        # fence first: a hung-but-alive thread that wakes later sees the
        # epoch mismatch at the loop top, the _tick entry, the pre-dispatch
        # checks, or any _commit/_finish/_fail_slot and dies (_StaleThread)
        # before mutating host state.  RESIDUAL RISK: a thread blocked
        # INSIDE a device call wakes past its pre-dispatch fence and can
        # still rebind k_pool/v_pool or scatter into reassigned pages
        # before the next check — restart-after-hang is best-effort; a
        # production deployment escalates a repeat offender to process
        # restart.  Loop DEATH (the common case) has no such window.
        self._epoch += 1
        tids: list = []
        dump_path = None
        if self.ec.telemetry:
            # the postmortem the flight recorder exists for: what the loop
            # was doing when the watchdog had to step in.  Best-effort
            # trace ids (no lock: the loop may be hung holding state) so
            # the failover trace tree can cite this dying replica's dump.
            try:
                tids = [p.span.trace_id
                        for p in list(self._requests.values())
                        if p.span is not None]
            except RuntimeError:
                # a concurrent generate_async resized the dict under our
                # lock-free snapshot; losing the ids beats killing the
                # watchdog thread mid-recovery
                tids = []
            self.flight.record(tick=self._ticks, phase="watchdog",
                               slots=list(self._slot_req),
                               rids=list(self._slot_req.values()),
                               trace_ids=tids,
                               shape=None, duration_s=0.0,
                               outcome="supervise", error=reason)
            dump_path = self.flight.dump(
                "watchdog_" + ("restart" if self.ec.watchdog_restart
                               else "halt"),
                extra={"detail": reason, "tick": self._ticks,
                       "trace_ids": tids,
                       "epoch": self._epoch, "restarts": self._restarts})
            self._note_dump(dump_path, tids)
        # incident signal (README "Incident plane"): a watchdog trip IS
        # the engine-local replica death — the classifier's strongest
        # evidence.  Carries the dump just written so the incident cites
        # it instead of burning a second recorder slot.
        self._incident_event("watchdog", detail=reason,
                             restart=self.ec.watchdog_restart,
                             trace_ids=tids, dump=dump_path)
        err = TickFailure(f"engine {reason}; request abandoned by supervisor")
        # drop (never commit) the in-flight pipeline tick: its requests are
        # being failed wholesale, and a readback here — on the watchdog
        # thread, against a possibly-hung dispatch — could block forever
        self._discard_pipeline()
        for slot in list(self._slot_req):
            self._fail_slot(slot, err)
        self._fail_unassigned(err)
        self._sched.clear()
        self._kv.clear_swap()
        self._prefilling.clear()
        self._prefill_rows.clear()
        self._pt_host[:] = 0
        self._len_host[:] = 0
        self._aid_host[:] = 0
        self._tok_host[:] = 0
        if self.ec.watchdog_restart:
            self._restarts += 1
            self._last_tick_ts = time.monotonic()
            self._thread = threading.Thread(
                target=self._loop, args=(self._epoch,), daemon=True)
            self._thread.start()
        else:
            self._running = False

    def _decode_tick_single(self, decode_ready, seq_lens, page_table,
                            gmask=None) -> None:
        # _tok_host is maintained by _commit/_activate_decode (steady-state
        # ticks no longer rebuild it with a Python pass over all slots);
        # inactive/prefilling rows stay 0 via _release_slot_state
        tokens = self._tok_host
        # host mirrors go to the jit RAW — eager jnp.asarray would add a
        # Python-level device_put op per array per tick (3 extra dispatches
        # per token over the remote tunnel).  SAFETY INVARIANT: on CPU
        # backends jax may zero-copy-alias aligned numpy inputs, so the
        # mirrors must not be mutated while the step is in flight; the
        # blocking np.asarray(sample_tokens(...)) below is that barrier —
        # every mirror mutation (_commit and later) happens after it
        self._check_epoch()  # last fence before rebinding device pools
        t_issue = time.perf_counter()
        self._note_dispatch_gap(t_issue)
        logits, self.k_pool, self.v_pool = decode_step(
            self.params, self.config, tokens,
            seq_lens, page_table,
            self.k_pool, self.v_pool, paged=self._paged, mesh=self._mesh,
            lora_params=self._lora,
            adapter_ids=(self._aid_host
                         if self._lora is not None else None),
        )
        self._dispatch_mark = (self._ticks, time.perf_counter())
        logits, ok_dev = self._guard_logits(logits, self._row_rids())
        if gmask is not None:
            # the one extra masked-logits op (README "Structured output"):
            # ordered AFTER the guard reads the raw logits, so a poisoned
            # row still trips the NaN guard — masking must never hide a
            # non-finite dispatch behind a finite -1e30 floor
            jnp = self._jnp
            logits = jnp.where(jnp.asarray(gmask), logits,
                               jnp.float32(-1e30))
        sampled = np.asarray(
            sample_tokens(logits, self._next_key(), self.ec.temperature))
        ok = np.asarray(ok_dev) if ok_dev is not None else None
        if self._perf_on:
            for slot in decode_ready:
                if self._slot_req.get(slot) not in self._requests:
                    continue
                bad = ok is not None and not ok[slot]
                self.perf.charge("decode",
                                 self._fm.decode_row(int(seq_lens[slot])),
                                 1, "tick_retry" if bad else None)
        for slot in decode_ready:
            if ok is not None and not ok[slot]:
                self._fail_nan(slot, f"decode row (slot {slot})")
                continue
            self._commit(slot, int(sampled[slot]))

    def _row_rids(self) -> list:
        """Request id per decode row (slot), -1 for inactive/prefilling rows
        — the chaos injector's per-request targeting key.  Cached between
        roster changes (_mark_roster_change invalidates) so steady-state
        ticks skip the per-tick Python pass over all slots."""
        rids = self._row_rids_c
        if rids is None:
            rids = [-1] * self.ec.max_slots
            for slot, rid in self._slot_req.items():
                if slot not in self._prefilling:
                    rids[slot] = rid
            self._row_rids_c = rids
        return rids

    def _note_dispatch_gap(self, t_issue: float) -> None:
        """Observe the host-side gap since the previous decode dispatch
        completed — only across consecutive decode ticks, so idle waits and
        prefill-only ticks don't pollute the overlap histogram."""
        mark = self._dispatch_mark
        if (self.ec.telemetry and mark is not None
                and mark[0] >= self._ticks - 1):
            self.telemetry.observe_dispatch_gap(t_issue - mark[1])

    # ------------------------------------------------- pipelined decode loop

    def _mark_roster_change(self, reason: str = "roster") -> None:
        """A slot joined or left the decode roster: the next pipelined
        dispatch must drain + rebuild device state first, and the cached
        row-rid view is stale.  ``reason`` labels the resulting fence in
        engine_pipeline_fences_total (first RECORDED cause wins until
        consumed — the dirty flag can outlive a consumed reason, e.g. at
        engine start or when a drain leaves no decode-ready rows, so an
        already-dirty state with no reason still takes this one).
        Exception: "nan" overrides a pending mundane cause — a NaN trip is
        the postmortem-relevant label, and losing it to an admit/finish
        that happened to dirty the roster first in the same tick would
        hide the one fence an incident review looks for."""
        if self._dirty_reason is None or reason == "nan":
            self._dirty_reason = reason
        self._roster_dirty = True
        self._row_rids_c = None

    def _count_fence(self, reason: str) -> None:
        self._fences += 1
        self._fence_reasons[reason] = self._fence_reasons.get(reason, 0) + 1
        self.telemetry.count_fence(reason)

    def _drain_pipeline(self, reason: str) -> None:
        """Pipeline fence: block on the in-flight tick's async readback,
        commit its tokens, and discard device decode state so the next
        dispatch rebuilds from the (now fully current) host mirrors.  A
        no-op — and not counted — when nothing is in flight."""
        rec, self._inflight = self._inflight, None
        self._dec_state = None
        if rec is None:
            return
        self._count_fence(reason)
        t0 = time.perf_counter() if self._perf_on else 0.0
        self._commit_inflight(rec)
        if self._perf_on:
            self.timeline.note(self._ticks, "drain",
                               time.perf_counter() - t0)

    def _discard_pipeline(self) -> None:
        """Drop pipeline state WITHOUT committing (watchdog restart / stop:
        the in-flight tick's requests are being failed wholesale, and this
        may run off the loop thread where a device readback could block on
        a hung dispatch forever)."""
        if self._inflight is not None:
            self._count_fence("restart")
            # dispatched, never committed: real device work, discarded
            self._charge_dropped(self._inflight, "tick_retry")
        self._inflight = None
        self._dec_state = None
        self._roster_dirty = True

    def _commit_inflight(self, rec: dict) -> None:
        """Commit-behind: land tick N's sampled tokens in the C++ batcher
        (and host mirrors/streams) — called right after tick N+1's dispatch,
        or from a fence.  Rows whose slot was rebound or released since the
        dispatch are discarded via the rid guard; a guard-tripped row
        (negative guarded token, see model.decode_step_sample) fails only
        itself, exactly like the sync loop's post-sample check."""
        if rec.get("kind") == "spec":
            self._commit_inflight_spec(rec)
            return
        perf = self._perf_on
        t0 = time.perf_counter() if perf else 0.0
        sampled = np.asarray(rec["sampled"])  # async copy started at dispatch
        if perf:
            self.timeline.note(self._ticks, "readback",
                               time.perf_counter() - t0)
            t0 = time.perf_counter()
        fl = rec.get("flops") or {}
        for slot in rec["slots"]:
            rid = rec["rids"][slot]
            if self._slot_req.get(slot) != rid or rid not in self._requests:
                # finished/failed/preempted behind the dispatch: the row's
                # device work is discarded by the rid guard
                f = fl.get(slot)
                if f:
                    self.perf.charge("decode", f, 1, "pipeline_drop")
                continue
            tok = int(sampled[slot])
            if tok < 0:  # guard encoding: -token - 1 == non-finite row
                f = fl.get(slot)
                if f:
                    self.perf.charge("decode", f, 1, "tick_retry")
                self._fail_nan(slot, f"pipelined decode row (slot {slot})")
                continue
            f = fl.get(slot)
            if f:
                self.perf.charge("decode", f, 1, None)
            self._commit(slot, tok)
        if perf:
            self.timeline.note(self._ticks, "commit_behind",
                               time.perf_counter() - t0)

    # -------------------------------------------- pipelined speculative loop

    def _accepted_row(self, pending: _Pending, row: "np.ndarray") -> list:
        """Decode one packed verify row into the token list the sync commit
        walk would have committed: the leading non-sentinel entries,
        truncated at the remaining token budget and at the first stop id
        (the batcher finishes the slot there — rc != 1 ends the sync walk).
        Empty == the row's NaN guard tripped (sentinel-only row)."""
        n = int((row >= 0).sum())  # packed rows are leading-accepted
        toks = [int(t) for t in row[:n]]
        budget = pending.max_new_tokens - len(pending.generated)
        toks = toks[:max(0, budget)]
        for j, t in enumerate(toks):
            if t in self._stop_ids:
                return toks[:j + 1]
        return toks

    def _stage_inflight_spec(self, rec: dict) -> bool:
        """Read back the in-flight verify tick's packed tokens (async copy
        started at dispatch) and STAGE them: append to ``pending.context``
        (next tick's drafts read it) and advance the seq-len shadow — the
        cheap host edits drafting needs NOW.  The heavyweight per-token
        work (C++ commits, stream pushes, TPOT telemetry) stays deferred to
        the commit-behind after the next dispatch.

        Returns False when the tick needs a fence BEFORE the next dispatch
        — a row finished (EOS / budget) or tripped the NaN guard, so its
        release/fail must land before the next dispatch's snapshot — with
        ``rec["fence_reason"]`` set to the postmortem-relevant label."""
        t0 = time.perf_counter() if self._perf_on else 0.0
        packed = np.asarray(rec["packed"])
        if self._perf_on:
            self.timeline.note(self._ticks, "readback",
                               time.perf_counter() - t0)
        rec["packed_np"] = packed
        reason = None
        shadow = None
        for slot in rec["slots"]:
            rid = rec["rids"][slot]
            pending = self._requests.get(rid)
            if self._slot_req.get(slot) != rid or pending is None:
                continue
            toks = self._accepted_row(pending, packed[slot])
            rec["staged"][slot] = toks
            if not toks:  # sentinel row: NaN guard tripped mid-verify
                reason = "nan"
                continue
            pending.context.extend(toks)
            if (len(pending.generated) + len(toks) >= pending.max_new_tokens
                    or toks[-1] in self._stop_ids):
                reason = reason or "finish"  # slot finishes at commit
            if shadow is None:
                # rebound, never mutated in place: the in-flight dispatch
                # may alias the previous shadow zero-copy on CPU backends
                shadow = self._dec_lens_shadow.copy()
            shadow[slot] += len(toks)
        if shadow is not None:
            self._dec_lens_shadow = shadow
        rec["fence_reason"] = reason
        return reason is None

    def _commit_inflight_spec(self, rec: dict) -> None:
        """Commit-behind for a fused verify tick: land 1..K staged tokens
        per slot in the C++ batcher (and streams/telemetry).  Rows not yet
        staged (a fence drained the pipeline before the steady-state
        readback — preempt, stop, idle) are decoded from the packed array
        here, context append included.  A sentinel (NaN-guarded) row fails
        only its own slot, exactly like the sync verify's whole-pass
        check."""
        perf = self._perf_on
        t0 = time.perf_counter() if perf else 0.0
        packed = rec.get("packed_np")
        if packed is None:
            packed = np.asarray(rec["packed"])
        fl = rec.get("flops") or {}
        for slot in rec["slots"]:
            f_row, k_i = fl.get(slot, (0.0, 1))
            rid = rec["rids"][slot]
            if self._slot_req.get(slot) != rid or rid not in self._requests:
                # finished/failed/preempted behind the dispatch: the row's
                # device work is discarded by the rid guard
                if f_row:
                    self.perf.charge("verify", f_row, k_i, "pipeline_drop")
                continue
            pending = self._requests[rid]
            toks = rec["staged"].get(slot)
            staged = toks is not None
            if not staged:
                toks = self._accepted_row(pending, packed[slot])
            if not toks:
                if f_row:
                    self.perf.charge("verify", f_row, k_i, "tick_retry")
                rec["staged"].pop(slot, None)
                self._fail_nan(slot, f"fused verify row (slot {slot})")
                continue
            d = rec["drafts"].get(slot) or ()
            self._spec_proposed += len(d)
            committed = 0
            for t in toks:
                rc = self._commit(slot, int(t), ctx=not staged)
                committed += 1
                if staged:
                    # shrink-on-commit: anything left in rec["staged"]
                    # after an exception — including one raised by a later
                    # token's _commit — is exactly the uncommitted
                    # remainder the failed tick's rollback must un-stage
                    # from pending.context
                    rec["staged"][slot] = toks[committed:]
                if rc != 1:
                    break  # finished / truncated: slot already released
            if staged:
                rest = rec["staged"].pop(slot)
                if rest:
                    # the batcher finished earlier than staging predicted —
                    # un-stage the tail so context stays exactly prompt +
                    # generated (preempt/pin snapshots read it)
                    del pending.context[-len(rest):]
            if f_row:
                # committed positions' share is goodput; the remainder
                # (rejected drafts / early-EOS tail) is spec_reject
                good = f_row * min(committed, k_i) / k_i
                self.perf.charge("verify", good, committed, None)
                if k_i > committed:
                    self.perf.charge("verify", f_row - good,
                                     k_i - committed, "spec_reject")
            # accepted draft tokens = committed minus the bonus/correction
            # token (the sync walk's per-token increment, summed)
            acc = max(0, committed - 1)
            self._spec_accepted += acc
            if d:
                self.telemetry.observe_spec(len(d), acc)
        if perf:
            self.timeline.note(self._ticks, "commit_behind",
                               time.perf_counter() - t0)

    def _cover_row0(self, slot: int, S: int) -> bool:
        """Commit-behind page accounting for the speculative pipeline: a
        multi-token tick advances the shadow by 1..K, so the next
        dispatch's row-0 write (position S-1) may sit past the pages the
        committed length implies — reserve the shortfall up to
        pages_for(S), i.e. as much as K/page_size + 1 pages ahead of the
        landed commits (draft positions need no extra cover: _draft_for
        clamps them to owned room, exactly like the sync path).  Returns
        the slot's owned-page count (the tick hands it to _draft_for so
        the row is scanned once per tick, not twice), or -1 when the pool
        can't cover the row — the caller falls back to one sync tick
        whose commit-time OOM truncates exactly like depth 0."""
        need = self._pages_for(S)
        if need > self.ec.max_pages_per_slot:
            return -1
        return self._reserve_to(slot, need)

    def _reserve_to(self, slot: int, need: int) -> int:
        """Reserve pages until the slot OWNS ``need`` (native.reserve_page;
        a later commit crossing into a reserved page allocates nothing) and
        mirror them into the host page table.  Returns the resulting
        owned-page count (>= need), or -1 on pool exhaustion — both
        lookahead callers fall back to a sync tick whose commit-time OOM
        truncates exactly like depth 0."""
        owned = int(np.count_nonzero(self._pt_host[slot]))
        while owned < need:
            p = self.batcher.reserve_page(slot)
            if p < 0:
                return -1
            self._pt_host[slot, owned] = p
            owned += 1
        return owned

    def _ready_now(self) -> list:
        """The decode-ready slot set as of RIGHT NOW (post-drain): bound to
        a live request and not mid-prefill."""
        return [s for s in self._slot_req
                if s not in self._prefilling
                and self._slot_req[s] in self._requests]

    def _rebuild_device_state(self, decode_ready) -> None:
        """Upload the last committed token per slot — the device-resident
        feedback edge the fused decode step then carries forward between
        fences (seq_lens ride the host shadow: advanced per-tick from the
        committed/staged token counts, uploaded per dispatch, never read
        back from the device).  In speculative mode the feedback is a SEED
        packed row ``[last_token, -1, ...]`` shaped like the fused verify
        dispatch's output, so steady-state ticks chain the previous packed
        output directly."""
        if self._spec is not None:
            K = 1 + self.ec.spec_max_draft
            seed = np.full((self.ec.max_slots, K), -1, np.int32)
            for slot in decode_ready:
                seed[slot, 0] = self._feedback_token(
                    self._requests[self._slot_req[slot]])
            self._dec_state = self._jnp.asarray(seed)
        else:
            toks = np.zeros((self.ec.max_slots,), np.int32)
            for slot in decode_ready:
                toks[slot] = self._feedback_token(
                    self._requests[self._slot_req[slot]])
            self._dec_state = self._jnp.asarray(toks)
        self._dec_lens_shadow = self._len_host.copy()
        self._roster_dirty = False
        # reasons recorded by the drain's OWN commits (a finish during the
        # fence) are absorbed by this rebuild — a dangling one would
        # mislabel the next unrelated fence.  EXCEPT "nan": a NaN trip is
        # the one label a postmortem looks for (same precedence rule as
        # _mark_roster_change), and a poisoned token committed DURING an
        # admit/finish drain would otherwise leave no nan-labeled fence at
        # all — keep it so the next fence carries it
        if self._dirty_reason != "nan":
            self._dirty_reason = None

    def _reserve_lookahead(self, decode_ready) -> bool:
        """Commit-behind page accounting: the C++ page grant for tick N's
        token happens one tick late, so BEFORE dispatching with seq_lens S
        every live row must already own pages_for(S) pages — reserve the
        shortfall now (native.reserve_page; a later commit crossing into the
        reserved page allocates nothing, so the two paths compose).  False
        when the pool can't cover a row: the caller falls back to one sync
        tick, whose commit-time OOM handling truncates exactly like the
        sync loop.

        Coverage invariant (keeps non-boundary ticks O(1) per row, no
        owned-page scan): after a rebuild, owned >= pages_for(len) holds by
        the commit-growth invariant, and every boundary tick below restores
        owned >= pages_for(S) — so a row only needs work when THIS
        dispatch's KV write position starts a new page ((S-1) % page_size
        == 0); a reservation failure fences + rebuilds, re-establishing the
        invariant before the next pipelined dispatch."""
        ps = self.ec.page_size
        for slot in decode_ready:
            S = int(self._dec_lens_shadow[slot])
            if S <= 0 or (S - 1) % ps:
                continue  # covered by the pages already verified for S-1
            need = self._pages_for(S)
            if need > self.ec.max_pages_per_slot:
                # one-past-final masked step of a row finishing behind the
                # dispatch: the fused step trash-routes its KV write
                continue
            if self._reserve_to(slot, need) < 0:
                return False
        return True

    def _decode_tick_pipelined(self, decode_ready, gmask=None) -> None:
        """One pipelined decode tick: fence if the roster changed, reserve
        lookahead pages, dispatch the fused step (device consumes its own
        previous output), start the async token readback, then commit the
        PREVIOUS tick's tokens while this one runs on device.

        ``gmask`` (README "Structured output"): [max_slots, V] grammar mask
        shipped into the fused sampler as its ``token_mask`` — the one
        extra masked-logits op, no new jit signature.  Constrained ticks
        arrive here with the pipeline already drained (_tick's "constrain"
        fence), so the mask is exact for the token THIS dispatch samples;
        the commit lands at the next tick's fence."""
        self._check_epoch()  # a superseded thread must not touch pipeline
        try:
            if self._roster_dirty or self._dec_state is None:
                reason, self._dirty_reason = (self._dirty_reason or "roster",
                                              None)
                self._drain_pipeline(reason)
                # the drain's blocking readback is the hang window the
                # watchdog fires on: a stale thread resuming here must die
                # before rebuilding state the restarted loop now owns
                self._check_epoch()
                # the drain's commits may have finished/failed rows (or, via
                # a NaN fail, released slots): recompute the ready set
                decode_ready = self._ready_now()
                if not decode_ready:
                    return
                self._rebuild_device_state(decode_ready)
            if not self._reserve_lookahead(decode_ready):
                # pool exhausted at the lookahead: run this tick through the
                # sync path (its commit-time rc==-2 handling truncates the
                # right row); device state rebuilds next tick
                self._drain_pipeline("pool")
                decode_ready = self._ready_now()  # drain may finish rows
                if not decode_ready:
                    return
                self._decode_tick_single(decode_ready, self._len_host,
                                         self._pt_host, gmask)
                return
            tok_dev = self._dec_state
            # per-dispatch page-table snapshot: commit-behind mutates
            # _pt_host while this dispatch is in flight, and the previous
            # snapshot may still back the in-flight tick — alternate
            self._pt_flip ^= 1
            buf = self._pt_dispatch[self._pt_flip]
            np.copyto(buf, self._pt_host)
            poison = None
            if self._chaos is not None:
                poison = np.zeros((self.ec.max_slots,), bool)
                for row in self._chaos.nan_rows(self._row_rids()):
                    poison[row] = True
            self._check_epoch()  # last fence before rebinding device pools
            t_issue = time.perf_counter()
            self._note_dispatch_gap(t_issue)
            sampled, self.k_pool, self.v_pool = decode_step_sample(
                self.params, self.config, tok_dev, self._dec_lens_shadow,
                buf, self.k_pool, self.v_pool, self._next_key(), poison,
                temperature=self.ec.temperature,
                guard=self.ec.logit_guard,
                paged=self._paged, mesh=self._mesh,
                lora_params=self._lora,
                adapter_ids=(np.array(self._aid_host)
                             if self._lora is not None else None),
                token_mask=gmask,
            )
            self._dispatch_mark = (self._ticks, time.perf_counter())
            if self._async_readback:
                try:
                    # async readback: the D2H copy overlaps the device
                    # step; the commit (next tick or fence) finds it ready
                    sampled.copy_to_host_async()
                except Exception:  # noqa: BLE001 — best-effort prefetch
                    pass
            rec = {
                "sampled": sampled, "slots": tuple(decode_ready),
                "rids": {s: self._slot_req[s] for s in decode_ready},
            }
            if self._perf_on:
                # FLOPs priced at dispatch (the shadow lens this dispatch
                # used), attributed at commit-behind when the outcome per
                # row is known
                rec["flops"] = {
                    s: self._fm.decode_row(int(self._dec_lens_shadow[s]))
                    for s in decode_ready}
            prev, self._inflight = self._inflight, rec
            self._dec_state = sampled
            self._dec_lens_shadow = np.where(
                self._dec_lens_shadow > 0, self._dec_lens_shadow + 1, 0)
            if prev is not None:
                # commit-behind: tick N lands while tick N+1 runs on device
                self._commit_inflight(prev)
        except BaseException:
            # a failed pipelined tick leaves in-flight/device state suspect
            # (donated pools, unread arrays): reset so the retry rebuilds
            # from committed host state — greedy re-derives any dropped
            # in-flight token byte-identically.  A SUPERSEDED thread
            # (_StaleThread) must not touch the state: it now belongs to
            # the restarted loop, which reset it itself in _supervise.
            if getattr(self._tls, "epoch", None) in (None, self._epoch):
                self._inflight = None
                self._dec_state = None
                self._roster_dirty = True
            raise

    def _decode_tick_spec_pipelined(self, decode_ready) -> None:
        """One pipelined SPECULATIVE tick (ISSUE 9): fence if the roster
        changed, read back the previous verify tick's packed tokens (async
        copy started at its dispatch) and stage them, draft from the
        staged context, reserve up to K lookahead pages per slot, dispatch
        the fused verify step (the device derives its own committed-token
        feedback from the previous packed output), then commit the
        PREVIOUS tick's 1..K tokens per slot while this one runs on device
        — the per-token host work (C++ commits, stream pushes, TPOT) runs
        behind the dispatch, cut off the critical path by the acceptance
        factor."""
        self._check_epoch()  # a superseded thread must not touch pipeline
        K = 1 + self.ec.spec_max_draft
        staged_rec = None  # staged-but-uncommitted record, for rollback
        try:
            if self._roster_dirty or self._dec_state is None:
                reason, self._dirty_reason = (self._dirty_reason or "roster",
                                              None)
                self._drain_pipeline(reason)
                self._check_epoch()
                decode_ready = self._ready_now()
                if not decode_ready:
                    return
                self._rebuild_device_state(decode_ready)
            prev = self._inflight
            staged_n = {}
            if prev is not None:
                if not self._stage_inflight_spec(prev):
                    # a row finished (EOS/budget) or tripped the NaN guard
                    # behind the dispatch: commit NOW at a fence so the
                    # release/fail lands before the next dispatch's
                    # page-table snapshot — the spec twin of the plain
                    # loop's finish/nan fences.  Staging already extended
                    # pending.context, so the rollback must see this
                    # record if the drain's commit raises partway
                    staged_rec = prev
                    fr = prev["fence_reason"]
                    self._drain_pipeline(fr)
                    if fr == "nan" and self._dirty_reason == "nan":
                        # this fence already carried the nan label; the
                        # _fail_nan inside the drain re-marked the roster —
                        # don't bill a second nan fence for the same trip
                        self._dirty_reason = None
                    self._check_epoch()
                    decode_ready = self._ready_now()
                    if not decode_ready:
                        return
                    self._rebuild_device_state(decode_ready)
                    prev = None
                else:
                    staged_rec = prev
                    staged_n = {s: len(t)
                                for s, t in prev["staged"].items()}
            # ---- row-0 lookahead cover + drafts (the sync loop's exact
            # draft-size policy via _draft_for, so the any-drafts gate
            # below fires on the same ticks as the sync loop's)
            jnp = self._jnp
            drafts = np.zeros((self.ec.max_slots, K - 1), np.int32)
            dlen = np.zeros((self.ec.max_slots,), np.int32)
            by_slot = {}
            shadow = self._dec_lens_shadow
            for slot in decode_ready:
                S = int(shadow[slot])
                if S <= 0:
                    continue
                owned = self._cover_row0(slot, S)
                if owned < 0:
                    # pool exhausted at the lookahead: run this tick through
                    # the sync path (its commit-time rc==-2 handling
                    # truncates the right row); device state rebuilds next
                    # tick — same fallback the plain pipelined loop takes
                    self._drain_pipeline("pool")
                    decode_ready = self._ready_now()
                    if not decode_ready:
                        return
                    self._decode_tick_single(decode_ready, self._len_host,
                                             self._pt_host)
                    return
                pending = self._requests[self._slot_req[slot]]
                gen = len(pending.generated) + staged_n.get(slot, 0)
                d = self._draft_for(slot, S, gen_count=gen, owned=owned)
                if d:
                    drafts[slot, :len(d)] = d
                    dlen[slot] = len(d)
                    by_slot[slot] = list(d)
            # per-dispatch page-table snapshot (double-buffered): the
            # commit-behind below mutates _pt_host while this dispatch and
            # possibly the previous one are still in flight
            self._pt_flip ^= 1
            buf = self._pt_dispatch[self._pt_flip]
            np.copyto(buf, self._pt_host)
            self._check_epoch()  # last fence before rebinding device pools
            if by_slot:
                # verify tick: 1 committed + up to K-1 draft tokens per row
                # in one fused dispatch, accept/reject resolved on device
                poison = None
                if self._chaos is not None:
                    poison = np.zeros((self.ec.max_slots,), bool)
                    for row in self._chaos.nan_rows(self._row_rids(),
                                                    phase="verify"):
                        poison[row] = True
                t_issue = time.perf_counter()
                self._note_dispatch_gap(t_issue)
                packed, self.k_pool, self.v_pool = decode_step_verify_sample(
                    self.params, self.config, self._dec_state, drafts, dlen,
                    shadow, buf, self.k_pool, self.v_pool, self._next_key(),
                    poison,
                    temperature=self.ec.temperature,
                    guard=self.ec.logit_guard,
                    paged=self._paged, mesh=self._mesh,
                    lora_params=self._lora,
                    adapter_ids=(np.array(self._aid_host)
                                 if self._lora is not None else None),
                )
            else:
                # no drafts anywhere this tick: mirror the sync loop's
                # single-token dispatch (decode_step_sample_packed shares
                # _sample_core/_decode_core with the sync decode_step, so a
                # no-draft tick's numerics are STRUCTURALLY identical
                # between the two modes — dispatching the K-wide verify
                # here instead would expose bf16 reduction-order drift to
                # near-ties).  The packed-shaped feedback derive and repack
                # ride INSIDE the jit, so an index-miss tick stays one
                # dispatch and mode switches need no fence.
                poison = None
                if self._chaos is not None:
                    poison = np.zeros((self.ec.max_slots,), bool)
                    for row in self._chaos.nan_rows(self._row_rids()):
                        poison[row] = True
                t_issue = time.perf_counter()
                self._note_dispatch_gap(t_issue)
                packed, self.k_pool, self.v_pool = decode_step_sample_packed(
                    self.params, self.config, self._dec_state, shadow, buf,
                    self.k_pool, self.v_pool, self._next_key(), poison,
                    temperature=self.ec.temperature,
                    guard=self.ec.logit_guard,
                    paged=self._paged, mesh=self._mesh,
                    lora_params=self._lora,
                    adapter_ids=(np.array(self._aid_host)
                                 if self._lora is not None else None),
                )
            self._dispatch_mark = (self._ticks, time.perf_counter())
            if self._async_readback:
                try:
                    packed.copy_to_host_async()
                except Exception:  # noqa: BLE001 — best-effort prefetch
                    pass
            rec = {
                "kind": "spec", "packed": packed,
                "slots": tuple(decode_ready),
                "rids": {s: self._slot_req[s] for s in decode_ready},
                "drafts": by_slot, "staged": {},
            }
            if self._perf_on:
                # (flops, k) per row priced at dispatch — k = 1 committed
                # + real drafts (padding verify lanes are not requested
                # work); attributed goodput/spec_reject at commit-behind
                rec["flops"] = {
                    s: (self._fm.verify_row(int(shadow[s]),
                                            int(dlen[s]) + 1),
                        int(dlen[s]) + 1)
                    for s in decode_ready}
            prev2, self._inflight = prev, rec
            self._dec_state = packed
            if prev2 is not None:
                # commit-behind: tick N's 1..K tokens per slot land while
                # tick N+1 runs on device
                self._commit_inflight(prev2)
        except BaseException:
            # same recovery contract as _decode_tick_pipelined: a failed
            # tick leaves in-flight/device state suspect — reset so the
            # retry rebuilds from committed host state (greedy re-derives
            # any dropped tokens byte-identically); a SUPERSEDED thread
            # must not touch state the restarted loop now owns
            if getattr(self._tls, "epoch", None) in (None, self._epoch):
                if staged_rec is not None:
                    # un-stage context tokens the commit-behind never
                    # landed (the commit pops each slot's staged entry as
                    # it commits): the retry re-derives them byte-
                    # identically, and a double-append here would poison
                    # every later draft/preempt/pin snapshot
                    for slot, toks in staged_rec.get("staged", {}).items():
                        p = self._requests.get(
                            staged_rec["rids"].get(slot))
                        if p is not None and toks:
                            del p.context[-len(toks):]
                self._inflight = None
                self._dec_state = None
                self._roster_dirty = True
            raise

    # ------------------------------------------------------- speculative

    def _draft_for(self, slot: int, seq_len: int,
                   gen_count: Optional[int] = None,
                   owned: Optional[int] = None) -> list[int]:
        """Prompt-lookup draft: continuation of the most recent earlier
        occurrence of the context's final n-gram, clamped so every draft
        position stays inside the slot's currently-owned pages.

        The n-gram index is built incrementally (each committed position is
        indexed exactly once per request), so a tick costs O(new tokens),
        not an O(context) backward scan — the long-context host-loop fix.

        ``gen_count`` overrides the generated-token count the budget clamp
        uses: the pipelined speculative loop passes committed + STAGED
        (readback landed, commit-behind pending) so drafts never overshoot
        the token budget.  The room/reserve policy here is THE draft-size
        policy for both loops — sharing it keeps the sync and pipelined
        tick sequences structurally aligned (same any-drafts gate, same
        dispatch shapes), which greedy byte-identity across the two modes
        rests on.  ``owned`` passes a just-computed owned-page count (the
        pipelined tick's _cover_row0 already scanned the row; scanning it
        twice per tick is host work on the path this PR strips)."""
        if seq_len == 0:
            return []
        ps = self.ec.page_size
        # draft row j writes KV at position seq_len-1+j, which must land in
        # an OWNED page; count room against owned pages (reservations
        # included), not just the pages the committed length implies
        if owned is None:
            owned = int(np.count_nonzero(self._pt_host[slot]))
        room = owned * ps - seq_len
        pending = self._requests[self._slot_req[slot]]
        if pending.brownout >= 2:
            # ingress brownout stage 2+ (README "Overload control"):
            # speculation spends K-wide verify dispatches to buy latency —
            # exactly the quality-not-availability spend a browned-out
            # service sheds first.  No draft = the plain single-token
            # step, byte-identical output, just slower.
            return []
        if gen_count is None:
            gen_count = len(pending.generated)
        budget = pending.max_new_tokens - gen_count - 1
        if (room < min(self.ec.spec_max_draft, budget)
                and self.batcher.free_pages > self.ec.max_slots):
            # near the boundary with drafts still wanted: reserve the next
            # page ahead of the draft so boundary ticks keep their
            # acceptance rate (the slack gate keeps reservations from
            # starving another slot's commit into OOM-truncation)
            p = self.batcher.reserve_page(slot)
            if p >= 0:
                self._pt_host[slot, owned] = p
                room += ps
        draft = self._lookup_draft(pending,
                                   min(self.ec.spec_max_draft, room, budget))
        if draft and pending.constrain is not None:
            draft = self._legal_draft_prefix(pending, draft)
        return draft

    def _legal_draft_prefix(self, pending: "_Pending", draft: list) -> list:
        """Truncate a prompt-lookup draft at the first token the grammar
        rejects, walking an automaton CLONE (README "Structured output" —
        the request's own automaton only ever advances at _commit).  A
        known-illegal draft position would burn a verify lane on a
        guaranteed grammar rejection; truncating keeps every rejected
        draft that DOES reach verify a genuine model disagreement, charged
        to the existing spec_reject waste bucket.  Stop ids also end the
        draft — the commit walk terminates there regardless."""
        ts = time.perf_counter()
        walker = pending.constrain.clone()
        keep = 0
        for t in draft:
            if int(t) in self._stop_ids or not walker.advance(int(t)):
                break
            if not self._grammar_row(walker).any():
                # the grammar CLOSED behind this token (zero legal rows —
                # e.g. a complete utterance with no eos id configured):
                # keep the closing token OUT of the draft so no verify
                # position ever samples from an all-False mask.  It
                # arrives through the regular sampled path instead, and
                # the next tick's mask build finishes the slot.
                break
            keep += 1
        if pending.span is not None:
            pending.span.hint("grammar_advance", time.perf_counter() - ts)
        return draft[:keep]

    def _lookup_draft(self, pending: _Pending, limit: int) -> list:
        """The prompt-lookup index walk shared by the sync and pipelined
        speculative paths: advance the incremental n-gram index over any
        newly-appended context (each position indexed exactly once per
        request — staged tokens from the pipelined readback included), then
        return up to ``limit`` continuation tokens of the most recent
        EARLIER occurrence of the context's final n-gram."""
        if limit <= 0:
            return []
        ctx = pending.context
        n = self.ec.spec_ngram
        if len(ctx) <= n:
            return []
        # index n-grams with starts STRICTLY before the final one, so the
        # lookup yields the most recent EARLIER occurrence (later writes win)
        idx = pending.ngram_index
        p = pending.ngram_p
        last = len(ctx) - n
        while p < last:
            idx[tuple(ctx[p:p + n])] = p
            p += 1
        pending.ngram_p = p
        i = idx.get(tuple(ctx[-n:]))
        if i is None:
            return []
        return ctx[i + n:i + n + limit]

    def _decode_tick_speculative(self, decode_ready, drafts, seq_lens,
                                 page_table, gmask=None) -> None:
        """One verify pass over [last token + drafts] for every ready slot;
        commit the longest draft prefix matching greedy argmax plus the one
        bonus token the final logit row yields (lossless vs token-by-token).
        Rejected draft KV stays masked and is overwritten by the next tick's
        row-0 write before anything reads it.

        ``gmask`` (README "Structured output"): the [max_slots, V] position-0
        grammar mask; expanded here into the [max_slots, K, V] verify mask by
        walking an automaton CLONE over each slot's drafts — position j's
        rows assume drafts 0..j-1 accepted, exactly the state the commit
        walk is in when it reads logits[j].  Draft tokens the grammar
        rejects were already truncated by _draft_for, so rejected-draft
        waste stays charged to the existing spec_reject bucket, never to a
        grammar disagreement."""
        K = 1 + self.ec.spec_max_draft
        tokens = np.zeros((self.ec.max_slots, K), np.int32)
        for slot in decode_ready:
            tokens[slot, 0] = self._feedback_token(
                self._requests[self._slot_req[slot]])
            d = drafts.get(slot) or []
            tokens[slot, 1:1 + len(d)] = d
        vmask = None
        if gmask is not None:
            tm = time.perf_counter()
            vmask = np.ones((self.ec.max_slots, K, self.config.vocab_size),
                            np.bool_)
            for slot in decode_ready:
                pending = self._requests.get(self._slot_req.get(slot))
                if pending is None or pending.constrain is None:
                    continue
                ts = time.perf_counter()
                vmask[slot, 0, :] = gmask[slot]
                walker = pending.constrain.clone()
                for j, t in enumerate(drafts.get(slot) or []):
                    if not walker.advance(int(t)):
                        # only reachable from a dead-end state (_draft_for
                        # already truncated illegal drafts); the preceding
                        # position's mask forbids continuing, so the commit
                        # walk can never read the rows left all-True here
                        break
                    vmask[slot, j + 1, :] = self._grammar_row(walker)
                if pending.span is not None:
                    pending.span.hint("grammar_advance",
                                      time.perf_counter() - ts)
            self.telemetry.observe_grammar_mask(time.perf_counter() - tm)
        # raw host mirrors, as in _decode_tick_single — same safety
        # invariant: the blocking sample_tokens fence below precedes every
        # mirror mutation, so the (possibly aliased) buffers are stable
        # while the step is in flight
        self._check_epoch()  # last fence before rebinding device pools
        t_issue = time.perf_counter()
        self._note_dispatch_gap(t_issue)
        logits, self.k_pool, self.v_pool = decode_step_k(
            self.params, self.config, tokens,
            seq_lens, page_table,
            self.k_pool, self.v_pool, paged=self._paged, mesh=self._mesh,
            lora_params=self._lora,
            adapter_ids=(self._aid_host
                         if self._lora is not None else None),
        )
        self._dispatch_mark = (self._ticks, time.perf_counter())
        logits, ok_dev = self._guard_logits(logits, self._row_rids(),
                                            phase="verify")
        if vmask is not None:
            # one extra masked-logits op, AFTER the guard read the raw
            # logits (a poisoned verify pass must still trip the guard)
            jnp = self._jnp
            logits = jnp.where(jnp.asarray(vmask), logits,
                               jnp.float32(-1e30))
        B, _, V = logits.shape
        sampled = np.asarray(sample_tokens(
            logits.reshape(B * K, V), self._next_key(), self.ec.temperature,
        )).reshape(B, K)
        ok = np.asarray(ok_dev) if ok_dev is not None else None
        for slot in decode_ready:
            k_i = 1 + len(drafts.get(slot) or [])
            f_row = (self._fm.verify_row(int(seq_lens[slot]), k_i)
                     if self._perf_on else 0.0)
            if ok is not None and not ok[slot]:
                if self._perf_on:
                    # the whole poisoned pass is discarded work
                    self.perf.charge("verify", f_row, k_i, "tick_retry")
                # any of the slot's K verify rows non-finite: fail the slot
                # before committing anything from the poisoned pass
                self._fail_nan(slot, f"speculative verify (slot {slot})")
                continue
            d = drafts.get(slot) or []
            self._spec_proposed += len(d)
            acc = 0
            committed = 0
            for j in range(len(d) + 1):
                tok = int(sampled[slot, j])
                rc = self._commit(slot, tok)
                committed += 1
                if rc != 1:
                    break  # finished / truncated: slot already released
                # logits[j+1] is only valid if the input at that row (the
                # j-th draft token) matches what greedy actually produced
                if j >= len(d) or d[j] != tok:
                    break
                self._spec_accepted += 1
                acc += 1
            if self._perf_on and f_row > 0:
                # committed positions' share is goodput; the remainder —
                # rejected drafts (and the tail of an early EOS) — is the
                # speculation tax, attributed spec_reject
                good = f_row * committed / k_i
                self.perf.charge("verify", good, committed, None)
                if k_i > committed:
                    self.perf.charge("verify", f_row - good,
                                     k_i - committed, "spec_reject")
            if d:
                self.telemetry.observe_spec(len(d), acc)

    def _pages_for(self, tokens: int) -> int:
        return (tokens + self.ec.page_size - 1) // self.ec.page_size

    @staticmethod
    def _feedback_token(pending: "Optional[_Pending]") -> int:
        """The decode input token for a slot with no tick history: the
        last generated token normally; for a handoff-imported request —
        decode-ready with ZERO generated tokens — the prompt's final
        token, which IS the prefill phase's first sampled token (the
        decode phase folds it into the prompt)."""
        if pending is None:
            return 0
        if pending.generated:
            return pending.generated[-1]
        if pending.handoff_import and pending.tokens:
            return pending.tokens[-1]
        return 0

    def _activate_decode(self, slot: int, plen: int, owned: int, row) -> None:
        """Prefill finished: install the slot's page row + length into the
        host mirrors, making it visible to the decode step (rows are zero —
        trash page — until this point so decode KV writes can't touch a
        mid-prefill slot).  A new decode row is a roster change: the
        pipeline fences before its next dispatch."""
        self._check_epoch()
        self._pt_host[slot, :owned] = row[:owned]
        self._len_host[slot] = plen
        pending = self._requests.get(self._slot_req.get(slot))
        self._tok_host[slot] = self._feedback_token(pending)
        self._prefill_rows.pop(slot, None)
        self._mark_roster_change("admit")

    def _commit(self, slot: int, token: int, ctx: bool = True) -> int:
        """Record one generated token; returns the batcher rc (1 = keep
        decoding; anything else means the slot was finished+released).
        ``ctx=False``: the token was already STAGED into ``pending.context``
        by the pipelined speculative loop's readback (drafting needed it
        before this commit-behind landed) — don't append it twice."""
        self._check_epoch()
        rid = self._slot_req[slot]
        pending = self._requests[rid]
        self._reset_failures(pending)  # consecutive cap: progress resets it
        if pending.constrain is not None and token not in self._stop_ids:
            # THE automaton-advance point (README "Structured output"):
            # exactly once per committed token, every commit path (sync,
            # pipelined commit-behind, spec walk, prefill first token)
            # funnels here.  The mask already forced legality, so a failed
            # advance is a mask/automaton disagreement — the stall bug
            # class; fail the slot BEFORE the token reaches the stream or
            # the result (an illegal byte must never leave the engine).
            if not pending.constrain.advance(int(token)):
                self._fail_constraint_stall(slot)
                return 0
        if self.ec.telemetry:
            now = time.perf_counter()
            if pending.last_token_at:
                # inter-token interval (TPOT) — the decode-speed histogram
                self.telemetry.observe_tpot(now - pending.last_token_at,
                                            pending.priority)
            pending.last_token_at = now
        pending.generated.append(token)
        if ctx:
            pending.context.append(token)
        if pending.stream is not None:
            pending.stream.put(token)
        is_eos = token in self._stop_ids
        rc, new_page = self.batcher.commit_token_ex(slot, is_eos)
        if rc == 1:
            # mirror the growth (finished slots are zeroed in _finish, so
            # only the keep-decoding path needs it); _tok_host feeds the
            # next sync decode dispatch without a per-tick rebuild
            self._len_host[slot] += 1
            self._tok_host[slot] = token
            if new_page >= 0:
                idx = self._pages_for(int(self._len_host[slot])) - 1
                self._pt_host[slot, idx] = new_page
            return rc
        # finished (0) or page-pool OOM (-2): either way the slot frees; OOM
        # truncates the generation rather than deadlocking the pool
        self._finish(slot, rid, truncated=(rc == -2))
        return rc

    def _finish(self, slot: int, rid: int, truncated: bool,
                cancelled: bool = False, cache_ok: bool = True) -> None:
        self._check_epoch()
        with self._lock:  # cancel() resolves futures under this lock
            pending = self._requests.pop(rid, None)
            if pending is not None:
                self._future_rid.pop(pending.future, None)
            self._slot_req.pop(slot, None)
        if pending is None:
            # already failed out from under us (supervisor raced a stale
            # tick): just make sure the slot state is clean
            self._release_slot_state(slot)
            self.batcher.release(slot)
            return
        # session pin BEFORE the mirrors zero: the slot's page row and
        # committed length are what the snapshot reads
        session = None
        if pending.session_id is not None:
            session = self._pin_session(slot, pending, cache_ok)
        # disaggregation export BEFORE the mirrors zero, same reason: the
        # prefill phase's committed pages leave through the handoff store
        # (the pages ALSO release to the prefix cache below — a degraded
        # decode phase that lands back here re-prefills as a cache hit)
        handoff_rec = None
        if pending.handoff and not cancelled:
            handoff_rec = self._export_handoff(slot, pending, cache_ok)
        # fleet-fabric publish, same before-the-mirrors-zero window: the
        # finishing request's committed full-page prefix becomes pullable
        # by every other replica.  Handoff prefill phases skip it — their
        # pages already leave through the (one-shot) handoff store.
        if self._fabric is not None and not cancelled and not pending.handoff:
            if pending.brownout >= 3:
                # ingress brownout stage 3 (README "Overload control"):
                # publishing snapshots device pages to host — deferrable
                # work by definition; under a storm the pages still reach
                # the local prefix cache below, only the FLEET misses out
                # until pressure recedes
                self.telemetry.count_fabric("publish_deferred")
            else:
                self._publish_fabric(slot, pending, cache_ok)
        self._release_slot_state(slot)  # freed slots decode as zero adapter
        # hand the prompt's full pages to the prefix cache on the way out —
        # unless the prefill never finished (cancel mid-prefill): those pages
        # hold garbage and must not be served to other requests.  A
        # successfully pinned session's pages live in the tiered store
        # instead: releasing them to the device cache too would double-home
        # the bytes and make warm-tier attribution (host vs cache) racy
        release_hashes = pending.page_hashes if cache_ok else None
        if session is not None and session.get("pinned"):
            release_hashes = None
        self.batcher.release(slot, release_hashes)
        self._archive_span(pending, "cancelled" if cancelled else "done")
        now = time.perf_counter()
        result = {
            "rid": rid,
            "tokens": pending.generated,
            "num_tokens": len(pending.generated),
            "truncated": truncated,
            "cancelled": cancelled,
            "preemptions": pending.preemptions,
            "ttft_s": (pending.first_token_at - pending.submitted_at
                       if pending.first_token_at else 0.0),
            "latency_s": now - pending.submitted_at,
        }
        if handoff_rec is not None:
            result["handoff"] = handoff_rec
        if pending.fabric_restore is not None:
            result["fabric"] = {"restore": pending.fabric_restore}
        if pending.session_id is not None:
            # "evicted" is a COUNT, not the ids: session ids are bearer
            # capabilities (kvstore.normalize_session_id), so leaking
            # another client's id in this client's response would hand
            # over their conversation.  The full ids stay server-side
            # (store stats / flight events) for operators.
            result["session"] = {
                "id": pending.session_id,
                "restore": pending.session_restore or "cold",
                "pinned": bool(session and session.get("pinned")),
                "durable": bool(session and session.get("durable")),
                "evicted": len(session.get("evicted") or ()) if session else 0,
            }
            err = (session or {}).get("error") or (session or {}).get("reason")
            if err:
                result["session"]["error"] = err
        if pending.constrain is not None:
            # structured-output receipt (README "Structured output"):
            # "valid" == the automaton ACCEPTS the full generation (a
            # complete grammar-valid utterance); anything else — budget
            # cut, OOM truncation, client cancel — left a legal-but-
            # incomplete prefix and reports "truncated".  The serve layer
            # turns "valid" into the parsed json/tool_call payload.
            c = pending.constrain
            outcome = "valid" if c.accepting() else "truncated"
            self.telemetry.count_constrain(outcome)
            result["constrain"] = {
                "kind": c.kind,
                "outcome": outcome,
                "n_tokens": c.n_tokens,
                "n_bytes": c.n_bytes,
            }
            if c.tool_name is not None:
                result["constrain"]["tool"] = c.tool_name
        pending.future.set_result(result)
        if pending.stream is not None:
            pending.stream.put((None, result))

    def _export_handoff(self, slot: int, pending: _Pending,
                        cache_ok: bool) -> dict:
        """Disaggregated prefill phase, export half (README "Disaggregated
        serving"): snapshot the finishing request's committed KV pages —
        every page the slot owns, covering positions [0, L-2] where L =
        len(context) = prompt + first token (the last token's KV is
        written by the decode step that runs on the PULLING replica) —
        frame them KVPG/CRC via the kvstore wire format, and register the
        frame in the handoff store under a one-shot TTL'd handle.

        Degrades, never raises: any failure returns ``{"error": ...}``
        and the proxy falls back to the unified path (the pages still
        release to the prefix cache, so that fallback usually re-adopts
        them)."""
        if not cache_ok:
            self.telemetry.count_handoff("export_failed")
            return {"error": "incomplete prefill"}
        L = len(pending.context)
        owned = min(self._pages_for(L),
                    int(np.count_nonzero(self._pt_host[slot])))
        if L < 2 or owned <= 0:
            self.telemetry.count_handoff("export_failed")
            return {"error": "nothing committed to hand off"}
        t0 = time.perf_counter()
        try:
            row = np.ascontiguousarray(self._pt_host[slot, :owned])
            blob, _ = self._snapshot_pages(row)
            meta = {"resume_len": L, "page_size": self.ec.page_size,
                    "pages": owned, "adapter_id": pending.adapter_id,
                    "generated": list(pending.generated)}
            if self._mesh is not None:
                # shard-native wire frame: per-sub-frame CRCs, degree in
                # meta so the importer can verify layout compatibility
                meta["tp"] = self.ec.tensor_parallel
                data, nbytes, _ = pack_sharded_frame(
                    f"handoff/{pending.rid}", blob, meta)
            else:
                data, nbytes, _ = pack_frame(f"handoff/{pending.rid}",
                                             blob, meta)
            ttl = None
            if (self._handoff_chaos is not None
                    and self._handoff_chaos.expire_export()):
                ttl = 0.0  # chaos: the puller must find it expired
            handle = self._handoffs.put(data, meta, ttl_s=ttl)
            if handle is None:
                self.telemetry.count_handoff("export_failed")
                return {"error": "handoff store budget exhausted"}
            self.telemetry.count_handoff("export")
            if self.ec.telemetry:
                self._flight_event(
                    "handoff_export", [slot],
                    {"pages": owned, "bytes": nbytes, "resume_len": L},
                    t0, "ok")
            return {"handle": handle, "pages": owned, "nbytes": nbytes,
                    "resume_len": L,
                    "ttl_s": (self.ec.handoff_ttl_s if ttl is None
                              else ttl)}
        except Exception as exc:  # noqa: BLE001 — export must degrade
            self.telemetry.count_handoff("export_failed")
            if self.ec.telemetry:
                self._flight_event("handoff_export", [slot], None, t0,
                                   "error",
                                   error=f"{type(exc).__name__}: {exc}")
            return {"error": f"{type(exc).__name__}: {exc}"}

    def _publish_fabric(self, slot: int, pending: _Pending,
                        cache_ok: bool) -> None:
        """Fleet-fabric publish (README "Fleet KV fabric"): snapshot the
        finishing request's committed FULL pages — the session-pin
        geometry: positions [0, L-2], full pages only — frame them
        KVPG/CRC keyed by the prefix's deepest chain hash, and register
        the frame in the multi-reader FabricStore, where any replica can
        pull it via ``GET /engine/kv_fabric/<key>``.  The frame's meta
        carries the per-page chain hashes (the puller's correctness gate)
        and the text fingerprint ladder (the router's placement key).

        Degrades, never raises: a failed publish costs the FLEET a share,
        not this request anything — the pages still release to the local
        prefix cache right after."""
        if not cache_ok:
            return
        ps = self.ec.page_size
        L = int(self._len_host[slot])
        covered = max(0, (L - 1) // ps)
        covered = min(covered, int(np.count_nonzero(self._pt_host[slot])))
        if covered < max(1, self.ec.fabric_min_pages):
            return
        t0 = time.perf_counter()
        try:
            hashes = self._page_hashes(pending.context,
                                       pending.adapter_id)[:covered]
            key = fabric_key(hashes[-1])
            if self._fabric.covers(key, covered):
                # identical prefix already published and live: skip the
                # expensive device->host snapshot (the store check is the
                # cheap half by design)
                self.telemetry.count_fabric("publish_skipped")
                return
            fps = []
            if self.fabric_fingerprinter is not None:
                fps = self.fabric_fingerprinter(
                    pending.context[:covered * ps]) or []
            row = np.ascontiguousarray(self._pt_host[slot, :covered])
            blob, _ = self._snapshot_pages(row)
            meta = {"hashes": [int(h) for h in hashes], "pages": covered,
                    "page_size": ps, "adapter_id": pending.adapter_id,
                    "model": self.fabric_model_id, "fps": fps}
            if self._mesh is not None:
                meta["tp"] = self.ec.tensor_parallel
                data, nbytes, _ = pack_sharded_frame(f"fabric/{key}",
                                                     blob, meta)
            else:
                data, nbytes, _ = pack_frame(f"fabric/{key}", blob, meta)
            ttl = None
            if (self._fabric_chaos is not None
                    and self._fabric_chaos.expire_publish()):
                ttl = 0.0  # chaos: every later pull must find it expired
            ok = self._fabric.publish(key, data, meta, ttl_s=ttl)
            self.telemetry.count_fabric("publish" if ok
                                        else "publish_failed")
            if self.ec.telemetry:
                self._flight_event(
                    "fabric_publish", [slot],
                    {"key": key, "pages": covered, "bytes": nbytes},
                    t0, "ok" if ok else "rejected")
        except Exception as exc:  # noqa: BLE001 — publish must degrade
            self.telemetry.count_fabric("publish_failed")
            if self.ec.telemetry:
                self._flight_event("fabric_publish", [slot], None, t0,
                                   "error",
                                   error=f"{type(exc).__name__}: {exc}")

    def pull_fabric(self, key: str,
                    count_miss: bool = True) -> Optional[bytes]:
        """Serve one published prefix frame to a pulling replica
        (``GET /engine/kv_fabric/<key>``).  MULTI-READER: unlike a
        handoff handle, a fabric key is pulled as many times as the fleet
        wants — every reader past the first is the sharing the fabric
        exists for.  None on expired / unknown keys (the puller degrades
        to re-prefill).  ``count_miss=False``: a multi-model server
        probing every engine for the owner must not charge a miss to the
        ones that never published it."""
        if self._fabric is None:
            return None
        outcome, data = self._fabric.pull(key, count_miss=count_miss)
        if outcome != "miss" or count_miss:
            self.telemetry.count_fabric(
                {"ok": "pull", "expired": "expired",
                 "miss": "miss"}[outcome])
        if data is not None:
            self.telemetry.count_fabric_bytes("out", len(data))
        return data

    def fabric_view(self) -> list:
        """The placement-facing listing of this replica's live published
        prefixes (kvfabric.FabricStore.view) — rides the cache analytics
        block of ``GET /engine/perf`` into the proxy's ``/fleet/cache``
        view, which is what the router's cache-aware placement scores."""
        return self._fabric.view() if self._fabric is not None else []

    def pull_handoff(self, handle: str,
                     count_miss: bool = True) -> Optional[bytes]:
        """Serve one exported KV frame to a pulling decode replica
        (``GET /engine/kv_handoff/<handle>``).  One-shot: a second pull
        of the same handle is refused — after a failover re-dispatch the
        frame may already be scattered into another replica's pool, and
        two slots must not decode from one blob.  None on refused /
        expired / unknown handles (the puller degrades to re-prefill).
        ``count_miss=False``: a multi-model server probing every engine
        for the owner must not charge a miss to the ones that never
        exported it."""
        outcome, data = self._handoffs.pull(handle, count_miss=count_miss)
        if outcome != "miss" or count_miss:
            self.telemetry.count_handoff(
                {"ok": "pull", "refused": "pull_refused",
                 "expired": "expired", "miss": "miss"}[outcome])
        if data is not None:
            self.telemetry.count_handoff_bytes("out", len(data))
        return data

    def drop_handoff(self, handle: str) -> bool:
        """Discard an exported frame that will never be pulled (the
        prefill phase saw the generation complete on its only token) —
        frees the bytes immediately instead of at TTL expiry."""
        return self._handoffs.drop(handle)

    def _pin_session(self, slot: int, pending: _Pending,
                     cache_ok: bool) -> dict:
        """Park a finishing session turn's KV pages in the tiered store
        (README "Sessions & tiered KV"): snapshot every COMPLETE page of
        committed KV (positions [0, L-2] — the final token's KV is only
        written by the decode step that never runs) plus the context's
        chain hashes, so the next turn can verify byte-exact prefix
        identity before re-adopting.  Degrades, never raises."""
        sid = pending.session_id
        if not cache_ok:
            return {"pinned": False, "reason": "incomplete prefill"}
        ps = self.ec.page_size
        L = int(self._len_host[slot])
        covered = max(0, (L - 1) // ps)
        covered = min(covered, int(np.count_nonzero(self._pt_host[slot])))
        if covered == 0:
            self.telemetry.count_session_pin("rejected")
            return {"pinned": False,
                    "reason": "committed context shorter than one page"}
        t0 = time.perf_counter()
        try:
            row = np.ascontiguousarray(self._pt_host[slot, :covered])
            blob, nbytes = self._snapshot_pages(row)
            hashes = self._page_hashes(pending.context,
                                       pending.adapter_id)[:covered]
            meta = {"hashes": [int(h) for h in hashes],
                    "context_len": len(pending.context),
                    "adapter_id": pending.adapter_id,
                    "pages": covered}
            if self._mesh is not None:
                # per-shard list blobs flatten natively into the store's
                # version-1 page files; the degree rides in meta so a
                # restore at another degree reshards explicitly
                meta["tp"] = self.ec.tensor_parallel
            res = self._kv.pin_session(sid, blob, nbytes, meta)
        except Exception as exc:  # noqa: BLE001 — pin must not fail the turn
            self.telemetry.count_session_pin("rejected")
            if self.ec.telemetry:
                self._flight_event("session_pin", [slot], None, t0, "error",
                                   error=f"{type(exc).__name__}: {exc}")
            return {"pinned": False,
                    "reason": f"{type(exc).__name__}: {exc}"}
        self.telemetry.count_session_pin(
            "durable" if res.get("durable")
            else "pinned" if res.get("pinned") else "rejected")
        if self.ec.telemetry:
            self._flight_event(
                "session_pin", [slot],
                {"pages": covered, "bytes": res.get("nbytes"),
                 "durable": res.get("durable"),
                 "evicted": len(res.get("evicted") or ())},
                t0, "ok" if res.get("pinned") else "rejected")
        return res
