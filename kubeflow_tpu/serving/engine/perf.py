"""Performance introspection plane (ISSUE 11): FLOPs/MFU accounting,
goodput attribution, tick-phase timelines, cache analytics.

Every perf claim the engine has made so far was a black-box tokens/s
number: it could not say where a tick's time went, what a dispatch cost,
or what fraction of dispatched work was wasted on speculative rejects,
preemption recompute, or handoff re-prefill.  NanoFlow (PAPERS.md) names
exactly this per-iteration accounting gap as the first-order bottleneck
once kernels are tuned; this module is the measurement plane the MFU>=0.55
push (ROADMAP item 2) and the fleet KV fabric's cache-aware placement
(item 3) both read from.  Four parts:

  * ``FlopsModel`` — analytical per-dispatch FLOPs derived from the model
    config (model.DecoderConfig.matmul_flops_per_token /
    attn_flops_per_token): prefill ``[B, L]`` charged per ROW at the
    row's real length (padding is not work the model asked for), decode
    ``[B]`` at the row's context length, fused verify ``[B, K]`` at the
    row's real draft count, plus LoRA delta matmuls when an adapter table
    is loaded.  Matmul FLOPs only, mirroring bench.py's training-side
    accounting so serving and training MFU rows compare.

  * ``PerfLedger`` — the goodput ledger.  ONE charge API:
    ``charge(kind, flops, positions, reason)`` where ``reason=None``
    means useful (goodput) work and any other reason is waste —
    ``goodput + sum(waste) == dispatched`` holds BY CONSTRUCTION, not by
    reconciliation.  Waste reasons: ``spec_reject`` (verify positions
    whose drafts greedy rejected), ``preempt_recompute`` (re-prefill of a
    drop-preempted victim's already-computed context),
    ``handoff_degraded`` (a disaggregation import that fell back to
    re-prefill), ``failover_reprefill`` (an ingress failover re-admission
    re-prefilling tokens a dead replica already produced), ``tick_retry``
    (a failed/NaN dispatch whose work was discarded), ``pipeline_drop``
    (rows dispatched behind a finish/preempt and discarded by the
    commit-behind rid guard).  A rolling window over the charges derives
    ``engine_mfu_ratio`` (against the platform peak-FLOPs table) and
    ``engine_goodput_ratio`` at scrape time.

  * ``TickTimeline`` — per-tick phase segments (admit / prefill_dispatch /
    decode_dispatch / readback / commit_behind / drain) on the loop
    thread, kept in a bounded ring like the FlightRecorder: the "where
    did this tick's time go" answer a flat tick-duration histogram
    cannot give.

  * ``CacheStats`` + ``ProfileStore`` — prefix-cache hit/miss-by-reason
    counters with bounded per-prefix reuse counts (the fleet KV fabric's
    placement input), and the managed jax.profiler artifact store:
    capture dirs are capped in count AND bytes with oldest-first
    eviction and removed on ``Engine.stop()`` (pre-ISSUE-11 they
    accumulated unbounded across engine lifecycles).

Served as ``GET /engine/perf`` (JSON snapshot) and /metrics gauges;
the service proxy aggregates per-replica cache views into
``GET /fleet/cache`` (router.py) — the read-only global cache state
ROADMAP item 3's router placement will consume.
"""

from __future__ import annotations

import collections
import os
import shutil
import threading
import time
from typing import Optional

# canonical waste-attribution reasons (README "Performance introspection")
WASTE_REASONS = ("spec_reject", "preempt_recompute", "handoff_degraded",
                 "fabric_degraded", "failover_reprefill", "tick_retry",
                 "pipeline_drop")

# dispatch kinds the ledger buckets by
DISPATCH_KINDS = ("prefill", "decode", "verify")

# tick-phase segment names (TickTimeline)
TIMELINE_PHASES = ("admit", "prefill_dispatch", "decode_dispatch",
                   "readback", "commit_behind", "drain")


# --------------------------------------------------- platform peak-FLOPs table

def _cpu_peak_estimate() -> float:
    """Order-of-magnitude peak for the CPU fallback: cores x ~3GHz x 16
    f32 FLOPs/cycle (one 256-bit FMA pipe).  Deliberately coarse — a CPU
    MFU row exists so the accounting path is exercised end to end, not as
    a benchmark claim; the README says so."""
    return max(1, os.cpu_count() or 1) * 3.0e9 * 16


def platform_peak_flops(backend: str, device_kind: str = "",
                        n_devices: int = 1) -> tuple:
    """-> (platform_label, peak_flops) for MFU math.

    TPU backends resolve through scheduler.topology.VARIANTS (the same
    per-chip bf16 peaks the training bench divides by, so a
    chip_opportunist drain gets serving MFU rows consistent with the
    mfu_sweep rows for free); unknown TPU kinds fall back to v5e rather
    than refusing to serve.  A tensor-parallel engine passes its mesh
    degree as ``n_devices``: the TPU peak multiplies per chip (N chips of
    silicon really do offer N× the FLOPs — charging a TP=4 engine against
    one chip's peak would report 4× the honest MFU) and the label gains
    an ``xN`` suffix so per-mesh rows are distinguishable in snapshots.
    The CPU fallback keeps the HOST-wide estimate un-multiplied — the
    forced multi-device CPU mesh is virtual, every "device" shares the
    same cores — but still annotates the degree.  ``ENGINE_PEAK_FLOPS``
    overrides the value (label gains a ``!`` so a doctored denominator is
    visible in every snapshot)."""
    env = os.environ.get("ENGINE_PEAK_FLOPS")
    if backend == "tpu":
        from ...scheduler.topology import VARIANTS, variant_for_device_kind

        try:
            variant = variant_for_device_kind(device_kind)
        except KeyError:
            variant = "v5e"
        label = f"tpu-{variant}"
        peak = VARIANTS[variant].flops_bf16 * max(1, n_devices)
    else:
        label = backend or "cpu"
        peak = _cpu_peak_estimate()
    if n_devices > 1:
        label += f"x{n_devices}"
    if env:
        try:
            peak = float(env)
            label += "!"
        except ValueError:
            pass
    return label, peak


# ------------------------------------------------------------------ FLOPs model

class FlopsModel:
    """Analytical per-dispatch FLOPs from the decoder config.

    All methods return FLOPs for ONE batch row; the engine sums rows per
    dispatch (mask-aware: a padded [B, bucket] prefill charges each row
    at its real prompt length — padding lanes are machine work but not
    work the request asked for, and charging them would let bucket
    geometry inflate goodput)."""

    def __init__(self, config, lora=None):
        self.lin = config.matmul_flops_per_token()
        # attention flops per token = slope * context
        self.attn_slope = config.attn_flops_per_token(1)
        # LoRA delta matmuls (lora.py fused path): per adapted projection
        # per layer per token, x@A (2*d_in*r) + (xA)@B (2*r*d_out).  The
        # fused decode computes the delta for EVERY row when a table is
        # loaded (row 0 is the zero adapter), so the per-token constant
        # applies to all rows of an adapter-enabled engine.
        extra = 0
        if lora:
            for proj in lora.values():
                A, B = proj["A"], proj["B"]
                n_layers, d_in, r = A.shape[1], A.shape[2], A.shape[3]
                d_out = B.shape[3]
                extra += n_layers * 2 * r * (d_in + d_out)
        self.lora = extra
        self.per_token = self.lin + self.lora

    def prefill_row(self, tokens: int, history: int = 0) -> float:
        """One row advancing ``tokens`` prompt positions that attend over
        ``history`` prior positions (chunked prefill passes the chunk
        offset); causal attention inside the new span."""
        if tokens <= 0:
            return 0.0
        # sum_{p=history+1..history+tokens} attn(p)
        attn = self.attn_slope * (tokens * history
                                  + tokens * (tokens + 1) // 2)
        return tokens * self.per_token + attn

    def decode_row(self, context: int) -> float:
        """One decode position attending over ``context`` positions."""
        return self.per_token + self.attn_slope * max(0, context)

    def verify_row(self, context: int, k: int) -> float:
        """One fused-verify row: ``k`` positions (committed token + k-1
        drafts) each attending ~``context`` (the per-position growth
        inside one pass is noise)."""
        return k * self.decode_row(context)


# --------------------------------------------------------------- goodput ledger

class PerfLedger:
    """FLOPs ledger with exact waste attribution.

    ``charge(kind, flops, positions, reason)`` is the only mutation:
    reason None -> goodput, else the named waste bucket — so
    ``dispatched == goodput + sum(waste)`` is an identity, never a
    reconciliation.  A bounded rolling window of charges derives MFU and
    goodput ratios at read time (scrape-time math, O(window))."""

    def __init__(self, peak_flops: float, platform: str,
                 window_s: float = 60.0, on_charge=None):
        self.peak_flops = max(1.0, float(peak_flops))
        self.platform = platform
        self.window_s = float(window_s)
        self._on_charge = on_charge  # telemetry hook (counter exposition)
        self._lock = threading.Lock()
        self.flops_by_kind = {k: 0.0 for k in DISPATCH_KINDS}
        self.positions_by_kind = {k: 0 for k in DISPATCH_KINDS}
        self.goodput_flops = 0.0
        self.goodput_positions = 0
        self.waste_flops = {}
        self.waste_positions = {}
        # (t, flops, goodput_flops) — bounded by count as well as age so a
        # charge storm cannot grow the deque faster than reads trim it
        self._window: collections.deque = collections.deque(maxlen=4096)

    def charge(self, kind: str, flops: float, positions: int = 0,  # graftlint: hot-path
               reason: Optional[str] = None) -> None:
        if flops <= 0:
            return
        with self._lock:
            self.flops_by_kind[kind] = self.flops_by_kind.get(kind, 0.0) + flops
            self.positions_by_kind[kind] = (
                self.positions_by_kind.get(kind, 0) + positions)
            if reason is None:
                self.goodput_flops += flops
                self.goodput_positions += positions
                good = flops
            else:
                self.waste_flops[reason] = (
                    self.waste_flops.get(reason, 0.0) + flops)
                self.waste_positions[reason] = (
                    self.waste_positions.get(reason, 0) + positions)
                good = 0.0
            self._window.append((time.perf_counter(), flops, good))
        if self._on_charge is not None:
            self._on_charge(kind, flops, reason)

    def _window_sums(self, now: float) -> tuple:
        """(dispatched, goodput, span_s) over the rolling window; caller
        holds the lock."""
        horizon = now - self.window_s
        w = self._window
        while w and w[0][0] < horizon:
            w.popleft()
        if not w:
            return 0.0, 0.0, 0.0
        disp = sum(f for _, f, _ in w)
        good = sum(g for _, _, g in w)
        span = max(now - w[0][0], 1e-9)
        return disp, good, span

    def mfu(self) -> float:
        """Windowed model FLOPs utilization vs the platform peak."""
        with self._lock:
            disp, _, span = self._window_sums(time.perf_counter())
        if span <= 0:
            return 0.0
        return disp / span / self.peak_flops

    def goodput_ratio(self) -> float:
        """Windowed goodput / dispatched (1.0 when nothing dispatched —
        an idle engine wastes nothing)."""
        with self._lock:
            disp, good, _ = self._window_sums(time.perf_counter())
        return good / disp if disp > 0 else 1.0

    def snapshot(self) -> dict:
        with self._lock:
            dispatched = sum(self.flops_by_kind.values())
            waste = dict(self.waste_flops)
            out = {
                "platform": self.platform,
                "peak_flops": self.peak_flops,
                "window_s": self.window_s,
                "dispatched_flops": dispatched,
                "flops_by_kind": dict(self.flops_by_kind),
                "positions_by_kind": dict(self.positions_by_kind),
                "goodput_flops": self.goodput_flops,
                "goodput_positions": self.goodput_positions,
                "waste_flops": waste,
                "waste_positions": dict(self.waste_positions),
                # identity by construction; exported so every consumer
                # (tests, benches, dashboards) can assert it for free
                "accounted_flops": self.goodput_flops + sum(waste.values()),
            }
        out["mfu"] = round(self.mfu(), 6)
        out["goodput_ratio"] = round(self.goodput_ratio(), 6)
        return out


# ----------------------------------------------------------------tick timeline

class TickTimeline:
    """Bounded ring of per-tick phase segments.

    ``note(tick, phase, dur_s)`` is called from the engine loop only
    (same single-writer discipline as the host mirrors); ``snapshot``
    copies under the lock.  A tick's record accumulates segment time by
    phase — repeated segments (several prefill groups in one tick) sum."""

    def __init__(self, capacity: int = 256):
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._cur_tick = -1
        self._cur: Optional[dict] = None

    def note(self, tick: int, phase: str, dur_s: float) -> None:
        with self._lock:
            if tick != self._cur_tick or self._cur is None:
                self._cur = {"tick": tick, "t_s": round(time.perf_counter(), 6),
                             "segments": {}}
                self._cur_tick = tick
                self._ring.append(self._cur)
            seg = self._cur["segments"]
            seg[phase] = round(seg.get(phase, 0.0) + dur_s, 9)

    def snapshot(self, last: int = 32) -> list:
        with self._lock:
            items = list(self._ring)[-max(0, last):]
            return [{"tick": r["tick"], "t_s": r["t_s"],
                     "segments": dict(r["segments"])} for r in items]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# --------------------------------------------------------------- cache analytics

class CacheStats:
    """Prefix-cache lookup outcomes + bounded per-prefix reuse counts.

    Fed at admission (the one point where requested-vs-granted cache
    pages are both known): ``hit`` pages counted per lookup, misses
    attributed ``cold`` (no page matched) or ``partial`` (the chain
    diverged / aged out mid-prefix).  Reuse counts key on the deepest
    matched chain hash — chain hashing makes that a unique identity for
    the whole reused prefix (a popular system prompt shows up as one hot
    key), bounded LRU so a high-cardinality workload cannot grow it.
    Each entry also keeps the prefix's PAGE COUNT (the deepest hit depth
    seen under that key): the fleet KV fabric's placement scorer weighs
    bytes saved per reuse, not just hit counts — two prefixes with equal
    reuse but 2 vs 20 pages are very different placement prizes."""

    _REUSE_CAP = 512

    def __init__(self):
        self._lock = threading.Lock()
        self.lookups = 0
        self.hit_pages = 0
        self.miss_pages = {"cold": 0, "partial": 0}
        # key -> [reuses, pages]; insertion order is the LRU order
        self._reuse: "collections.OrderedDict[str, list]" = \
            collections.OrderedDict()

    def note_lookup(self, requested: int, hit: int,
                    key: Optional[int]) -> None:
        if requested <= 0:
            return
        hit = max(0, min(hit, requested))
        with self._lock:
            self.lookups += 1
            self.hit_pages += hit
            if hit < requested:
                reason = "partial" if hit > 0 else "cold"
                self.miss_pages[reason] += requested - hit
            if hit > 0 and key is not None:
                k = f"{int(key):016x}"
                rec = self._reuse.pop(k, None) or [0, 0]
                rec[0] += 1
                rec[1] = max(rec[1], hit)
                self._reuse[k] = rec
                while len(self._reuse) > self._REUSE_CAP:
                    self._reuse.popitem(last=False)

    def snapshot(self, top: int = 16) -> dict:
        with self._lock:
            hot = sorted(self._reuse.items(), key=lambda kv: -kv[1][0])[:top]
            return {
                "lookups": self.lookups,
                "hit_pages": self.hit_pages,
                "miss_pages": dict(self.miss_pages),
                "tracked_prefixes": len(self._reuse),
                "top_reused_prefixes": [
                    {"prefix": k, "reuses": v[0], "pages": v[1]}
                    for k, v in hot],
            }


# ------------------------------------------------------- profiler artifact store

def _dir_nbytes(path: str) -> int:
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


class ProfileStore:
    """Managed jax.profiler capture dirs: capped in count AND bytes with
    oldest-first eviction, removed on ``Engine.stop()``.

    Pre-ISSUE-11, ``trace_n_ticks`` wrote wherever the caller pointed and
    nothing ever cleaned up — a profiling soak (or a restart loop that
    re-profiles on every incident) grew artifact dirs without bound
    across engine lifecycles.  Mirrors the FlightRecorder dump cap: the
    store only deletes dirs IT created (``new_dir``); explicit
    caller-owned dirs are recorded in the run history (entry-capped) but
    never deleted out from under their owner."""

    def __init__(self, parent: Optional[str] = None, max_runs: int = 8,
                 max_bytes: int = 256 << 20):
        import secrets
        import tempfile

        self.parent = (parent or os.environ.get("ENGINE_PROFILE_DIR")
                       or os.path.join(tempfile.gettempdir(),
                                       f"engine_profiles-{os.getpid()}"))
        self.max_runs = max(1, max_runs)
        self.max_bytes = max(1, max_bytes)
        self._lock = threading.Lock()
        self._seq = 0
        # per-STORE token in every capture dir name: several engines in
        # one process share the per-pid parent, and a bare pid+seq name
        # would collide — one engine's eviction/stop would then rmtree a
        # directory another engine is still capturing into
        self._token = secrets.token_hex(4)
        # run records, oldest first: {dir, managed, ticks, requested_at,
        # nbytes (filled at completion), state}
        self.runs: list = []

    def new_dir(self) -> str:
        with self._lock:
            self._seq += 1
            d = os.path.join(
                self.parent,
                f"capture-{os.getpid()}-{self._token}-{self._seq:03d}")
        os.makedirs(d, exist_ok=True)
        return d

    def begin(self, trace_dir: str, ticks: int, managed: bool) -> dict:
        rec = {"dir": trace_dir, "managed": managed, "ticks": ticks,
               "requested_at": time.time(), "nbytes": 0,
               "state": "capturing"}
        with self._lock:
            self.runs.append(rec)
        return rec

    def discard(self, rec: dict) -> None:
        """Un-register a run whose capture never armed (the profiler
        refused it): the record leaves the history and a managed dir is
        removed — no orphan 'capturing' entries."""
        with self._lock:
            if rec in self.runs:
                self.runs.remove(rec)
        if rec["managed"]:
            shutil.rmtree(rec["dir"], ignore_errors=True)

    def complete(self, rec: dict, error: Optional[str] = None) -> None:
        """Capture finished (engine loop thread): size the artifacts and
        evict past the count/byte caps, oldest managed run first."""
        rec["nbytes"] = _dir_nbytes(rec["dir"])
        rec["state"] = "error" if error else "complete"
        if error:
            rec["error"] = error
        with self._lock:
            self._evict_locked()

    def _evict_locked(self) -> None:
        def total() -> int:
            return sum(r["nbytes"] for r in self.runs)

        while self.runs and (len(self.runs) > self.max_runs
                             or total() > self.max_bytes):
            # oldest first; never evict a capture still in flight
            victim = next((r for r in self.runs
                           if r["state"] != "capturing"), None)
            if victim is None:
                break
            self.runs.remove(victim)
            if victim["managed"]:
                shutil.rmtree(victim["dir"], ignore_errors=True)

    def close(self) -> None:
        """Engine.stop(): managed capture dirs die with the engine —
        profiles are scratch diagnostics, and nothing would ever reap
        them once the process moves on (explicit caller dirs survive)."""
        with self._lock:
            for r in self.runs:
                if r["managed"]:
                    shutil.rmtree(r["dir"], ignore_errors=True)
            self.runs.clear()
        try:
            # several engines in one process share the parent: remove it
            # only once the LAST one's captures are gone
            os.rmdir(self.parent)
        except OSError:
            pass

    def snapshot(self) -> list:
        with self._lock:
            return [dict(r) for r in self.runs]
