"""HuggingFace checkpoint → engine params converter (Llama-family).

Role (SURVEY.md §2a KServe storage-initializer row, §0 benchmark configs):
upstream users serve `hf://meta-llama/Meta-Llama-3-8B` through KServe's
huggingfaceserver; a user switching to this framework holds the same
safetensors checkpoints.  This module maps them onto the JetStream-class
engine's param dict (model.py: wq/wk/wv/wo, w1/w2/w3, ln_*, embed/unembed)
so `InferenceService` + `storage_uri` pointing at an HF checkout "just
serves" — serve.py auto-converts on load when it finds an HF-format
config.json without engine params.

Scope: Llama-architecture models (llama / llama2 / llama3 / mistral —
RMSNorm + RoPE + SwiGLU + optional GQA, incl. Nemo-style decoupled
head_dim) and Gemma-1, whose block deltas the engine's config flags
express (GeGLU via act="gelu_tanh", sqrt(d_model) input-embedding
scaling, explicit head_dim) with the (1+w) norms folded into the stored
weights here.  Conversion is otherwise a pure weight relayout: HF stores
projections as [out, in] torch tensors; the engine right-multiplies, so
every projection transposes, and per-layer tensors stack into one
[L, ...] array (jit-friendly: one HBM buffer per name).  Architectures
with block math the engine does NOT implement (gemma-2/3 softcapping,
phi's partial rotary, rope_scaling, non-tanh GeLU) are rejected loudly
rather than converted wrong.
"""

from __future__ import annotations

import gc
import json
import os
import sys

import numpy as np

_LLAMA_TYPES = {"llama", "mistral"}
# Gemma-1: same projection layout, three block deltas the engine's config
# flags express (GeGLU activation, sqrt(d_model) input-embedding scaling,
# decoupled head_dim) plus (1+w) norms folded into the weights at
# conversion.  gemma2/gemma3 add softcapping / sliding-window / extra
# norms the engine does NOT implement — rejected, not approximated.
_GEMMA_TYPES = {"gemma"}


def is_hf_config(raw: dict) -> bool:
    """True if a config.json dict is a transformers config (not ours).
    HF configs always carry model_type/architectures; ours never do."""
    return "model_type" in raw or "architectures" in raw


def hf_dir_needs_conversion(model_dir: str) -> bool:
    """True while config.json is still HF-format.  config.json is the ONE
    gate — it is written LAST (atomically) by convert_hf_checkpoint, so a
    crash anywhere mid-conversion leaves it HF-format and conversion
    simply re-runs on the next load.  (Keying on params.npz existence
    would wedge a dir whose crash landed between the two writes: convert
    skipped, from_dir raising, forever.)"""
    cfg = os.path.join(model_dir, "config.json")
    if not os.path.exists(cfg):
        return False
    with open(cfg) as f:
        try:
            raw = json.load(f)
        except ValueError:
            return False
    return is_hf_config(raw)


def _map_config(raw: dict) -> dict:
    mt = raw.get("model_type", "")
    if mt not in _LLAMA_TYPES | _GEMMA_TYPES:
        raise ValueError(
            f"unsupported model_type {mt!r}: the engine decoder implements "
            f"the Llama block (+ Gemma-1's flagged deltas); supported: "
            f"{sorted(_LLAMA_TYPES | _GEMMA_TYPES)}.  Models with different "
            "block math (gemma2's softcapping, phi's partial rotary, ...) "
            "must not be silently mis-converted.")
    if raw.get("rope_scaling"):
        # llama-3.1+ long-context scaling changes the RoPE frequencies; the
        # engine applies plain theta-RoPE, so converting would produce
        # numerically wrong generations with no error — reject loudly
        raise ValueError(
            f"rope_scaling={raw['rope_scaling']!r} is not implemented in "
            "the engine's RoPE; refusing to convert to silently-wrong "
            "frequencies (base Llama-3 / Llama-2 / Mistral / Gemma work)")
    implied_hd = raw["hidden_size"] // raw["num_attention_heads"]
    explicit_hd = raw.get("head_dim") or implied_hd  # None = derive
    out = {
        "vocab_size": raw["vocab_size"],
        "d_model": raw["hidden_size"],
        "n_layers": raw["num_hidden_layers"],
        "n_heads": raw["num_attention_heads"],
        "n_kv_heads": raw.get("num_key_value_heads",
                              raw["num_attention_heads"]),
        "d_ff": raw["intermediate_size"],
        "rope_theta": float(raw.get("rope_theta", 10000.0)),
        "norm_eps": float(raw.get("rms_norm_eps", 1e-5)),
    }
    if "eos_token_id" in raw:
        # passthrough: DecoderConfig ignores it, but serve.py's EOS
        # fallback reads it — conversion overwrites the HF config.json,
        # so a checkout declaring eos only there must not lose it
        out["eos_token_id"] = raw["eos_token_id"]
    if mt in _GEMMA_TYPES:
        # only the tanh-approx GeLU is implemented: explicit
        # hidden_activation="gelu" (erf) or "gelu_new" would silently
        # diverge if mapped onto tanh — reject, never approximate.
        # (hidden_activation unset means transformers forces
        # gelu_pytorch_tanh regardless of the legacy hidden_act field.)
        act = raw.get("hidden_activation")
        if act not in (None, "gelu_pytorch_tanh"):
            raise ValueError(f"gemma hidden_activation {act!r} is not the "
                             "tanh-approx GeLU the engine implements")
        out.update(head_dim_override=explicit_hd, act="gelu_tanh",
                   scale_embed=True)
    elif explicit_hd != implied_hd:
        # Mistral-Nemo-class: head_dim decoupled from hidden/heads (e.g.
        # 128 with 5120/32=160) — expressible since head_dim_override
        out["head_dim_override"] = explicit_hd
    return out


class _LazyTensors:
    """name -> numpy array, materialized one tensor at a time.

    Eagerly loading every shard costs a full extra model copy in host RAM
    next to the stacked output (8B ≈ +16-32GB) — instead keep safetensors
    handles open and read each tensor when the mapper asks for it.  The
    torch-bin fallback has no lazy API; it loads eagerly (legacy path)."""

    def __init__(self, src_dir: str):
        import glob

        self._by_name: dict = {}     # name -> (safe_open handle) or ndarray
        self._handles: list = []
        shards = sorted(glob.glob(os.path.join(src_dir, "*.safetensors")))
        if shards:
            from safetensors import safe_open

            for shard in shards:
                f = safe_open(shard, framework="np")
                self._handles.append(f)
                for name in f.keys():
                    self._by_name[name] = f
            return
        bins = sorted(glob.glob(os.path.join(src_dir, "pytorch_model*.bin")))
        if not bins:
            raise FileNotFoundError(
                f"no *.safetensors or pytorch_model*.bin in {src_dir}")
        import torch

        for b in bins:
            sd = torch.load(b, map_location="cpu", weights_only=True)
            for name, t in sd.items():
                self._by_name[name] = t.float().numpy()

    def pop(self, name):
        if name not in self._by_name:
            # a checkout whose shards hold fewer tensors/layers than its
            # config claims should fail with the tensor name, not a raw
            # KeyError from deep inside the mapper
            raise ValueError(
                f"checkpoint is missing tensor {name!r} (config declares "
                "more layers/weights than the shards contain)")
        src = self._by_name.pop(name)
        if isinstance(src, np.ndarray):
            return src
        return src.get_tensor(name)

    def close(self) -> None:
        for h in self._handles:
            try:
                h.__exit__(None, None, None)  # safe_open's only close API
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
        self._handles = []

    def __contains__(self, name) -> bool:
        return name in self._by_name

    def remaining(self) -> list:
        return sorted(self._by_name)


_PER_LAYER = {
    # engine name -> (HF suffix, transpose)
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "w1": ("mlp.gate_proj.weight", True),
    "w3": ("mlp.up_proj.weight", True),
    "w2": ("mlp.down_proj.weight", True),
    "ln_attn": ("input_layernorm.weight", False),
    "ln_mlp": ("post_attention_layernorm.weight", False),
}


def _map_tensors(tensors: "_LazyTensors", cfg: dict, raw: dict, store) -> dict:
    """Map every checkpoint tensor into the engine's layer-stacked layout;
    raises on missing/unmapped/non-finite weights (see convert docstring)."""

    def grab(name, transpose=False):
        """One tensor, downcast to the storage dtype immediately — only one
        fp32 tensor is ever alive, keeping peak RAM ~1x model size."""
        t = np.asarray(tensors.pop(name), np.float32)
        t = (t.T if transpose else t).astype(store)
        if not np.isfinite(t).all():
            # fp16 storage has a narrower exponent range than bf16: an
            # outlier weight > 65504 becomes inf here and NaN logits at
            # serve time — fail at conversion, where it is attributable
            raise ValueError(f"{name} has non-finite values after casting "
                             f"to {np.dtype(store).name} (outlier weight "
                             "beyond the storage dtype's range)")
        return t

    out = {"embed": grab("model.embed_tokens.weight")}
    for ours, (suffix, transpose) in _PER_LAYER.items():
        out[ours] = np.stack([
            grab(f"model.layers.{l}.{suffix}", transpose)
            for l in range(cfg["n_layers"])])
        gc.collect()
    out["ln_out"] = grab("model.norm.weight")
    if raw.get("model_type") in _GEMMA_TYPES:
        # gemma's RMSNorm multiplies by (1 + w); folding the +1 into the
        # stored weights keeps the runtime norm shared with llama
        for k in ("ln_attn", "ln_mlp", "ln_out"):
            out[k] = (out[k].astype(np.float32) + 1.0).astype(store)
    if "lm_head.weight" in tensors:
        out["unembed"] = grab("lm_head.weight", transpose=True)
    else:  # tied embeddings (gemma, llama3.2-1b, and most tiny test configs)
        out["unembed"] = out["embed"].T.copy()
    leftovers = [n for n in tensors.remaining() if "rotary_emb" not in n]
    if leftovers:
        raise ValueError(f"unmapped checkpoint tensors: {leftovers[:8]} — "
                         "refusing to drop weights silently")
    return out


def convert_hf_checkpoint(src_dir: str, out_dir: str,
                          dtype: str = "bfloat16") -> dict:
    """Convert an HF Llama-family checkout into ``out_dir`` (config.json +
    params.npz in the engine's format).  Returns the engine config dict.

    ``dtype``: storage dtype for params.npz — "bfloat16" (default; stored
    as float16, whose 10-bit mantissa strictly covers bf16's 7 — numpy's
    npz loader can't round-trip ml_dtypes.bfloat16) or "float32" (parity
    testing).  load_params casts to bf16 on load either way."""
    if dtype not in ("bfloat16", "float32"):
        raise ValueError(f"dtype must be 'bfloat16' or 'float32', got {dtype!r}")
    with open(os.path.join(src_dir, "config.json")) as f:
        raw = json.load(f)
    cfg = _map_config(raw)
    store = np.float32 if dtype == "float32" else np.float16

    tensors = _LazyTensors(src_dir)
    try:
        out = _map_tensors(tensors, cfg, raw, store)
    finally:
        tensors.close()

    # params FIRST, config LAST, both atomic: config.json is the one gate
    # hf_dir_needs_conversion reads, so a crash anywhere before the final
    # replace leaves the dir still recognized as unconverted and the next
    # load re-runs conversion.  (Config-first would make a later load fall
    # back to RANDOM params and serve garbage.)
    os.makedirs(out_dir, exist_ok=True)
    tmp = os.path.join(out_dir, "params.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **out)
    os.replace(tmp, os.path.join(out_dir, "params.npz"))
    tmp_cfg = os.path.join(out_dir, "config.json.tmp")
    with open(tmp_cfg, "w") as f:
        json.dump(cfg, f, indent=1)
    os.replace(tmp_cfg, os.path.join(out_dir, "config.json"))
    return cfg


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) not in (2, 3):
        print("usage: python -m kubeflow_tpu.serving.engine.hf_convert "
              "SRC_HF_DIR OUT_DIR [float32|bfloat16]", file=sys.stderr)
        return 2
    cfg = convert_hf_checkpoint(argv[0], argv[1],
                                argv[2] if len(argv) > 2 else "bfloat16")
    print(json.dumps(cfg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
