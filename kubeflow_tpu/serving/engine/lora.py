"""Multi-LoRA adapter loading for the serving engine (PEFT format).

Role (SURVEY.md §2b Triton row — "don't stop at parity"): JetStream-class
servers multiplex many fine-tunes over one set of base weights by keeping
per-request low-rank deltas; upstream's huggingfaceserver users bring PEFT
adapter checkouts.  This module loads ``model_dir/adapters/<name>/`` PEFT
directories (adapter_config.json + adapter_model.safetensors) into ONE
stacked pytree the batched decode consumes:

    {proj: {"A": [n_adapters+1, L, in, r], "B": [n_adapters+1, L, r, out]}}

Adapter id 0 is reserved all-zeros ("no adapter"), so a mixed batch needs
no branching — every row pays two rank-r matmuls (model._proj), and rows
without an adapter multiply by zeros.  Adapters with different ranks are
right-padded to the max rank (zero A columns x zero B rows contribute
nothing).  The PEFT scale (lora_alpha / r) is folded into B at load time.
"""

from __future__ import annotations

import json
import os

import numpy as np

# PEFT target_modules name -> engine param name
_PROJ_MAP = {"q_proj": "wq", "k_proj": "wk", "v_proj": "wv", "o_proj": "wo",
             "gate_proj": "w1", "up_proj": "w3", "down_proj": "w2"}


def _read_peft_dir(path: str) -> tuple[dict, dict]:
    """One adapter dir -> (config dict, {(proj, layer): (A [r,in], B [out,r])})."""
    with open(os.path.join(path, "adapter_config.json")) as f:
        cfg = json.load(f)
    if cfg.get("peft_type", "LORA").upper() != "LORA":
        raise ValueError(f"{path}: unsupported peft_type {cfg.get('peft_type')!r}")
    if cfg.get("use_dora") or cfg.get("use_rslora"):
        raise ValueError(f"{path}: DoRA/rsLoRA variants are not implemented — "
                         "refusing to load with silently-wrong scaling")
    if cfg.get("rank_pattern") or cfg.get("alpha_pattern"):
        # per-module rank/alpha overrides change the scale per projection;
        # applying the global alpha/r to them would be silently wrong
        raise ValueError(f"{path}: rank_pattern/alpha_pattern overrides are "
                         "not implemented — refusing to mis-scale them")
    st = os.path.join(path, "adapter_model.safetensors")
    if not os.path.exists(st):
        raise FileNotFoundError(f"{path}: adapter_model.safetensors missing")
    from safetensors import safe_open

    pairs: dict = {}
    with safe_open(st, framework="np") as f:
        names = list(f.keys())
        for name in names:
            # ...model.layers.{l}.self_attn.q_proj.lora_A.weight
            parts = name.split(".")
            try:
                li = parts.index("layers")
                layer = int(parts[li + 1])
                proj = parts[li + 3]
                which = parts[li + 4]  # lora_A | lora_B
            except (ValueError, IndexError):
                raise ValueError(f"{path}: unrecognized tensor name {name!r}")
            if proj not in _PROJ_MAP:
                raise ValueError(f"{path}: target module {proj!r} is not a "
                                 f"decoder projection ({sorted(_PROJ_MAP)})")
            key = (_PROJ_MAP[proj], layer)
            a, b = pairs.get(key, (None, None))
            t = np.asarray(f.get_tensor(name), np.float32)
            if which == "lora_A":
                a = t  # [r, in]
            elif which == "lora_B":
                b = t  # [out, r]
            else:
                raise ValueError(f"{path}: unexpected component {which!r} in {name!r}")
            pairs[key] = (a, b)
    for key, (a, b) in pairs.items():
        if a is None or b is None:
            raise ValueError(f"{path}: incomplete A/B pair for {key}")
    return cfg, pairs


def load_adapters(model_dir: str, config) -> tuple:
    """Scan ``model_dir/adapters/*/`` -> (lora_params | None, {name: id}).

    ``config``: the engine DecoderConfig (shapes to validate against).
    Ids are 1-based (0 = the reserved zero adapter); names are the
    directory names, sorted for determinism.
    """
    root = os.path.join(model_dir, "adapters") if model_dir else ""
    if not root or not os.path.isdir(root):
        return None, {}
    names = sorted(d for d in os.listdir(root)
                   if os.path.isdir(os.path.join(root, d)))
    if not names:
        return None, {}

    dims = {"wq": (config.d_model, config.n_heads * config.head_dim),
            "wk": (config.d_model, config.n_kv_heads * config.head_dim),
            "wv": (config.d_model, config.n_kv_heads * config.head_dim),
            "wo": (config.n_heads * config.head_dim, config.d_model),
            "w1": (config.d_model, config.d_ff),
            "w3": (config.d_model, config.d_ff),
            "w2": (config.d_ff, config.d_model)}
    L = config.n_layers
    loaded = []  # (name, scale, pairs)
    for name in names:
        cfg, pairs = _read_peft_dir(os.path.join(root, name))
        r = int(cfg.get("r", 8))
        scale = float(cfg.get("lora_alpha", r)) / r
        for (proj, layer), (a, b) in pairs.items():
            din, dout = dims[proj]
            if layer >= L or a.shape[1] != din or b.shape[0] != dout:
                raise ValueError(
                    f"adapter {name!r}: {proj} layer {layer} shapes "
                    f"A{a.shape} B{b.shape} do not match the base model "
                    f"(in={din}, out={dout}, layers={L})")
            if a.shape[0] != r:
                # scale is alpha/r from the config; a tensor whose actual
                # rank disagrees would be applied at the wrong magnitude
                raise ValueError(
                    f"adapter {name!r}: {proj} layer {layer} has rank "
                    f"{a.shape[0]} but adapter_config.json says r={r}")
        loaded.append((name, scale, pairs))

    projs = sorted({proj for _, _, pairs in loaded for (proj, _) in pairs})
    max_r = max(a.shape[0] for _, _, pairs in loaded for (a, _) in pairs.values())
    n = len(loaded)
    import jax.numpy as jnp

    out = {}
    for proj in projs:
        din, dout = dims[proj]
        A = np.zeros((n + 1, L, din, max_r), np.float32)
        B = np.zeros((n + 1, L, max_r, dout), np.float32)
        for i, (name, scale, pairs) in enumerate(loaded, start=1):
            for (p, layer), (a, b) in pairs.items():
                if p != proj:
                    continue
                r = a.shape[0]
                A[i, layer, :, :r] = a.T
                B[i, layer, :r, :] = b.T * scale  # fold alpha/r into B
        out[proj] = {"A": jnp.asarray(A, jnp.bfloat16),
                     "B": jnp.asarray(B, jnp.bfloat16)}
    return out, {name: i for i, (name, _, _) in enumerate(loaded, start=1)}
