"""ctypes bindings + build-on-first-use for the C++ engine core (core.cc).

No pybind11 in this image (SURVEY.md §7 env notes), so the core exposes a C
ABI and we bind with ctypes.  The shared object is compiled once per source
hash into the package directory (also buildable via the Makefile here).
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from ...utils.native_build import load_native

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "core.cc")
_LOCK = threading.Lock()
_LIB = None


def load_library() -> ctypes.CDLL:
    global _LIB
    with _LOCK:
        if _LIB is None:
            lib = load_native(_SRC, "core")
            i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
            ip = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            lib.eng_create.restype = p
            lib.eng_create.argtypes = [i32, i32, i32, i32]
            lib.eng_destroy.argtypes = [p]
            u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.eng_submit.restype = i32
            lib.eng_submit.argtypes = [p, i64, i32, i32, ctypes.c_void_p, i32]
            lib.eng_admit.restype = i32
            lib.eng_admit.argtypes = [p, ctypes.POINTER(i64), ctypes.POINTER(i32),
                                      ctypes.POINTER(i32), ctypes.POINTER(i32)]
            lib.eng_commit_token.restype = i32
            lib.eng_commit_token.argtypes = [p, i32, i32]
            lib.eng_commit_token_ex.restype = i32
            lib.eng_commit_token_ex.argtypes = [p, i32, i32, ctypes.POINTER(i32)]
            lib.eng_reserve_page.restype = i32
            lib.eng_reserve_page.argtypes = [p, i32]
            lib.eng_slot_pages.argtypes = [p, i32, ip]
            lib.eng_reclaimable.restype = i32
            lib.eng_reclaimable.argtypes = [p]
            lib.eng_reclaimable_slow.restype = i32
            lib.eng_reclaimable_slow.argtypes = [p]
            lib.eng_release.argtypes = [p, i32]
            lib.eng_release_cached.argtypes = [p, i32, u64p, i32]
            lib.eng_cache_stats.argtypes = [p, i64p]
            lib.eng_page_table.argtypes = [p, ip]
            lib.eng_seq_lens.argtypes = [p, ip]
            lib.eng_active_mask.argtypes = [p, ip]
            lib.eng_slot_req.restype = i64
            lib.eng_slot_req.argtypes = [p, i32]
            lib.eng_slot_seq_len.restype = i32
            lib.eng_slot_seq_len.argtypes = [p, i32]
            for fn in ("eng_num_free_pages", "eng_queue_depth", "eng_num_active"):
                getattr(lib, fn).restype = i32
                getattr(lib, fn).argtypes = [p]
            _LIB = lib
    return _LIB


class NativeBatcher:
    """Thin OO wrapper over the C core. Thread-safe (the core has the mutex)."""

    def __init__(self, max_slots: int, num_pages: int, page_size: int, max_pages_per_slot: int):
        self.lib = load_library()
        self.max_slots = max_slots
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_pages_per_slot = max_pages_per_slot
        self._e = self.lib.eng_create(max_slots, num_pages, page_size, max_pages_per_slot)
        if not self._e:
            raise ValueError("bad engine geometry")

    def close(self) -> None:
        if self._e:
            self.lib.eng_destroy(self._e)
            self._e = None

    def _handle(self):
        """The live engine pointer; a clean Python error after close() —
        passing NULL into the C core would segfault instead."""
        if not self._e:
            raise RuntimeError("batcher closed")
        return self._e

    def submit(self, req_id: int, prompt_len: int, max_new_tokens: int,
               prefix_hashes=None) -> bool:
        """Queue a request; False if it can never fit. ``prefix_hashes``:
        uint64 chain hashes for the lookup-eligible full prompt pages (see
        Engine._page_hashes) — the prefix-cache lookup happens at admit."""
        if prefix_hashes is not None and len(prefix_hashes):
            h = np.ascontiguousarray(prefix_hashes, dtype=np.uint64)
            rc = self.lib.eng_submit(self._handle(), req_id, prompt_len,
                                     max_new_tokens, h.ctypes.data, len(h))
        else:
            rc = self.lib.eng_submit(self._handle(), req_id, prompt_len,
                                     max_new_tokens, None, 0)
        return rc == 0

    def admit(self):
        """-> (slot, req_id, prompt_len, max_new_tokens, cached_pages) or None."""
        rid = ctypes.c_int64()
        plen = ctypes.c_int32()
        mnew = ctypes.c_int32()
        cached = ctypes.c_int32()
        slot = self.lib.eng_admit(self._handle(), ctypes.byref(rid), ctypes.byref(plen),
                                  ctypes.byref(mnew), ctypes.byref(cached))
        if slot < 0:
            return None
        return slot, rid.value, plen.value, mnew.value, cached.value

    def commit_token(self, slot: int, is_eos: bool) -> int:
        """1=continue, 0=finished, -2=page pool exhausted."""
        return self.lib.eng_commit_token(self._handle(), slot, 1 if is_eos else 0)

    def commit_token_ex(self, slot: int, is_eos: bool) -> tuple:
        """-> (rc, new_page_id or -1): rc as commit_token; new_page_id lets
        the caller grow a host-side page-table mirror incrementally."""
        new_page = ctypes.c_int32(-1)
        rc = self.lib.eng_commit_token_ex(self._handle(), slot,
                                          1 if is_eos else 0,
                                          ctypes.byref(new_page))
        return rc, new_page.value

    def reserve_page(self, slot: int) -> int:
        """Pre-allocate one page for an active slot.  Returns the page id,
        -1 no-op (bad/inactive slot or per-slot cap), -2 pool empty.

        Lookahead contract (the engine's consumers rely on it):
        speculative drafting reserves the next page so boundary-tick drafts
        have owned KV positions, and the PIPELINED decode loop reserves
        every page a dispatch will write into BEFORE dispatching, because
        its commits — and therefore the C++ page grants — run one tick
        behind the device (commit-behind).  A later ``commit_token_ex``
        that crosses into a reserved page finds the slot's page list
        already long enough and allocates nothing, so reservation and
        commit-growth compose; a reservation never used (the row finished
        behind the dispatch, or drafts were rejected) is freed with the
        slot by ``release`` like any owned page — no leak path.

        Multi-token (speculative) extension, ISSUE 9: the pipelined
        VERIFY dispatch writes up to K = 1 + spec_max_draft positions per
        slot per tick, and its commits land 1..K ``commit_token_ex``
        calls per slot one tick late — so the engine reserves up to
        ``pages_for(seq_len + draft_len)`` pages (as many as K/page_size
        + 1 new pages) before each dispatch.  The same composition rule
        makes this safe: however many of those 1..K commits cross page
        boundaries, each crossing finds its page already reserved and
        allocates nothing, so variable tokens-per-tick never races the
        free list, and rejected-draft reservations free with the slot."""
        return load_library().eng_reserve_page(self._handle(), slot)

    def release(self, slot: int, prefix_hashes=None) -> None:
        """Free the slot; with ``prefix_hashes`` (uint64, one per full PROMPT
        page) the covered pages enter the prefix cache instead.

        Preemption (engine/scheduler.py) rides this same path: a swap
        eviction releases WITHOUT hashes (the pages' contents moved to the
        host store and must not be served from cache), while a
        drop-and-recompute eviction releases WITH the victim's completed
        full-page hashes — the resume prefill then re-adopts those very
        pages as cache hits instead of recomputing them."""
        h = np.ascontiguousarray(prefix_hashes if prefix_hashes is not None else [],
                                 dtype=np.uint64)
        self.lib.eng_release_cached(self._handle(), slot, h, len(h))

    def cache_stats(self) -> dict:
        out = np.zeros((4,), np.int64)
        self.lib.eng_cache_stats(self._handle(), out)
        return {"cached_pages": int(out[0]), "page_hits": int(out[1]),
                "page_misses": int(out[2]), "evictions": int(out[3])}

    def page_table(self) -> np.ndarray:
        out = np.zeros((self.max_slots, self.max_pages_per_slot), np.int32)
        self.lib.eng_page_table(self._handle(), out.reshape(-1))
        return out

    def slot_pages(self, slot: int) -> np.ndarray:
        """One slot's page-table row (fetched at admission; see commit_token_ex)."""
        out = np.zeros((self.max_pages_per_slot,), np.int32)
        self.lib.eng_slot_pages(self._handle(), slot, out)
        return out

    def seq_lens(self) -> np.ndarray:
        out = np.zeros((self.max_slots,), np.int32)
        self.lib.eng_seq_lens(self._handle(), out)
        return out

    def active_mask(self) -> np.ndarray:
        out = np.zeros((self.max_slots,), np.int32)
        self.lib.eng_active_mask(self._handle(), out)
        return out

    def slot_req(self, slot: int) -> int:
        return self.lib.eng_slot_req(self._handle(), slot)

    def slot_seq_len(self, slot: int) -> int:
        return self.lib.eng_slot_seq_len(self._handle(), slot)

    def reclaimable(self) -> int:
        return self.lib.eng_reclaimable(self._handle())

    def reclaimable_slow(self) -> int:
        return self.lib.eng_reclaimable_slow(self._handle())

    @property
    def free_pages(self) -> int:
        return self.lib.eng_num_free_pages(self._handle())

    @property
    def queue_depth(self) -> int:
        return self.lib.eng_queue_depth(self._handle())

    @property
    def num_active(self) -> int:
        return self.lib.eng_num_active(self._handle())

    @property
    def free_slots(self) -> int:
        """Slots not currently holding a request — the QoS scheduler's
        admission headroom check (engine/scheduler.py)."""
        return self.max_slots - self.lib.eng_num_active(self._handle())

    def __del__(self):  # pragma: no cover - defensive
        try:
            self.close()
        except Exception:
            pass
