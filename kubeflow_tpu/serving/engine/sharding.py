"""Tensor-parallel sharding for the serving engine.

Role (SURVEY.md §2c TP row + system brief "long-context and distributed are
first-class"): Llama-3-8B-class models don't fit one v5e chip in bf16 with a
KV pool, so the engine must run tensor-parallel across a slice.  The TPU-
first mechanism is pure GSPMD: place the params and the KV page pool with
``NamedSharding``s over a 1-D ``tensor`` mesh and let XLA partition the SAME
jitted ``prefill``/``decode_step`` computations — attention heads and FFN
columns split across chips, with the all-reduces after ``wo``/``w2`` inserted
by the compiler (no hand-written collectives, unlike the reference's
NCCL-backed servers).

Layout (the standard Megatron split, expressed as shardings):
  * wq/wk/wv: column-parallel  [D, H*hd] → heads on ``tensor``;
  * wo:       row-parallel     [H*hd, D] → input dim on ``tensor``;
  * w1/w3:    column-parallel  [D, F] → F on ``tensor``;
  * w2:       row-parallel     [F, D];
  * embed/unembed + norms: replicated (vocab matmuls are small per step);
  * k_pool/v_pool: sharded on the KV-head axis — each chip holds its own
    heads' pages, so pool HBM also scales with the slice.

``n_kv_heads`` (and ``n_heads``) must divide the tensor size.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .model import DecoderConfig

# param name -> PartitionSpec over the ("tensor",) mesh; leading dim of the
# layer-stacked weights is the layer axis (replicated)
PARAM_SPECS = {
    "embed": P(),
    "wq": P(None, None, "tensor"),
    "wk": P(None, None, "tensor"),
    "wv": P(None, None, "tensor"),
    "wo": P(None, "tensor", None),
    "w1": P(None, None, "tensor"),
    "w3": P(None, None, "tensor"),
    "w2": P(None, "tensor", None),
    "ln_attn": P(),
    "ln_mlp": P(),
    "ln_out": P(),
    "unembed": P(),
}

# pool: [L, P, Hkv, page_size, hd] — KV heads on tensor
POOL_SPEC = P(None, None, "tensor", None, None)


def tensor_mesh(n: int) -> Mesh:
    """A 1-D tensor-parallel mesh over the first n local devices."""
    devices = jax.devices()
    if len(devices) < n:
        raise ValueError(
            f"tensor_parallel={n} needs {n} devices, have {len(devices)} — "
            "refusing to silently run at a lower degree")
    return Mesh(devices[:n], ("tensor",))


def validate_config(config: DecoderConfig, mesh: Mesh) -> None:
    tp = mesh.shape["tensor"]
    if config.n_kv_heads % tp or config.n_heads % tp:
        raise ValueError(
            f"tensor={tp} must divide n_heads={config.n_heads} and "
            f"n_kv_heads={config.n_kv_heads}")
    if config.d_ff % tp:
        raise ValueError(f"tensor={tp} must divide d_ff={config.d_ff}")


def _scale_spec(spec: P, s_shape: tuple) -> P:
    """Sharding for an int8 weight's scale tensor: same as the weight's spec
    except axes where the scale keeps a singleton (the contraction axis) go
    unsharded — a dim of 1 can't split over the mesh."""
    return P(*(None if s_shape[i] == 1 else ax for i, ax in enumerate(spec)))


def shard_params(params: dict, mesh: Mesh) -> dict:
    """Place engine params tensor-parallel on the mesh.  int8-quantized
    weights ({"q", "s"} leaves from model.quantize_weights_int8) shard q by
    the weight's spec and s by the singleton-adjusted spec."""
    out = {}
    for name, value in params.items():
        spec = PARAM_SPECS[name]
        if isinstance(value, dict):
            out[name] = {
                "q": jax.device_put(value["q"], NamedSharding(mesh, spec)),
                "s": jax.device_put(value["s"], NamedSharding(
                    mesh, _scale_spec(spec, value["s"].shape))),
            }
        else:
            out[name] = jax.device_put(value, NamedSharding(mesh, spec))
    return out


def alloc_pool(shape: tuple, mesh: Mesh, dtype=None, quant=None):
    """Allocate a zeroed pool sharded-direct — no chip ever holds the full
    pool (allocating replicated first would OOM exactly the models TP serves).
    With ``quant='int8'`` returns the {"q", "s"} pool pytree (model.py):
    values shard like the bf16 pool; the per-(token,head) scales end in a
    singleton dim, so the same kv-head-axis spec applies."""
    from .model import make_kv_pool

    if quant is not None:
        # one source of truth for the quantized-pool pytree (model.py);
        # every leaf shards on the kv-head axis (scales end in a singleton
        # dim, so POOL_SPEC applies unchanged)
        structure = jax.eval_shape(lambda: make_kv_pool(shape, quant))
        shardings = jax.tree.map(lambda _: NamedSharding(mesh, POOL_SPEC), structure)
        return jax.jit(lambda: make_kv_pool(shape, quant), out_shardings=shardings)()
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16
    return jax.jit(
        lambda: jnp.zeros(shape, dtype),
        out_shardings=NamedSharding(mesh, POOL_SPEC),
    )()


# ------------------------------------------- shard-native snapshot / scatter
#
# The KV data plane (engine session save/restore, swap park, handoff export,
# fabric publish) moves pool pages host<->device through these three
# primitives.  The contract: a TP-N pool is snapshotted as N per-shard host
# blocks — each the shard's OWN addressable bytes, 1/N of the kv-head axis —
# and restored shard-to-shard.  No pool-sized gathered buffer ever
# materializes on host, and no cross-chip collective runs (each transfer is
# chip<->host for that chip's heads only).


def shard_order(leaf) -> list:
    """The pool leaf's addressable shards ordered by kv-head slice start —
    shard i of a sharded KVPG frame is always the i-th block of the kv-head
    axis, independent of device enumeration order."""
    return sorted(leaf.addressable_shards,
                  key=lambda s: s.index[2].start or 0)


def snapshot_shards(leaf, pages) -> list:
    """Per-shard host snapshot of ``pages`` (axis 1) -> list of numpy
    blocks, one per shard in kv-head order.  The page gather runs on each
    shard's device over its local heads; only the selected pages of that
    shard cross to host."""
    return [np.asarray(s.data[:, pages]) for s in shard_order(leaf)]


def scatter_shards(leaf, pages, blocks, mesh):
    """Scatter per-shard host ``blocks`` into the sharded pool leaf at
    ``pages`` (axis 1), shard-to-shard.  Each block is device_put to its own
    shard's device and written into that shard's local pages; the global
    array is reassembled from the per-device pieces
    (make_array_from_single_device_arrays matches arrays to shard positions
    by committed device, so list order is free)."""
    arrs = []
    for s, block in zip(shard_order(leaf), blocks):
        host = np.ascontiguousarray(block)
        arrs.append(s.data.at[:, pages].set(jax.device_put(host, s.device)))
    return jax.make_array_from_single_device_arrays(
        leaf.shape, NamedSharding(mesh, POOL_SPEC), arrs)
