"""Serving telemetry: request lifecycle spans, engine histograms, and the
flight recorder (ISSUE 3).

PR 2 made the engine loop fault-tolerant but observable only through flat
gauges.  This module is the missing instrumentation layer, in three parts:

  * ``EngineTelemetry`` — a per-engine ``core.metrics.Registry`` holding the
    serving histograms the JetStream/vLLM literature treats as first-class
    (PAPERS.md): TTFT, TPOT (inter-token), queue-wait, tick-duration,
    prefill-batch-size, plus KV-page-occupancy gauges and a requests-total
    counter by outcome.  The model server renders the registry into
    ``/metrics`` verbatim (valid Prometheus text exposition), replacing the
    old float()-coerced gauge path for distribution data.
  * ``RequestSpan`` — one per request: monotonic (perf_counter) phase marks
    from queued through admitted/prefill/first_token to a terminal outcome.
    Exposed live via ``Engine.trace(rid)`` and, opt-in, as an
    ``X-Request-Trace`` response field on the generate surfaces.
  * ``FlightRecorder`` — a bounded ring buffer of structured tick events
    (phase, slots, dispatch shape, duration, outcome).  The engine dumps it
    as JSONL on TickFailure escalation, NaN-guard trips, and watchdog
    restarts, so a chaos-test failure or a production incident leaves a
    readable postmortem instead of nothing.

Everything here is host-side, allocation-light, and lock-scoped so the
decode hot loop pays nanoseconds when telemetry is on and a boolean check
when it is off (serving_bench --obs asserts the p50 overhead budget).

``TickProfiler`` wires ``jax.profiler`` to the tick loop: ``Engine.
trace_n_ticks(n, dir)`` captures an XLA trace of exactly n live ticks.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Optional

from ...core import tracing
from ...core.metrics import Registry

# Latency-class buckets (seconds).  TTFT/queue-wait span sub-ms CPU ticks up
# to cold-compile minutes; TPOT/tick-duration are per-step and an order of
# magnitude tighter.
LATENCY_BUCKETS_S = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
STEP_BUCKETS_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                  0.25, 0.5, 1.0, 2.5)
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
# Inter-dispatch host gap (pipelined decode, ISSUE 5): the time the host
# spends between handing the device one decode dispatch and the next.  The
# whole point of the pipeline is to push this toward zero, so the buckets
# reach well below STEP_BUCKETS_S — a sync loop's gap includes the blocking
# sample readback (~device step time), a pipelined loop's is bookkeeping.
GAP_BUCKETS_S = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)

# Speculative accept-length histogram (ISSUE 9): accepted draft tokens per
# verify pass, 0..K — the distribution behind the headline accept rate
# (engine_spec_accepted_tokens_total / engine_spec_draft_tokens_total).
# Buckets reach the largest spec_max_draft anyone configures in practice.
SPEC_ACCEPT_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16)

# Proxy-overhead histogram (ISSUE 18): wall the serving stack adds
# around the engine.  The floor reaches 10 µs — ROADMAP item 6 wants the
# proxy-added number in µs, and a wire-speed ingress refactor would be
# invisible under ms-scale buckets.
PROXY_OVERHEAD_BUCKETS_S = (0.00001, 0.000025, 0.00005, 0.0001, 0.00025,
                            0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                            0.05, 0.1, 0.25, 1.0)

# terminal span phases (everything else is a lifecycle waypoint)
TERMINAL_PHASES = ("done", "shed", "failed", "cancelled")


class RequestSpan:
    """Per-request lifecycle record: (phase, perf_counter) marks.

    Phases, in order: queued -> admitted -> prefill[xN] -> first_token ->
    done | shed | failed | cancelled.  A preempted request additionally
    cycles through preempted -> readmitted -> (resumed | prefill[xN])
    before its terminal phase.  Mutated only by the submitting thread
    (queued) and the engine loop (everything else), so marks need no lock;
    readers get a copying ``to_dict``.

    Fleet tracing (ISSUE 8): every span carries a trace identity — the
    ingress-minted W3C-style context when the request arrived with a
    ``traceparent`` header (so the engine span is a child of the relay
    hop that delivered it), a locally-minted trace otherwise.  ``links``
    connect spans across trace boundaries: a failover re-admission links
    the failed relay hop (``resumed_from``), a session's turn N+1 links
    turn N (``session_prev``).

    Latency attribution (ISSUE 18): ``hints`` accumulates per-request
    attribution seconds the phase marks alone cannot carry — the verify
    share of each decode dispatch, the serve-layer fabric/handoff pull
    walls measured before the span existed (``pre_*``).  ``cls`` is the
    request's priority class, the fleet latency-budget bucket key.
    """

    __slots__ = ("rid", "events", "outcome", "trace_id", "span_id",
                 "parent_id", "links", "hints", "cls")

    def __init__(self, rid: int, trace=None, links=None,
                 cls: Optional[str] = None):
        self.rid = rid
        self.events: list = [("queued", time.perf_counter())]
        self.outcome: Optional[str] = None
        if trace is not None:
            self.trace_id = trace.trace_id
            self.parent_id = trace.span_id
        else:
            self.trace_id = tracing.new_trace_id()
            self.parent_id = None
        self.span_id = tracing.new_span_id()
        self.links: list = list(links or ())
        self.hints: Optional[dict] = None
        self.cls = cls

    def hint(self, name: str, dur_s: float) -> None:  # graftlint: hot-path
        """Accumulate attribution seconds under ``name`` — O(1) dict
        upsert, called from the engine loop per dispatch (waterfall.py
        reads the total at assembly time, off the hot path)."""
        h = self.hints
        if h is None:
            h = self.hints = {}
        h[name] = h.get(name, 0.0) + dur_s

    def mark(self, phase: str) -> float:
        t = time.perf_counter()
        self.events.append((phase, t))
        if phase in TERMINAL_PHASES:
            self.outcome = phase
        return t

    def t(self, phase: str) -> Optional[float]:
        """First mark of ``phase`` (None if never reached)."""
        for p, ts in self.events:
            if p == phase:
                return ts
        return None

    def to_dict(self) -> dict:
        """JSON-safe trace: phases with timestamps relative to submit,
        plus the derived intervals dashboards actually plot."""
        events = list(self.events)
        t0 = events[0][1]
        out = {
            "rid": self.rid,
            "component": "engine",
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "outcome": self.outcome,
            "events": [{"phase": p, "t_s": round(ts - t0, 6)}
                       for p, ts in events],
        }
        if self.links:
            out["links"] = [dict(l) for l in self.links]
        if self.cls is not None:
            out["cls"] = self.cls
        if self.hints:
            out["hints"] = {k: round(v, 6) for k, v in self.hints.items()}
        by = {}
        for p, ts in events:  # first occurrence wins
            by.setdefault(p, ts)
        if "admitted" in by:
            out["queue_wait_s"] = round(by["admitted"] - t0, 6)
        if "first_token" in by:
            out["ttft_s"] = round(by["first_token"] - t0, 6)
        term = next((by[p] for p in TERMINAL_PHASES if p in by), None)
        if term is not None:
            out["latency_s"] = round(term - t0, 6)
        out["prefill_chunks"] = sum(1 for p, _ in events if p == "prefill")
        return out

    def nbytes(self) -> int:
        """Approximate retained size — the trace-history byte budget's
        accounting unit.  Deliberately a cheap closed form (not a real
        serialization): the budget needs proportionality, not precision,
        and this runs on every archive."""
        return (160 + 48 * len(self.events) + 96 * len(self.links)
                + 72 * len(self.hints or ()))


class FlightRecorder:
    """Bounded ring of structured tick events + JSONL postmortem dumps.

    ``record`` is called from the engine loop only; ``snapshot``/``dump``
    from any thread.  Dumps are capped per recorder so a chaos soak cannot
    fill a disk with identical postmortems."""

    def __init__(self, capacity: int = 256, dump_dir: Optional[str] = None,
                 max_dumps: int = 16):
        self._ring: collections.deque = collections.deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._dumps = 0
        self.max_dumps = max_dumps
        self.dump_dir = (dump_dir or os.environ.get("ENGINE_FLIGHT_DIR")
                         or os.path.join(tempfile.gettempdir(),
                                         "engine_flightrec"))
        self.last_dump_path: Optional[str] = None

    def record(self, **event) -> None:  # graftlint: hot-path
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            event["t_s"] = round(time.perf_counter(), 6)
            self._ring.append(event)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str, extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring as JSONL (header line first) and return the path;
        None once the per-recorder dump cap is hit or the write fails —
        postmortems must never take the serving path down with them."""
        with self._lock:
            if self._dumps >= self.max_dumps:
                return None
            self._dumps += 1  # reserve a slot (refunded if the write fails)
            n = self._dumps
            events = list(self._ring)
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(
                self.dump_dir, f"flightrec-{os.getpid()}-{n:03d}.jsonl")
            # reserved header keys win over extra (an extra "reason" must
            # not mask what triggered the dump)
            header = {**(extra or {}), "reason": reason,
                      "wall_time": time.time(), "events": len(events)}
            # graftlint: disable=atomic-write -- postmortem ring dump:
            # one-shot JSONL into a fresh per-pid path nothing reads
            # back programmatically; a torn tail is still a readable
            # prefix and the OSError path refunds the dump slot
            with open(path, "w") as f:
                f.write(json.dumps(header) + "\n")
                for e in events:
                    f.write(json.dumps(e) + "\n")
            self.last_dump_path = path
            return path
        except OSError:
            # refund the slot: a transiently full/unwritable disk must not
            # permanently exhaust the cap and silence later real incidents
            with self._lock:
                self._dumps -= 1
            return None


class EngineTelemetry:
    """The engine's metric surface: one Registry per engine (replicas are
    separate processes in production; separate engines in one test process
    must not pollute each other's distributions).  All observe paths no-op
    on ``enabled=False`` so the bench can measure the overhead honestly."""

    def __init__(self, enabled: bool = True,
                 registry: Optional[Registry] = None, slo=None):
        self.enabled = enabled
        self.registry = registry if registry is not None else Registry()
        # SLO attainment tracker (serving/slo.py, ISSUE 8): fed from the
        # same TTFT/TPOT/queue-wait hooks, exported at scrape time via
        # refresh_slo().  None = no tracking (telemetry-off benches).
        self.slo = slo
        r = self.registry
        self.ttft = r.histogram(
            "engine_ttft_seconds",
            "time from submit to first committed token", LATENCY_BUCKETS_S)
        self.tpot = r.histogram(
            "engine_tpot_seconds",
            "inter-token interval during decode (time per output token)",
            STEP_BUCKETS_S)
        self.queue_wait = r.histogram(
            "engine_queue_wait_seconds",
            "time from submit to slot admission", LATENCY_BUCKETS_S)
        self.tick_duration = r.histogram(
            "engine_tick_duration_seconds",
            "wall time of one engine tick that did work", STEP_BUCKETS_S)
        self.prefill_batch = r.histogram(
            "engine_prefill_batch_size",
            "prompt rows per fused prefill dispatch", BATCH_BUCKETS)
        self.requests_total = r.counter(
            "engine_requests_total", "terminal request outcomes")
        # QoS scheduler surface (ISSUE 4): preemption counts by reason
        # (priority/pages/pool/chaos) and mode (swap/recompute), KV bytes
        # moved through the host swap store, and queue wait broken out by
        # priority class (the unlabeled engine_queue_wait_seconds above
        # keeps the aggregate series stable for existing dashboards)
        self.preemptions = r.counter(
            "engine_preemptions_total",
            "decode-slot preemptions by reason and mode")
        self.swapped_bytes = r.counter(
            "engine_swapped_bytes_total",
            "KV bytes moved between the device pool and the host swap "
            "store, by direction")
        self.class_queue_wait = r.histogram(
            "engine_class_queue_wait_seconds",
            "time from submit to slot admission, by priority class",
            LATENCY_BUCKETS_S)
        self.kv_occupancy = r.gauge(
            "engine_kv_page_occupancy_ratio",
            "fraction of KV pool pages not free (in use or prefix-cached)")
        self.kv_pages = r.gauge(
            "engine_kv_pages", "KV pool pages by state (free/cached/used)")
        # Pipelined decode surface (ISSUE 5): the dispatch-gap histogram is
        # the overlap proof (sync mode's gap embeds the blocking sample;
        # pipelined mode's is host bookkeeping only), and the fence counter
        # shows how often roster changes force the pipeline to drain.
        self.dispatch_gap = r.histogram(
            "engine_dispatch_gap_seconds",
            "host-side gap between consecutive decode dispatches "
            "(device idle exposure between steps)", GAP_BUCKETS_S)
        self.pipeline_fences = r.counter(
            "engine_pipeline_fences_total",
            "decode-pipeline drains to a sync barrier, by reason")
        # Speculative decoding surface (ISSUE 9): drafted vs accepted token
        # totals (their ratio is the accept rate — the factor by which the
        # fused verify path divides per-token host overhead) and the
        # per-verify-pass accept-length distribution.  Counted identically
        # by the sync (depth-0 oracle) and pipelined speculative loops.
        self.spec_draft_tokens = r.counter(
            "engine_spec_draft_tokens_total",
            "prompt-lookup draft tokens proposed to the verify step")
        self.spec_accepted_tokens = r.counter(
            "engine_spec_accepted_tokens_total",
            "draft tokens accepted by greedy verification (excludes the "
            "per-pass bonus token)")
        self.spec_accept_len = r.histogram(
            "engine_spec_accept_len",
            "accepted draft tokens per verify pass with drafts proposed "
            "(0..spec_max_draft)", SPEC_ACCEPT_BUCKETS)
        # Tiered KV store / session surface (ISSUE 7): per-tier occupancy
        # (set at scrape time from the store's stats), an operations
        # counter labeled by tier and event (spill/evict/verify_fail/...),
        # and session-turn restore outcomes by source — "host" and "disk"
        # are warm hits, "cache" means the device prefix cache already
        # covered the prefix, "cold"/"degraded" are the re-prefill paths
        # (degraded = the store had the session but verification failed).
        self.kv_store_bytes = r.gauge(
            "engine_kv_store_bytes",
            "tiered KV store occupancy in bytes, by tier (host/disk)")
        self.kv_store_events = r.counter(
            "engine_kv_store_events_total",
            "tiered KV store operations by tier and event")
        self.session_restores = r.counter(
            "engine_session_restores_total",
            "session-turn KV restore outcomes by source "
            "(host/disk/cache/cold/degraded)")
        self.session_pins = r.counter(
            "engine_session_pins_total",
            "session pin attempts by outcome (pinned/durable/rejected)")
        # Disaggregated prefill/decode surface (README "Disaggregated
        # serving"): handoff lifecycle outcomes — export / export_failed
        # on the prefill side; pull / pull_refused / expired / miss as the
        # store answers pullers; import / degraded on the decode side
        # (degraded = the verified-KV fast path fell back to re-prefill,
        # which still completes the request) — and payload bytes by
        # direction (out = frames served to pullers, in = frames imported).
        self.kv_handoff = r.counter(
            "engine_kv_handoff_total",
            "disaggregation KV handoff operations by outcome "
            "(export/export_failed/pull/pull_refused/expired/miss/"
            "import/degraded)")
        self.kv_handoff_bytes = r.counter(
            "engine_kv_handoff_bytes_total",
            "disaggregation KV handoff payload bytes by direction "
            "(out=served to pullers, in=imported)")
        # Fleet KV fabric surface (README "Fleet KV fabric"): shared-
        # prefix lifecycle outcomes — publish / publish_skipped /
        # publish_failed on the owner side; pull / miss / expired as the
        # store answers remote pullers (multi-reader: no refused state);
        # import (a placement hint accepted at submit) / hit (remote
        # pages scattered into the local pool) / local (the device cache
        # or session restore already covered everything the frame held) /
        # degraded (any fabric failure fell back to plain re-prefill,
        # which still completes the request) — and payload bytes by
        # direction (out = frames served to pullers, in = frames
        # imported).
        self.kv_fabric = r.counter(
            "engine_kv_fabric_total",
            "fleet KV fabric operations by outcome "
            "(publish/publish_skipped/publish_failed/publish_deferred/"
            "pull/miss/expired/import/hit/local/degraded)")
        self.kv_fabric_bytes = r.counter(
            "engine_kv_fabric_bytes_total",
            "fleet KV fabric payload bytes by direction "
            "(out=frames served to pullers, in=frames imported)")
        # Mesh-sharded KV data plane (ISSUE 16): host bytes moved through
        # the per-shard snapshot/scatter path — each shard's OWN
        # addressable bytes, never a gathered pool — and the layout
        # outcome of every sharded restore (match = degree-aligned
        # shard-to-shard scatter; reshard = the explicit host-side
        # cross-degree slow path).
        self.kv_shard_bytes = r.counter(
            "engine_kv_shard_bytes_total",
            "per-shard KV snapshot/scatter host bytes by direction "
            "(export=device->host shard blocks, restore=host->device)")
        self.kv_reshard = r.counter(
            "engine_kv_reshard_total",
            "sharded KV restore layout outcomes (match=degree-aligned "
            "shard-to-shard, reshard=host-side cross-degree conversion)")
        # Fleet robustness surface (ISSUE 6): the engine's health state as a
        # one-hot labeled gauge so dashboards can plot state transitions —
        # the scrape-time complement of the router's active /engine/health
        # probe (refreshed in JetStreamModel.metrics_text).
        self.health_state = r.gauge(
            "engine_health_state",
            "engine health state machine, one-hot by state "
            "(SERVING/DEGRADED/DRAINING/DEAD)")
        # Fleet observability surface (ISSUE 8): per-class SLO attainment
        # over rolling windows (refreshed at scrape from the SloTracker),
        # multi-window burn rate, and the trace-history eviction counter
        # (RequestSpan history is byte/entry budgeted; evictions here mean
        # the budget is working, a flat 0 on a long run means it's sized
        # right).
        self.slo_attainment = r.gauge(
            "slo_attainment_ratio",
            "fraction of in-window requests meeting their latency target, "
            "by priority class and metric (ttft/tpot/queue_wait)")
        self.slo_burn = r.gauge(
            "slo_burn_rate",
            "error-budget burn rate (1-attainment)/(1-objective), by "
            "class, metric and rolling window")
        self.trace_evictions = r.counter(
            "engine_trace_evictions_total",
            "request spans evicted from the bounded trace history "
            "(entry or byte budget)")
        # Performance introspection plane (ISSUE 11, perf.py): analytical
        # model FLOPs charged at dispatch time by kind, the waste share by
        # attribution reason (goodput + waste == dispatched by the
        # ledger's construction), and the scrape-time derived gauges —
        # windowed MFU against the platform peak-FLOPs table, windowed
        # goodput ratio, and KV internal fragmentation.  Cache analytics:
        # prefix-cache page outcomes per admission lookup.
        self.flops_total = r.counter(
            "engine_model_flops_total",
            "analytical model FLOPs charged at dispatch, by kind "
            "(prefill/decode/verify)")
        self.wasted_flops = r.counter(
            "engine_wasted_flops_total",
            "dispatched FLOPs attributed to waste, by reason "
            "(spec_reject/preempt_recompute/handoff_degraded/"
            "fabric_degraded/failover_reprefill/tick_retry/pipeline_drop)")
        self.mfu_ratio = r.gauge(
            "engine_mfu_ratio",
            "rolling-window analytical model-FLOPs utilization vs the "
            "platform peak (perf.platform_peak_flops), by platform label")
        self.goodput_ratio = r.gauge(
            "engine_goodput_ratio",
            "rolling-window goodput FLOPs / dispatched FLOPs "
            "(1.0 = nothing wasted)")
        self.kv_fragmentation = r.gauge(
            "engine_kv_fragmentation_ratio",
            "internal fragmentation of live KV pages: 1 - committed "
            "tokens / (owned pages * page_size), set at scrape")
        self.prefix_cache_pages = r.counter(
            "engine_prefix_cache_pages_total",
            "prefix-cache page lookup outcomes at admission "
            "(hit/miss_cold/miss_partial)")
        # Incident plane (README "Incident plane", serving/incidents.py):
        # open incidents right now (set at scrape, right-when-read like
        # the KV gauges), terminal incident count by FINAL classified
        # root cause (counted at resolution, the engine_requests_total
        # terminal-outcome analogy), and raw detector firings (a coalesced
        # burst fires many times but opens ONE incident — the ratio is
        # the debounce working).  The router registers the same three
        # names in the shared core registry for its ingress-scope manager.
        self.incidents_open = r.gauge(
            "incidents_open",
            "open (unresolved) incidents held by this component's "
            "incident manager")
        self.incidents_total = r.counter(
            "incidents_total",
            "resolved incidents by classified root cause "
            "(replica_death/prefill_interference/storage_degradation/"
            "handoff_degradation/fabric_degradation/capacity/unknown)")
        self.incident_firings = r.counter(
            "incident_detector_firings_total",
            "incident detector firings by detector (many firings "
            "coalesce into one incident inside the debounce window)")
        # Overload control (README "Overload control", serving/overload.py):
        # requests this engine served under an ingress brownout stage —
        # the engine-side receipt that degraded-quality admission is
        # actually reaching the hot loop (stage 2 disables speculation
        # drafting, stage 3 defers fabric publishes).
        self.brownout_requests = r.counter(
            "engine_brownout_requests_total",
            "requests served under an ingress brownout stage, by stage")
        # Latency attribution plane (ISSUE 18, serving/waterfall.py):
        # wall the serving stack ADDED around the engine — here the
        # model-server scope (HTTP handling + tokenize/detokenize +
        # serve-layer pulls around one engine run, observed per unary
        # request in server.py).  The router registers the same name in
        # the shared core registry for its ingress scope (relay wall
        # minus engine-attributed wall — ROADMAP item 6's "proxy-added
        # latency in µs", measured per-request, not inferred from paired
        # benches).  One metric contract, two scopes, like incidents.
        self.proxy_overhead = r.histogram(
            "ingress_proxy_overhead_seconds",
            "serving-stack wall added around the engine per request "
            "(engine scope: model server; ingress scope: service proxy)",
            PROXY_OVERHEAD_BUCKETS_S)
        # Structured output (README "Structured output"): constrained
        # requests by terminal outcome — "valid" (finished with the
        # automaton accepting), "truncated" (max_new_tokens/deadline cut
        # generation mid-grammar; the emitted prefix is still legal),
        # "stall" (zero legal tokens — engine bug, the slot failed),
        # "recompile" (a corrupted token-map cache degraded to a counted
        # rebuild; the request itself still lands in another outcome) —
        # and per-tick host wall spent building grammar masks (automaton
        # advance + trie walk; the waterfall's grammar_advance segment is
        # the per-request view of the same cost).
        self.constrained_requests = r.counter(
            "engine_constrained_requests_total",
            "constrained (grammar/schema) requests, by terminal outcome")
        self.grammar_mask = r.histogram(
            "engine_grammar_mask_seconds",
            "host wall per tick spent advancing grammar automata and "
            "building token masks for constrained slots",
            PROXY_OVERHEAD_BUCKETS_S)

    # Observe methods stay branch-cheap: one attribute check, then a dict
    # op under the metric's own lock.

    def observe_ttft(self, s: float, priority: Optional[str] = None) -> None:
        if self.enabled:
            self.ttft.observe(s)
            if self.slo is not None and priority is not None:
                self.slo.observe(priority, "ttft", s)

    def observe_tpot(self, s: float, priority: Optional[str] = None) -> None:
        if self.enabled:
            self.tpot.observe(s)
            if self.slo is not None and priority is not None:
                self.slo.observe(priority, "tpot", s)

    def observe_queue_wait(self, s: float,
                           priority: Optional[str] = None) -> None:
        if self.enabled:
            self.queue_wait.observe(s)
            if priority is not None:
                self.class_queue_wait.observe(s, priority=priority)
                if self.slo is not None:
                    self.slo.observe(priority, "queue_wait", s)

    def count_trace_evictions(self, n: int) -> None:
        if self.enabled and n:
            self.trace_evictions.inc(n)

    def observe_proxy_overhead(self, s: float) -> None:
        if self.enabled:
            self.proxy_overhead.observe(s)

    def refresh_slo(self) -> None:
        """Recompute the SLO gauges from the tracker's rolling windows —
        scrape-time only (a gauge needs to be right when read, and the
        window math is O(samples), not O(1))."""
        if self.enabled and self.slo is not None:
            self.slo.export(self.slo_attainment, self.slo_burn)

    def count_preemption(self, reason: str, mode: str) -> None:
        if self.enabled:
            self.preemptions.inc(reason=reason, mode=mode)

    def count_flops(self, kind: str, flops: float,
                    reason: Optional[str] = None) -> None:
        """PerfLedger charge hook: dispatched FLOPs by kind, waste share
        by reason.  Exposition mirrors the ledger exactly because the
        ledger CALLS this per charge — the /metrics counters and the
        /engine/perf snapshot can never disagree."""
        if self.enabled:
            self.flops_total.inc(flops, kind=kind)
            if reason is not None:
                self.wasted_flops.inc(flops, reason=reason)

    def count_cache_pages(self, requested: int, hit: int) -> None:
        if not self.enabled or requested <= 0:
            return
        if hit > 0:
            self.prefix_cache_pages.inc(hit, outcome="hit")
        if hit < requested:
            outcome = "miss_partial" if hit > 0 else "miss_cold"
            self.prefix_cache_pages.inc(requested - hit, outcome=outcome)

    def set_perf(self, mfu: float, goodput: float, fragmentation: float,
                 platform: str) -> None:
        """Scrape-time derived gauges (serve.metrics_text refreshes them
        alongside the KV/SLO gauges — right when read, not per tick)."""
        if self.enabled:
            self.mfu_ratio.set(mfu, platform=platform)
            self.goodput_ratio.set(goodput)
            self.kv_fragmentation.set(fragmentation)

    def count_swap(self, direction: str, nbytes: int) -> None:
        if self.enabled:
            self.swapped_bytes.inc(nbytes, direction=direction)

    def observe_tick(self, s: float) -> None:
        if self.enabled:
            self.tick_duration.observe(s)

    def observe_dispatch_gap(self, s: float) -> None:
        if self.enabled:
            self.dispatch_gap.observe(s)

    def count_fence(self, reason: str) -> None:
        if self.enabled:
            self.pipeline_fences.inc(reason=reason)

    def observe_spec(self, drafted: int, accepted: int) -> None:
        """One verify pass that PROPOSED drafts: ``drafted`` tokens offered,
        ``accepted`` of them kept (bonus token excluded).  No-draft passes
        are not observed — they would swamp the accept-length histogram
        with structural zeros during index-miss phases."""
        if self.enabled and drafted:
            self.spec_draft_tokens.inc(drafted)
            if accepted:
                self.spec_accepted_tokens.inc(accepted)
            self.spec_accept_len.observe(accepted)

    def count_handoff(self, outcome: str) -> None:
        if self.enabled:
            self.kv_handoff.inc(outcome=outcome)

    def count_handoff_bytes(self, direction: str, nbytes: int) -> None:
        if self.enabled and nbytes:
            self.kv_handoff_bytes.inc(nbytes, direction=direction)

    def count_fabric(self, outcome: str) -> None:
        if self.enabled:
            self.kv_fabric.inc(outcome=outcome)

    def count_fabric_bytes(self, direction: str, nbytes: int) -> None:
        if self.enabled and nbytes:
            self.kv_fabric_bytes.inc(nbytes, direction=direction)

    def count_kv_shard_bytes(self, direction: str, nbytes: int) -> None:
        if self.enabled and nbytes:
            self.kv_shard_bytes.inc(nbytes, direction=direction)

    def count_reshard(self, outcome: str) -> None:
        if self.enabled:
            self.kv_reshard.inc(outcome=outcome)

    def count_kv_event(self, tier: str, event: str) -> None:
        if self.enabled:
            self.kv_store_events.inc(tier=tier, event=event)

    def count_session_restore(self, source: str) -> None:
        if self.enabled:
            self.session_restores.inc(source=source)

    def count_session_pin(self, outcome: str) -> None:
        if self.enabled:
            self.session_pins.inc(outcome=outcome)

    def count_brownout(self, stage: int) -> None:
        if self.enabled and stage > 0:
            self.brownout_requests.inc(stage=str(stage))

    def count_constrain(self, outcome: str) -> None:
        if self.enabled:
            self.constrained_requests.inc(outcome=outcome)

    def observe_grammar_mask(self, s: float) -> None:
        if self.enabled:
            self.grammar_mask.observe(s)

    def count_incident_firing(self, detector: str) -> None:
        if self.enabled:
            self.incident_firings.inc(detector=detector)

    def count_incident(self, cause: str) -> None:
        if self.enabled:
            self.incidents_total.inc(cause=cause)

    def set_incidents_open(self, n: int) -> None:
        if self.enabled:
            self.incidents_open.set(n)

    def set_kv_store_bytes(self, host: int, disk: int) -> None:
        if self.enabled:
            self.kv_store_bytes.set(host, tier="host")
            self.kv_store_bytes.set(disk, tier="disk")

    def observe_prefill_batch(self, rows: int) -> None:
        if self.enabled:
            self.prefill_batch.observe(rows)

    def count_outcome(self, outcome: str) -> None:
        if self.enabled:
            self.requests_total.inc(outcome=outcome)

    def set_health(self, state: str) -> None:
        if not self.enabled:
            return
        for s in ("SERVING", "DEGRADED", "DRAINING", "DEAD"):
            self.health_state.set(1.0 if s == state else 0.0, state=s)

    def set_kv_pages(self, free: int, cached: int, total: int) -> None:
        if not self.enabled or total <= 0:
            return
        used = max(0, total - free - cached)
        self.kv_pages.set(free, state="free")
        self.kv_pages.set(cached, state="cached")
        self.kv_pages.set(used, state="used")
        self.kv_occupancy.set((total - free) / total)

    def render(self) -> str:
        return self.registry.render()


class TickProfiler:
    """jax.profiler glue for ``Engine.trace_n_ticks``: the engine loop calls
    the two hooks at tick boundaries; start/stop happen ON the loop thread
    so the captured trace brackets whole ticks, never a half-dispatch.

    The n-tick window counts WORK ticks only: a capture armed on an idle
    engine starts recording immediately (so the first dispatch's compile is
    in the trace) but stays open until ``n`` ticks that actually dispatched
    have elapsed — idle 20ms waits must not run the window down to an empty
    profile.  Corollary: a capture on an engine that never receives work
    stays active until work arrives or the engine stops.

    State transitions are lock-guarded (request() runs on a caller thread),
    and profiler failures degrade to a recorded error string — a broken
    profiler install must not take the decode loop down."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Optional[tuple] = None  # (n_work_ticks, dir)
        self._remaining: Optional[int] = None
        self.last_error: Optional[str] = None
        self.captures = 0
        # called (loop thread) when a capture finishes or fails to start,
        # as ``on_complete(error_or_None, ctx)`` where ``ctx`` is the
        # opaque value the arming ``request`` carried — the engine's
        # ProfileStore sizes and caps the artifacts from here.  Carrying
        # the ctx THROUGH the profiler (instead of a side field on the
        # engine) closes the race where a capture completes on the loop
        # thread before the arming thread records which run it was.
        self.on_complete = None
        self._ctx = None

    def request(self, n_ticks: int, trace_dir: str, ctx=None) -> None:
        if n_ticks <= 0:
            raise ValueError("n_ticks must be positive")
        with self._lock:
            if self._pending is not None or self._remaining is not None:
                raise RuntimeError("a profiler capture is already in flight")
            self._pending = (n_ticks, trace_dir)
            self._ctx = ctx

    @property
    def active(self) -> bool:
        with self._lock:
            return self._pending is not None or self._remaining is not None

    def on_tick_start(self, tick: int) -> None:
        with self._lock:
            if self._pending is None:
                return
            n, d = self._pending
            self._pending = None
            self._remaining = n
        try:
            import jax

            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            self.last_error = f"{type(e).__name__}: {e}"
            with self._lock:
                self._remaining = None
                ctx, self._ctx = self._ctx, None
            if self.on_complete is not None:
                self.on_complete(self.last_error, ctx)

    def on_tick_end(self, tick: int, did_work: bool) -> None:
        with self._lock:
            if self._remaining is None:
                return
            if did_work:
                self._remaining -= 1
            if self._remaining > 0:
                return
        err = None
        try:
            import jax

            jax.profiler.stop_trace()
            self.captures += 1
        except Exception as e:  # noqa: BLE001
            err = self.last_error = f"{type(e).__name__}: {e}"
        finally:
            # deactivate only AFTER stop_trace has run: `active` going False
            # is the caller-visible "capture finished" signal
            with self._lock:
                self._remaining = None
                ctx, self._ctx = self._ctx, None
            if self.on_complete is not None:
                self.on_complete(err, ctx)
