// Concurrent stress driver for the engine core (core.cc), built with
// sanitizers: the TPU rebuild's stand-in for upstream's `go test -race` CI
// lane (SURVEY.md §5 — the reference has no first-party C++; ours must prove
// its locking under TSAN/ASAN, not just pass single-threaded unit tests).
//
// Build + run (tests/test_engine.py::test_core_concurrent_stress_under_sanitizers):
//   make stress-tsan  (Makefile in this directory)
//
// Scenario: submitter threads race the decode thread across the full API —
// submit (with prefix hashes) / admit / commit / release-with-cache /
// snapshot readers — long enough for every lock-order mistake to surface.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <chrono>
#include <thread>
#include <vector>

extern "C" {
struct Engine;
Engine* eng_create(int32_t, int32_t, int32_t, int32_t);
void eng_destroy(Engine*);
int32_t eng_submit(Engine*, int64_t, int32_t, int32_t, const uint64_t*, int32_t);
int32_t eng_admit(Engine*, int64_t*, int32_t*, int32_t*, int32_t*);
int32_t eng_commit_token(Engine*, int32_t, int32_t);
void eng_release_cached(Engine*, int32_t, const uint64_t*, int32_t);
void eng_page_table(Engine*, int32_t*);
void eng_seq_lens(Engine*, int32_t*);
void eng_active_mask(Engine*, int32_t*);
int32_t eng_num_free_pages(Engine*);
int32_t eng_queue_depth(Engine*);
int32_t eng_num_active(Engine*);
void eng_cache_stats(Engine*, int64_t*);
int32_t eng_reclaimable(Engine*);
int32_t eng_reclaimable_slow(Engine*);
}

namespace {
constexpr int32_t kSlots = 4;
constexpr int32_t kPages = 65;
constexpr int32_t kPageSize = 8;
constexpr int32_t kMaxPagesPerSlot = 8;
constexpr int kRequests = 2000;

std::atomic<int64_t> next_id{1};
std::atomic<int64_t> completed{0};
std::atomic<bool> done{false};
}  // namespace

static void submitter(Engine* e, unsigned seed) {
  for (int i = 0; i < kRequests; ++i) {
    int64_t id = next_id.fetch_add(1);
    // a handful of shared prefixes so the cache path gets real contention
    uint64_t base = 100 + (seed + i) % 4;
    uint64_t hashes[3] = {base, base * 31 + 7, base * 977 + 13};
    int32_t prompt = 9 + static_cast<int32_t>((seed + i) % 20);
    while (eng_submit(e, id, prompt, 1 + (i % 6), hashes,
                      (prompt - 1) / kPageSize) != 0) {
      std::this_thread::yield();
    }
    // back-pressure: keep the queue bounded so admission keeps up
    while (eng_queue_depth(e) > 64) std::this_thread::yield();
  }
}

static void decoder(Engine* e) {
  std::vector<int32_t> table(kSlots * kMaxPagesPerSlot);
  std::vector<int32_t> lens(kSlots);
  std::vector<int32_t> active(kSlots);
  uint64_t hashes[3];
  while (!done.load()) {
    int64_t rid;
    int32_t plen, mnew, cached;
    while (true) {
      int32_t slot = eng_admit(e, &rid, &plen, &mnew, &cached);
      if (slot < 0) break;
      (void)cached;
    }
    eng_page_table(e, table.data());
    eng_seq_lens(e, lens.data());
    eng_active_mask(e, active.data());
    for (int32_t s = 0; s < kSlots; ++s) {
      if (!active[s]) continue;
      int32_t rc = eng_commit_token(e, s, 0);
      if (rc != 1) {
        uint64_t base = 100 + static_cast<uint64_t>(lens[s]) % 4;
        hashes[0] = base;
        hashes[1] = base * 31 + 7;
        hashes[2] = base * 977 + 13;
        eng_release_cached(e, s, hashes, lens[s] / kPageSize > 3 ? 3 : lens[s] / kPageSize);
        completed.fetch_add(1);
      }
    }
  }
}

static void snapshotter(Engine* e) {
  int64_t stats[4];
  std::vector<int32_t> table(kSlots * kMaxPagesPerSlot);
  while (!done.load()) {
    eng_cache_stats(e, stats);
    eng_page_table(e, table.data());
    (void)eng_num_free_pages(e);
    (void)eng_num_active(e);
    std::this_thread::yield();
  }
}

// The incremental reclaimable counter must never drift from the O(cache)
// recompute.  Checked single-threaded (after the drain) — the two calls take
// the lock separately, so comparing them mid-race would be meaningless.
static bool reclaimable_consistent(Engine* e) {
  int32_t fast = eng_reclaimable(e);
  int32_t slow = eng_reclaimable_slow(e);
  if (fast != slow) {
    std::fprintf(stderr, "reclaimable drift: incremental %d vs recompute %d\n",
                 fast, slow);
    return false;
  }
  return true;
}

int main() {
  Engine* e = eng_create(kSlots, kPages, kPageSize, kMaxPagesPerSlot);
  if (!e) {
    std::fprintf(stderr, "eng_create failed\n");
    return 2;
  }
  std::thread dec(decoder, e);
  std::thread snap(snapshotter, e);
  std::vector<std::thread> subs;
  for (unsigned t = 0; t < 3; ++t) subs.emplace_back(submitter, e, t * 7919);
  for (auto& t : subs) t.join();
  // drain: every submitted request must complete (generous deadline — TSAN
  // slows everything down ~10x and this box may have one core)
  const int64_t want = 3 * kRequests;
  for (int spin = 0; spin < 1200 && completed.load() < want; ++spin) {
    std::this_thread::sleep_for(std::chrono::milliseconds(100));
  }
  done.store(true);
  dec.join();
  snap.join();
  int64_t got = completed.load();
  bool consistent = reclaimable_consistent(e);
  eng_destroy(e);
  if (!consistent) return 1;
  if (got != want) {
    std::fprintf(stderr, "stress: completed %lld of %lld\n",
                 static_cast<long long>(got), static_cast<long long>(want));
    return 1;
  }
  std::printf("stress OK: %lld requests\n", static_cast<long long>(got));
  return 0;
}
