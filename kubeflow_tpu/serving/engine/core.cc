// Continuous-batching engine core: request queue, slot scheduler, paged-KV
// allocator.  C ABI for ctypes (this image has no pybind11).
//
// Role in the stack (SURVEY.md §2b): the TPU-native replacement for the
// reference stack's Triton C++ serving core — "request queue / batcher /
// KV-paging in C++ with JAX compute".  The Python side (engine.py) owns the
// JAX prefill/decode; this core owns admission, slot lifecycle and KV page
// accounting, and is safe to call from server threads (one mutex, no
// allocation on the hot path).
//
// Memory model: a fixed pool of `num_pages` KV pages of `page_size` tokens.
// Each active slot holds ceil(seq_len / page_size) pages, capped at
// max_pages_per_slot.  Admission is all-or-nothing: a request enters a slot
// only if its whole prompt fits in free pages (decode growth may still hit
// OOM; commit_token reports it so the scheduler can preempt).

#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Request {
  int64_t id;
  int32_t prompt_len;
  int32_t max_new_tokens;
};

struct Slot {
  bool active = false;
  int64_t req_id = -1;
  int32_t seq_len = 0;        // tokens currently in KV (prompt + generated)
  int32_t generated = 0;
  int32_t max_new_tokens = 0;
  std::vector<int32_t> pages; // page ids owned by this slot
};

struct Engine {
  std::mutex mu;
  int32_t max_slots;
  int32_t num_pages;
  int32_t page_size;
  int32_t max_pages_per_slot;
  std::deque<Request> queue;
  std::vector<Slot> slots;
  std::vector<int32_t> free_pages; // LIFO free list
  int64_t total_admitted = 0;
  int64_t total_completed = 0;
};

int32_t pages_needed(const Engine* e, int32_t tokens) {
  return (tokens + e->page_size - 1) / e->page_size;
}

}  // namespace

extern "C" {

Engine* eng_create(int32_t max_slots, int32_t num_pages, int32_t page_size,
                   int32_t max_pages_per_slot) {
  if (max_slots <= 0 || num_pages <= 0 || page_size <= 0 ||
      max_pages_per_slot <= 0)
    return nullptr;
  Engine* e = new Engine();
  e->max_slots = max_slots;
  e->num_pages = num_pages;
  e->page_size = page_size;
  e->max_pages_per_slot = max_pages_per_slot;
  e->slots.resize(max_slots);
  // Page 0 is RESERVED as the trash page and never allocated: the fused
  // decode step writes every slot's current-token KV unconditionally (static
  // shapes), and inactive/padded slots point at page 0 — reserving it makes
  // those writes harmless by construction.  Usable capacity: num_pages - 1.
  e->free_pages.reserve(num_pages - 1);
  for (int32_t p = num_pages - 1; p >= 1; --p) e->free_pages.push_back(p);
  return e;
}

void eng_destroy(Engine* e) { delete e; }

// Enqueue a request. Returns 0, or -1 if the prompt can never fit.
int32_t eng_submit(Engine* e, int64_t req_id, int32_t prompt_len,
                   int32_t max_new_tokens) {
  std::lock_guard<std::mutex> lock(e->mu);
  // Admission is head-of-line: a request that exceeds either the per-slot cap
  // OR the whole page pool would block the queue forever — reject it here.
  if (pages_needed(e, prompt_len + max_new_tokens) > e->max_pages_per_slot ||
      pages_needed(e, prompt_len) >= e->num_pages)  // page 0 is reserved
    return -1;
  e->queue.push_back({req_id, prompt_len, max_new_tokens});
  return 0;
}

// Admit the head-of-line request into a free slot if its prompt fits in free
// pages.  Returns the slot id (prompt pages already allocated) or -1.
int32_t eng_admit(Engine* e, int64_t* out_req_id, int32_t* out_prompt_len,
                  int32_t* out_max_new) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (e->queue.empty()) return -1;
  int32_t slot_id = -1;
  for (int32_t s = 0; s < e->max_slots; ++s)
    if (!e->slots[s].active) { slot_id = s; break; }
  if (slot_id < 0) return -1;
  const Request& r = e->queue.front();
  int32_t need = pages_needed(e, r.prompt_len);
  if (need > static_cast<int32_t>(e->free_pages.size())) return -1;
  Slot& slot = e->slots[slot_id];
  slot.active = true;
  slot.req_id = r.id;
  slot.seq_len = r.prompt_len;
  slot.generated = 0;
  slot.max_new_tokens = r.max_new_tokens;
  slot.pages.clear();
  for (int32_t i = 0; i < need; ++i) {
    slot.pages.push_back(e->free_pages.back());
    e->free_pages.pop_back();
  }
  *out_req_id = r.id;
  *out_prompt_len = r.prompt_len;
  *out_max_new = r.max_new_tokens;
  e->queue.pop_front();
  e->total_admitted++;
  return slot_id;
}

// Record one generated token for a slot, growing its KV by one position.
// Returns 1 = keep decoding, 0 = request finished (eos or budget),
// -2 = page pool exhausted (caller should preempt/release), -1 = bad slot.
int32_t eng_commit_token(Engine* e, int32_t slot_id, int32_t is_eos) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return -1;
  Slot& slot = e->slots[slot_id];
  if (!slot.active) return -1;
  int32_t need = pages_needed(e, slot.seq_len + 1);
  if (need > static_cast<int32_t>(slot.pages.size())) {
    if (need > e->max_pages_per_slot) return 0;  // hit the per-slot cap: done
    if (e->free_pages.empty()) return -2;
    slot.pages.push_back(e->free_pages.back());
    e->free_pages.pop_back();
  }
  slot.seq_len++;
  slot.generated++;
  if (is_eos || slot.generated >= slot.max_new_tokens) return 0;
  return 1;
}

void eng_release(Engine* e, int32_t slot_id) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return;
  Slot& slot = e->slots[slot_id];
  if (!slot.active) return;
  for (int32_t p : slot.pages) e->free_pages.push_back(p);
  slot.pages.clear();
  slot.active = false;
  slot.req_id = -1;
  slot.seq_len = 0;
  e->total_completed++;
}

// Snapshots for the JAX side (caller provides buffers).
void eng_page_table(Engine* e, int32_t* out /* max_slots*max_pages_per_slot */) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t s = 0; s < e->max_slots; ++s) {
    const Slot& slot = e->slots[s];
    for (int32_t i = 0; i < e->max_pages_per_slot; ++i) {
      out[s * e->max_pages_per_slot + i] =
          (slot.active && i < static_cast<int32_t>(slot.pages.size()))
              ? slot.pages[i]
              : 0;  // trash page: safe to write AND gather; masked by seq_lens
    }
  }
}

void eng_seq_lens(Engine* e, int32_t* out /* max_slots */) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t s = 0; s < e->max_slots; ++s)
    out[s] = e->slots[s].active ? e->slots[s].seq_len : 0;
}

void eng_active_mask(Engine* e, int32_t* out /* max_slots */) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t s = 0; s < e->max_slots; ++s)
    out[s] = e->slots[s].active ? 1 : 0;
}

int64_t eng_slot_req(Engine* e, int32_t slot_id) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return -1;
  return e->slots[slot_id].active ? e->slots[slot_id].req_id : -1;
}

int32_t eng_slot_seq_len(Engine* e, int32_t slot_id) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return 0;
  return e->slots[slot_id].seq_len;
}

int32_t eng_num_free_pages(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  return static_cast<int32_t>(e->free_pages.size());
}

int32_t eng_queue_depth(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  return static_cast<int32_t>(e->queue.size());
}

int32_t eng_num_active(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  int32_t n = 0;
  for (const Slot& s : e->slots) n += s.active ? 1 : 0;
  return n;
}

}  // extern "C"
