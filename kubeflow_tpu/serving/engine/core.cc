// Continuous-batching engine core: request queue, slot scheduler, paged-KV
// allocator.  C ABI for ctypes (this image has no pybind11).
//
// Role in the stack (SURVEY.md §2b): the TPU-native replacement for the
// reference stack's Triton C++ serving core — "request queue / batcher /
// KV-paging in C++ with JAX compute".  The Python side (engine.py) owns the
// JAX prefill/decode; this core owns admission, slot lifecycle and KV page
// accounting, and is safe to call from server threads (one mutex, no
// allocation on the hot path).
//
// Memory model: a fixed pool of `num_pages` KV pages of `page_size` tokens.
// Each active slot holds ceil(seq_len / page_size) pages, capped at
// max_pages_per_slot.  Admission is all-or-nothing: a request enters a slot
// only if its whole prompt fits in free pages (decode growth may still hit
// OOM; commit_token reports it so the scheduler can preempt).
//
// Prefix cache (vLLM/JetStream-style, allocator-level): full prompt pages
// are refcounted and indexed by a chain hash supplied by the caller
// (hash(page i) folds in hash(page i-1), so equal hashes mean equal
// token prefixes at equal positions).  On submit, the longest cached chain
// prefix is pinned for the request; on admit the slot adopts those pages
// and allocates only the remainder; on release the slot's full prompt
// pages are inserted into the cache instead of freed.  Cached pages with no
// other owner are reclaimed leaf-first by LRU when the free list runs dry,
// so the cache can never cause an admission failure that an empty cache
// would not also have had.

#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  int64_t id;
  int32_t prompt_len;
  int32_t max_new_tokens;
  // chain hashes of the lookup-eligible full prompt pages; the cache lookup
  // happens at ADMIT time (pinning at submit could deadlock head-of-line
  // admission: a pinned page is neither free nor evictable, and the pinning
  // request may sit behind one that needs those very pages)
  std::vector<uint64_t> hashes;
};

struct Slot {
  bool active = false;
  int64_t req_id = -1;
  int32_t seq_len = 0;        // tokens currently in KV (prompt + generated)
  int32_t generated = 0;
  int32_t max_new_tokens = 0;
  std::vector<int32_t> pages; // page ids owned by this slot
};

struct CacheEntry {
  int32_t page;
  uint64_t parent;     // chain hash of the previous page (0 = root)
  int32_t children;    // live cache entries whose parent is this hash
  uint64_t last_use;   // LRU clock
  // Incremental eviction accounting (replaces the per-admit O(cache) rescan):
  // blockers = (this page has a non-cache owner ? 1 : 0) + number of DIRECT
  // children that are themselves blocked.  blockers == 0 iff leaf-first
  // eviction could eventually reclaim this entry; maintained on every
  // ref/deref/insert/erase transition by add_blocker/remove_blocker.
  int32_t blockers;
  uint64_t filed;      // key this entry holds in Engine::evictable (0 = none)
};

struct Engine {
  std::mutex mu;
  int32_t max_slots;
  int32_t num_pages;
  int32_t page_size;
  int32_t max_pages_per_slot;
  std::deque<Request> queue;
  std::vector<Slot> slots;
  std::vector<int32_t> free_pages;  // LIFO free list (refcount 0 pages)
  std::vector<int32_t> refcount;    // per-page owners (slots + pins + cache)
  std::unordered_map<uint64_t, CacheEntry> cache;  // chain hash -> page
  std::vector<uint64_t> page_hash;  // page id -> cache hash (0 = not cached)
  // evictable leaves ordered by LRU clock (last_use is unique per touch), so
  // evict_one is O(log n) and the admit-time reclaimable count is O(1)
  std::map<uint64_t, uint64_t> evictable;  // last_use -> chain hash
  int64_t blocked_count = 0;        // cache entries with blockers > 0
  uint64_t clock = 0;
  int64_t cache_hits = 0;       // pages served from cache
  int64_t cache_misses = 0;     // prompt pages that had to be computed
  int64_t cache_evictions = 0;  // cached pages reclaimed under pressure
  int64_t total_admitted = 0;
  int64_t total_completed = 0;
};

int32_t pages_needed(const Engine* e, int32_t tokens) {
  return (tokens + e->page_size - 1) / e->page_size;
}

// Sync one entry's membership in the evictable-leaf LRU index after any
// mutation of its children/blockers/last_use.
void update_evictable(Engine* e, uint64_t h, CacheEntry& ce) {
  bool eligible = ce.children == 0 && ce.blockers == 0;
  if (eligible) {
    if (ce.filed != ce.last_use) {
      if (ce.filed) e->evictable.erase(ce.filed);
      e->evictable[ce.last_use] = h;
      ce.filed = ce.last_use;
    }
  } else if (ce.filed) {
    e->evictable.erase(ce.filed);
    ce.filed = 0;
  }
}

// An entry became blocked-from-below (own page gained a non-cache owner, or
// a direct child flipped to blocked): bump blockers up the chain, stopping
// at the first ancestor that was already blocked.
void add_blocker(Engine* e, uint64_t h) {
  while (h != 0) {
    auto it = e->cache.find(h);
    if (it == e->cache.end()) return;
    CacheEntry& ce = it->second;
    ce.blockers++;
    update_evictable(e, h, ce);
    if (ce.blockers > 1) return;  // already blocked: ancestors already count it
    e->blocked_count++;
    h = ce.parent;
  }
}

void remove_blocker(Engine* e, uint64_t h) {
  while (h != 0) {
    auto it = e->cache.find(h);
    if (it == e->cache.end()) return;
    CacheEntry& ce = it->second;
    ce.blockers--;
    update_evictable(e, h, ce);
    if (ce.blockers > 0) return;  // still blocked: ancestors keep counting it
    e->blocked_count--;
    h = ce.parent;
  }
}

// All refcount transitions of potentially-cached pages go through these two
// so the blocker accounting can never drift from the refcounts.
void ref_page(Engine* e, int32_t page) {
  if (++e->refcount[page] == 2 && e->page_hash[page] != 0)
    add_blocker(e, e->page_hash[page]);  // first non-cache owner appeared
}

void deref_page(Engine* e, int32_t page) {
  if (--e->refcount[page] == 1 && e->page_hash[page] != 0)
    remove_blocker(e, e->page_hash[page]);  // only the cache's ref remains
  if (e->refcount[page] == 0) e->free_pages.push_back(page);
}

// Drop the LRU evictable cache entry (a leaf whose page has no owner but the
// cache itself).  Returns true if a page was freed.  O(log cache).
bool evict_one(Engine* e) {
  if (e->evictable.empty()) return false;
  auto it = e->evictable.begin();
  uint64_t h = it->second;
  CacheEntry ce = e->cache[h];
  e->evictable.erase(it);
  e->cache.erase(h);
  if (ce.parent != 0) {
    auto pit = e->cache.find(ce.parent);
    if (pit != e->cache.end()) {
      pit->second.children--;
      update_evictable(e, ce.parent, pit->second);
    }
  }
  e->page_hash[ce.page] = 0;
  e->refcount[ce.page] = 0;
  e->free_pages.push_back(ce.page);
  e->cache_evictions++;
  return true;
}

// Pop a free page, evicting cache leaves if needed. -1 if truly exhausted.
int32_t take_page(Engine* e) {
  if (e->free_pages.empty() && !evict_one(e)) return -1;
  int32_t p = e->free_pages.back();
  e->free_pages.pop_back();
  e->refcount[p] = 1;
  return p;
}

// How many cached pages leaf-first eviction could eventually reclaim: an
// entry is reclaimable iff neither it nor any descendant has an owner other
// than the cache.  O(1) via the incremental blocker accounting; the O(cache)
// recompute survives as eng_reclaimable_slow for invariant checks.
int32_t count_reclaimable(Engine* e) {
  return static_cast<int32_t>(e->cache.size()) -
         static_cast<int32_t>(e->blocked_count);
}

int32_t count_reclaimable_slow(Engine* e) {
  std::unordered_map<uint64_t, bool> blocked;
  for (const auto& it : e->cache) {
    if (e->refcount[it.second.page] > 1) {
      uint64_t h = it.first;
      while (h != 0) {
        if (blocked.count(h)) break;  // ancestors above are already marked
        blocked[h] = true;
        auto pit = e->cache.find(h);
        if (pit == e->cache.end()) break;
        h = pit->second.parent;
      }
    }
  }
  int32_t n = 0;
  for (const auto& it : e->cache)
    if (!blocked.count(it.first)) n++;
  return n;
}

}  // namespace

extern "C" {

Engine* eng_create(int32_t max_slots, int32_t num_pages, int32_t page_size,
                   int32_t max_pages_per_slot) {
  if (max_slots <= 0 || num_pages <= 0 || page_size <= 0 ||
      max_pages_per_slot <= 0)
    return nullptr;
  Engine* e = new Engine();
  e->max_slots = max_slots;
  e->num_pages = num_pages;
  e->page_size = page_size;
  e->max_pages_per_slot = max_pages_per_slot;
  e->slots.resize(max_slots);
  // Page 0 is RESERVED as the trash page and never allocated: the fused
  // decode step writes every slot's current-token KV unconditionally (static
  // shapes), and inactive/padded slots point at page 0 — reserving it makes
  // those writes harmless by construction.  Usable capacity: num_pages - 1.
  e->free_pages.reserve(num_pages - 1);
  for (int32_t p = num_pages - 1; p >= 1; --p) e->free_pages.push_back(p);
  e->refcount.assign(num_pages, 0);
  e->refcount[0] = 1;  // the trash page is permanently owned
  e->page_hash.assign(num_pages, 0);
  return e;
}

void eng_destroy(Engine* e) { delete e; }

// Enqueue a request. `hashes` (may be null) are chain hashes for the
// request's lookup-eligible full prompt pages, consulted at admit time.
// Returns 0, or -1 if the prompt can never fit.
int32_t eng_submit(Engine* e, int64_t req_id, int32_t prompt_len,
                   int32_t max_new_tokens, const uint64_t* hashes,
                   int32_t n_hashes) {
  std::lock_guard<std::mutex> lock(e->mu);
  // Admission is head-of-line: a request that exceeds either the per-slot cap
  // OR the whole page pool would block the queue forever — reject it here.
  if (pages_needed(e, prompt_len + max_new_tokens) > e->max_pages_per_slot ||
      pages_needed(e, prompt_len) >= e->num_pages)  // page 0 is reserved
    return -1;
  Request r{req_id, prompt_len, max_new_tokens, {}};
  if (hashes && n_hashes > 0) r.hashes.assign(hashes, hashes + n_hashes);
  e->queue.push_back(std::move(r));
  return 0;
}

// Admit the head-of-line request into a free slot if its prompt fits in free
// (or cache-evictable) pages.  Returns the slot id (prompt pages allocated,
// cache-hit prefix adopted) or -1; *out_cached = adopted page count.
int32_t eng_admit(Engine* e, int64_t* out_req_id, int32_t* out_prompt_len,
                  int32_t* out_max_new, int32_t* out_cached) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (out_cached) *out_cached = 0;
  if (e->queue.empty()) return -1;
  int32_t slot_id = -1;
  for (int32_t s = 0; s < e->max_slots; ++s)
    if (!e->slots[s].active) { slot_id = s; break; }
  if (slot_id < 0) return -1;
  Request& r = e->queue.front();
  // longest cached chain prefix; take refs so these pages are neither free
  // nor counted reclaimable below
  std::vector<int32_t> pages;
  for (uint64_t h : r.hashes) {
    auto it = e->cache.find(h);
    if (it == e->cache.end()) break;
    it->second.last_use = ++e->clock;
    // ref_page makes the entry blocked (refcount >= 2), which unfiles it
    // from the evictable index; the LRU touch lands when the last external
    // ref drops (deref_page -> remove_blocker -> update_evictable refiles
    // under the new last_use)
    ref_page(e, it->second.page);
    pages.push_back(it->second.page);
  }
  int32_t cached = static_cast<int32_t>(pages.size());
  int32_t need = pages_needed(e, r.prompt_len);
  int32_t need_new = need - cached;
  if (need_new > static_cast<int32_t>(e->free_pages.size()) + count_reclaimable(e)) {
    // cannot fit yet: undo the hit refs (pages stay cached) and leave the
    // request queued — deciding BEFORE evicting keeps a failed attempt from
    // wiping the evictable cache
    for (int32_t p : pages) deref_page(e, p);
    return -1;
  }
  for (int32_t i = 0; i < need_new; ++i) {
    int32_t p = take_page(e);
    if (p < 0) {  // unreachable per the check above; fail closed regardless
      for (int32_t q : pages) deref_page(e, q);
      return -1;
    }
    pages.push_back(p);
  }
  e->cache_hits += cached;
  e->cache_misses += need_new;
  Slot& slot = e->slots[slot_id];
  slot.active = true;
  slot.req_id = r.id;
  slot.seq_len = r.prompt_len;
  slot.generated = 0;
  slot.max_new_tokens = r.max_new_tokens;
  slot.pages = std::move(pages);
  *out_req_id = r.id;
  *out_prompt_len = r.prompt_len;
  *out_max_new = r.max_new_tokens;
  if (out_cached) *out_cached = cached;
  e->queue.pop_front();
  e->total_admitted++;
  return slot_id;
}

// Record one generated token for a slot, growing its KV by one position.
// Returns 1 = keep decoding, 0 = request finished (eos or budget),
// -2 = page pool exhausted (caller should preempt/release), -1 = bad slot.
// *out_new_page (may be null) reports the page id allocated by this commit,
// or -1 — the caller can mirror the page table incrementally instead of
// re-snapshotting max_slots x max_pages ints from C every tick.
int32_t eng_commit_token_ex(Engine* e, int32_t slot_id, int32_t is_eos,
                            int32_t* out_new_page) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (out_new_page) *out_new_page = -1;
  if (slot_id < 0 || slot_id >= e->max_slots) return -1;
  Slot& slot = e->slots[slot_id];
  if (!slot.active) return -1;
  int32_t need = pages_needed(e, slot.seq_len + 1);
  if (need > static_cast<int32_t>(slot.pages.size())) {
    if (need > e->max_pages_per_slot) return 0;  // hit the per-slot cap: done
    int32_t p = take_page(e);  // evicts cache leaves before giving up
    if (p < 0) return -2;
    slot.pages.push_back(p);
    if (out_new_page) *out_new_page = p;
  }
  slot.seq_len++;
  slot.generated++;
  if (is_eos || slot.generated >= slot.max_new_tokens) return 0;
  return 1;
}

int32_t eng_commit_token(Engine* e, int32_t slot_id, int32_t is_eos) {
  return eng_commit_token_ex(e, slot_id, is_eos, nullptr);
}

// Pre-allocate one more KV page for an active slot.  Speculative drafting
// needs every draft row's KV position inside OWNED pages, so near a page
// boundary the drafter reserves the next page before proposing past it
// (otherwise drafts clamp to the room left and boundary ticks degrade to
// single-token decode).  Returns the page id, -1 for a no-op (bad/inactive
// slot or per-slot cap), -2 when the pool is exhausted.  A later commit
// that crosses into the reserved page finds pages.size() already
// sufficient and allocates nothing, so the two paths compose.
int32_t eng_reserve_page(Engine* e, int32_t slot_id) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return -1;
  Slot& slot = e->slots[slot_id];
  if (!slot.active) return -1;
  if (static_cast<int32_t>(slot.pages.size()) >= e->max_pages_per_slot)
    return -1;
  int32_t p = take_page(e);
  if (p < 0) return -2;
  slot.pages.push_back(p);
  return p;
}

// Release a slot. `hashes` (may be null) are chain hashes for the slot's
// first `n_hashes` full PROMPT pages: any not yet cached are inserted into
// the prefix cache (the cache takes a ref) instead of going straight back to
// the free list; everything else just drops the slot's ref.
void eng_release_cached(Engine* e, int32_t slot_id, const uint64_t* hashes,
                        int32_t n_hashes) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return;
  Slot& slot = e->slots[slot_id];
  if (!slot.active) return;
  if (hashes) {
    int32_t n = n_hashes;
    if (n > static_cast<int32_t>(slot.pages.size()))
      n = static_cast<int32_t>(slot.pages.size());
    for (int32_t i = 0; i < n; ++i) {
      uint64_t h = hashes[i];
      if (h == 0) break;  // 0 is the root-parent sentinel, never a real hash
      if (e->cache.count(h)) continue;  // same prefix already cached elsewhere
      uint64_t parent = (i == 0) ? 0 : hashes[i - 1];
      auto pit = e->cache.find(parent);
      if (i > 0 && pit == e->cache.end()) break;  // keep chains contiguous
      if (pit != e->cache.end()) {
        pit->second.children++;
        update_evictable(e, parent, pit->second);
      }
      int32_t pg = slot.pages[i];
      e->refcount[pg]++;  // the cache's ref, on top of the slot's
      e->cache[h] = CacheEntry{pg, parent, 0, ++e->clock, 0, 0};
      e->page_hash[pg] = h;
      // the slot still holds its ref (refcount >= 2), so the new entry
      // starts blocked; the deref loop below unblocks it once only the
      // cache owns the page
      add_blocker(e, h);
    }
  }
  for (int32_t p : slot.pages) deref_page(e, p);
  slot.pages.clear();
  slot.active = false;
  slot.req_id = -1;
  slot.seq_len = 0;
  e->total_completed++;
}

void eng_release(Engine* e, int32_t slot_id) {
  eng_release_cached(e, slot_id, nullptr, 0);
}

// Snapshots for the JAX side (caller provides buffers).
void eng_page_table(Engine* e, int32_t* out /* max_slots*max_pages_per_slot */) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t s = 0; s < e->max_slots; ++s) {
    const Slot& slot = e->slots[s];
    for (int32_t i = 0; i < e->max_pages_per_slot; ++i) {
      out[s * e->max_pages_per_slot + i] =
          (slot.active && i < static_cast<int32_t>(slot.pages.size()))
              ? slot.pages[i]
              : 0;  // trash page: safe to write AND gather; masked by seq_lens
    }
  }
}

// One slot's page-table row (max_pages_per_slot ints, trash-page padded) —
// fetched once at admission; commits then grow the caller's mirror via
// eng_commit_token_ex's out_new_page.
void eng_slot_pages(Engine* e, int32_t slot_id, int32_t* out) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t i = 0; i < e->max_pages_per_slot; ++i) out[i] = 0;
  if (slot_id < 0 || slot_id >= e->max_slots) return;
  const Slot& slot = e->slots[slot_id];
  if (!slot.active) return;
  for (size_t i = 0; i < slot.pages.size(); ++i)
    out[i] = slot.pages[i];
}

// Reclaimable-page counts: the O(1) incremental counter the allocator uses,
// and the O(cache) recompute — exposed so tests (and the sanitizer stress
// driver) can assert they never drift.
int32_t eng_reclaimable(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  return count_reclaimable(e);
}

int32_t eng_reclaimable_slow(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  return count_reclaimable_slow(e);
}

void eng_seq_lens(Engine* e, int32_t* out /* max_slots */) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t s = 0; s < e->max_slots; ++s)
    out[s] = e->slots[s].active ? e->slots[s].seq_len : 0;
}

void eng_active_mask(Engine* e, int32_t* out /* max_slots */) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int32_t s = 0; s < e->max_slots; ++s)
    out[s] = e->slots[s].active ? 1 : 0;
}

int64_t eng_slot_req(Engine* e, int32_t slot_id) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return -1;
  return e->slots[slot_id].active ? e->slots[slot_id].req_id : -1;
}

int32_t eng_slot_seq_len(Engine* e, int32_t slot_id) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (slot_id < 0 || slot_id >= e->max_slots) return 0;
  return e->slots[slot_id].seq_len;
}

int32_t eng_num_free_pages(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  return static_cast<int32_t>(e->free_pages.size());
}

int32_t eng_queue_depth(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  return static_cast<int32_t>(e->queue.size());
}

int32_t eng_num_active(Engine* e) {
  std::lock_guard<std::mutex> lock(e->mu);
  int32_t n = 0;
  for (const Slot& s : e->slots) n += s.active ? 1 : 0;
  return n;
}

// out[0]=cached pages (== entries), out[1]=page hits, out[2]=page misses,
// out[3]=evictions.
void eng_cache_stats(Engine* e, int64_t* out /* 4 */) {
  std::lock_guard<std::mutex> lock(e->mu);
  out[0] = static_cast<int64_t>(e->cache.size());
  out[1] = e->cache_hits;
  out[2] = e->cache_misses;
  out[3] = e->cache_evictions;
}

}  // extern "C"
