"""Tiered, durable KV store: host RAM -> disk, with crash-recoverable
sessions (ISSUE 7).

PR 4's ``HostSwapStore`` was a flat byte-budgeted dict: preempted KV lived
in volatile host RAM, a watchdog restart erased it, and every multi-turn
request re-paid full prefill.  This module grows it into the real memory
hierarchy ROADMAP item 5 asks for — JetStream-style host-side tiering
(PAPERS.md): all spill/age/restore orchestration happens OFF the dispatch
critical path, the device only ever sees ordinary page scatters.

Two tiers, one budget each:

  * **host** — raw numpy blobs (pytrees of KV page slabs), the fast path.
    Over budget, the least-recently-used entry ages to disk ("spill") and
    its host copy is dropped; if it cannot be made durable the incoming
    put is REJECTED instead (the engine degrades to recompute — the store
    never drops bytes it already accepted to make room).
  * **disk** — checksummed, versioned page files (format below).  Over
    budget, unpinned (swap) entries are evicted first; pinned (session)
    entries are evicted LRU-last and only to make room for another pinned
    entry, so a swap flood cannot silently destroy conversations.

Durability and the failure model (the headline, not just capacity):

  * every restore is VERIFIED — magic/length checks catch torn writes,
    a CRC32 over the payload catches bit flips, a missing file is a miss;
    any of them makes the restore return ``("corrupt"|"miss", None)`` and
    the caller transparently falls back to recompute-from-prefix-cache.
    A lying tier can cost latency, never a failed request.
  * page files are written tmp-then-``os.replace`` (atomic on POSIX), so
    a crash mid-write leaves the previous version intact; each overwrite
    bumps the entry ``version`` and lands in a NEW file before the old
    one is unlinked.
  * pinned sessions are WRITTEN THROUGH to disk at pin time and recorded
    in a small atomic ``manifest.json``; a fresh engine pointed at the
    same ``disk_dir`` replays the manifest at boot and lazily re-adopts
    each session's pages on first touch (blob bytes are read + verified
    only when a turn actually asks for them).

Storage chaos (``faults.StorageChaos``) hooks the two byte streams —
``on_write``/``on_read`` — so torn writes, bit flips, slow disks and
ENOSPC-mid-spill are provoked deterministically in tier-1 tests; the
manifest itself is deliberately NOT chaos-wrapped (it is the recovery
index the faults are measured against, and it is tiny + atomic).

Page-file format (version 1)::

    b"KVPG" | u32 format_version | u32 header_len | header JSON | payload

where the header carries {key, spec, meta, nbytes, crc, version} and the
payload is the concatenated C-order bytes of the blob's array leaves in
``spec`` order.  ``spec`` is a minimal pytree schema (dict/tuple/list/
ndarray) so quantized pools (dict-of-arrays) round-trip without pickle.

Synchronous by design: puts/gets run on the engine loop thread at slot
admission/release — events that already fence the pipeline — and blobs
are MBs, not GBs.  A production deployment would push disk writes to a
background thread; the contract here is correctness under failure.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import struct
import tempfile
import threading
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .faults import StorageChaos, StorageFaultConfig

MAGIC = b"KVPG"
FORMAT_VERSION = 1
# sharded-layout frames (tensor-parallel KV, ISSUE 16): same magic, format
# version 2.  Degree-1 frames keep the version-1 layout byte-for-byte —
# pre-existing on-disk session files and fabric frames parse unchanged.
SHARDED_FORMAT_VERSION = 2
MANIFEST = "manifest.json"

# visible ASCII only: session ids are echoed into HTTP response headers
# (X-Session-Id), where CR/LF would split the response and non-latin-1
# would crash send_header mid-reply — and they key manifest records, so
# the charset must stay printable-diffable everywhere
_SID_OK = frozenset(chr(c) for c in range(0x21, 0x7f))


def normalize_session_id(session_id) -> str:
    """Validate a request ``session_id``: non-empty, <=256 chars, visible
    ASCII (no spaces/control chars — the id is echoed into response
    headers and recorded in the on-disk manifest).  Raises RequestError —
    the HTTP layer maps it to 400 — on anything else.  Note: session ids
    are bearer capabilities (whoever presents one can restore, extend, or
    drop that conversation's KV); deploy behind an authenticating ingress
    and use unguessable ids."""
    from ..errors import RequestError

    if (not isinstance(session_id, str) or not session_id
            or len(session_id) > 256
            or not all(c in _SID_OK for c in session_id)):
        raise RequestError(
            "session_id must be 1-256 visible-ASCII characters "
            f"(no spaces/control chars), got {session_id!r}")
    return session_id


@dataclasses.dataclass(frozen=True)
class KVStoreConfig:
    """Frozen tier budgets + placement (rides in the frozen EngineConfig).

    ``disk_dir=None`` creates a fresh private directory under the system
    tempdir — functional tiering but no cross-restart durability (there is
    no path for the next engine to find).  Point it somewhere stable to
    make sessions survive a full engine restart."""

    host_max_bytes: int = 1 << 30
    disk_max_bytes: int = 1 << 32
    disk_dir: Optional[str] = None
    # deterministic storage-fault injection (faults.StorageFaultConfig)
    chaos: Optional[StorageFaultConfig] = None


class KVStoreCorrupt(Exception):
    """A page file failed verification (torn/flipped/truncated/missing).
    Internal — callers of the store see a degraded return value, never
    this exception."""


# ------------------------------------------------------- blob serialization


def _flatten(obj, leaves: list):
    """Pytree -> JSON-able spec + ordered array leaves.  Deliberately
    supports only what KV blobs are made of (ndarray / dict / tuple /
    list) — no pickle, so a corrupted file can never execute anything."""
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        leaves.append(arr)
        return {"t": "a", "dtype": str(arr.dtype), "shape": list(arr.shape),
                "i": len(leaves) - 1}
    if isinstance(obj, dict):
        return {"t": "d", "k": {str(k): _flatten(obj[k], leaves)
                                for k in sorted(obj)}}
    if isinstance(obj, (tuple, list)):
        return {"t": "t" if isinstance(obj, tuple) else "l",
                "v": [_flatten(v, leaves) for v in obj]}
    raise TypeError(f"unsupported blob leaf type {type(obj).__name__}")


def _np_dtype(name: str) -> "np.dtype":
    try:
        return np.dtype(name)
    except TypeError:
        # accelerator dtypes (bfloat16 et al.) register via ml_dtypes and
        # are not constructible by bare name on older numpy
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _unflatten(spec: dict, leaves: list):
    t = spec["t"]
    if t == "a":
        return leaves[spec["i"]]
    if t == "d":
        return {k: _unflatten(v, leaves) for k, v in spec["k"].items()}
    vals = [_unflatten(v, leaves) for v in spec["v"]]
    return tuple(vals) if t == "t" else vals


def _crc_leaves_view(leaves) -> Tuple[int, int]:
    """(payload_bytes, crc32) over array leaves WITHOUT materializing
    byte copies: contiguous arrays re-view as uint8 (works for custom
    accelerator dtypes too — ``view`` needs no buffer protocol), with a
    ``tobytes`` fallback.  The warm-restore verification path runs this
    on the engine admission path, so a multi-hundred-MB session must not
    pay a transient 2x RAM copy per turn."""
    crc, total = 0, 0
    for a in leaves:
        flat = np.ascontiguousarray(a).reshape(-1)
        try:
            b = flat.view(np.uint8)
        except (TypeError, ValueError):
            b = flat.tobytes()
        crc = zlib.crc32(b, crc)
        total += flat.nbytes if not isinstance(b, bytes) else len(b)
    return total, crc


def _crc_blob(blob) -> Tuple[dict, list, int, int]:
    """-> (spec, leaves, payload_bytes, crc32).  The CRC is computed leaf
    by leaf in spec order — exactly the bytes a page file's payload holds —
    so host-resident and disk-resident copies verify against the same
    checksum without materializing one concatenated buffer twice."""
    leaves: list = []
    spec = _flatten(blob, leaves)
    crc, total = 0, 0
    for a in leaves:
        b = a.tobytes()
        crc = zlib.crc32(b, crc)
        total += len(b)
    return spec, leaves, total, crc


def _frame_bytes(header_fields: dict, leaves: list) -> bytes:
    """Assemble one KVPG frame: magic | format version | header length |
    header JSON | concatenated leaf payload.  The ONE framing routine
    behind both the disk tier's page files and the disaggregation
    handoff's wire format (serving/disagg.py) — torn/corrupt transfers
    are detected by the same verifier either way."""
    header = json.dumps(header_fields).encode()
    return (MAGIC + struct.pack("<II", FORMAT_VERSION, len(header))
            + header + b"".join(a.tobytes() for a in leaves))


def pack_frame(key: str, blob, meta: dict, version: int = 1) -> tuple:
    """Serialize a KV blob into a standalone KVPG frame ->
    ``(data, nbytes, crc)``.  Used for over-the-wire handoff blobs; the
    disk tier builds the identical bytes via :func:`_frame_bytes` from its
    entry bookkeeping."""
    spec, leaves, total, crc = _crc_blob(blob)
    data = _frame_bytes({
        "v": FORMAT_VERSION, "key": key, "spec": spec, "meta": dict(meta),
        "nbytes": total, "crc": crc, "version": version,
    }, leaves)
    return data, total, crc


def pack_sharded_frame(key: str, shard_blobs: list, meta: dict,
                       version: int = 1) -> tuple:
    """Serialize a tensor-parallel KV blob (list of per-shard pytrees, one
    per mesh position in kv-head order) into a sharded KVPG frame ->
    ``(data, nbytes, crc)``.

    Sharded frame format (version 2)::

        b"KVPG" | u32 2 | u32 header_len | outer JSON | sub0 | ... | subN-1

    The outer header carries {key, meta, shards: [len0..lenN-1], nbytes,
    version}; each sub-frame is a COMPLETE version-1 frame (own magic,
    header, CRC32) whose meta records {shard: i, degree: N}.  Integrity is
    per-sub-frame by design: a torn or flipped single-shard transfer fails
    ITS verifier and degrades exactly like today's torn unified frame,
    while the outer header's length table catches a truncated stream.
    ``nbytes`` sums the per-shard payload bytes (the accounting unit, same
    semantics as version 1); ``crc`` is a CRC32 over the sub-frame region.
    """
    degree = len(shard_blobs)
    subs = []
    total = 0
    for i, blob in enumerate(shard_blobs):
        sub, n, _ = pack_frame(f"{key}#{i}", blob,
                               {"shard": i, "degree": degree}, version)
        subs.append(sub)
        total += n
    body = b"".join(subs)
    header = json.dumps({
        "v": SHARDED_FORMAT_VERSION, "key": key, "meta": dict(meta),
        "shards": [len(s) for s in subs], "nbytes": total,
        "version": version,
    }).encode()
    data = (MAGIC + struct.pack("<II", SHARDED_FORMAT_VERSION, len(header))
            + header + body)
    return data, total, zlib.crc32(body)


def _unpack_sharded(data: bytes, header: dict):
    """Verify + parse the sub-frames of a version-2 frame ->
    ``(shard_blobs, header)``; the degree is ``len(header["shards"])``."""
    shards = header.get("shards")
    if not isinstance(shards, list) or not shards:
        raise KVStoreCorrupt("corrupt sharded header: no shard table")
    degree = len(shards)
    blobs, off = [], 0
    for i, n in enumerate(shards):
        sub = data[off:off + n]
        if len(sub) != n:
            raise KVStoreCorrupt(
                f"torn write: shard {i} truncated ({len(sub)} != {n})")
        try:
            blob, sub_header = unpack_frame(sub)
        except KVStoreCorrupt as exc:
            raise KVStoreCorrupt(f"shard {i}: {exc}") from exc
        sm = sub_header.get("meta", {})
        if sm.get("shard") != i or sm.get("degree") != degree:
            raise KVStoreCorrupt(
                f"shard {i}: layout mismatch (shard={sm.get('shard')} "
                f"degree={sm.get('degree')} expected {i}/{degree})")
        blobs.append(blob)
        off += n
    if off != len(data):
        raise KVStoreCorrupt(
            f"torn write: {len(data) - off} trailing bytes after shards")
    return blobs, header


def blob_degree(blob) -> int:
    """Mesh degree of a KV blob: a list is per-shard (one entry per mesh
    position), anything else is a unified degree-1 blob."""
    return len(blob) if isinstance(blob, list) else 1


def reshard_blob(blob, degree: int):
    """Host-side layout conversion between mesh degrees — the EXPLICIT slow
    path for cross-degree import (counted by the caller, never silent).
    Concatenates per-shard blocks along the kv-head axis (axis 2 of every
    pool leaf, scales included) and re-splits into ``degree`` blocks.
    Returns a unified pytree for ``degree<=1``, else a per-shard list.
    Raises ValueError when the kv-head axis does not divide."""
    shards = blob if isinstance(blob, list) else [blob]
    if len(shards) == degree > 1:
        return shards
    unified = shards[0] if len(shards) == 1 else _tree_zip(
        lambda *parts: np.concatenate(parts, axis=2), *shards)
    if degree <= 1:
        return unified
    def cut(i):
        def f(a):
            if a.shape[2] % degree:
                raise ValueError(
                    f"kv-head axis {a.shape[2]} not divisible by "
                    f"degree {degree}")
            h = a.shape[2] // degree
            return np.ascontiguousarray(a[:, :, i * h:(i + 1) * h])
        return f
    return [_tree_zip(cut(i), unified) for i in range(degree)]


def _tree_zip(fn, *trees):
    """Map ``fn`` over aligned leaves of same-structure KV blob pytrees
    (the _flatten subset: ndarray / dict / tuple / list)."""
    t0 = trees[0]
    if isinstance(t0, np.ndarray):
        return fn(*trees)
    if isinstance(t0, dict):
        return {k: _tree_zip(fn, *[t[k] for t in trees]) for k in sorted(t0)}
    if isinstance(t0, (tuple, list)):
        out = [_tree_zip(fn, *[t[i] for t in trees]) for i in range(len(t0))]
        return tuple(out) if isinstance(t0, tuple) else out
    raise TypeError(f"unsupported blob leaf type {type(t0).__name__}")


def unpack_frame(data: bytes):
    """Parse + VERIFY one KVPG frame -> ``(blob, header)``.  Raises
    :class:`KVStoreCorrupt` on any verification failure — bad magic /
    truncated header (torn transfer), payload length mismatch, CRC32
    mismatch (bit flip), unsupported format version.  Version-2 (sharded)
    frames return ``(shard_blobs, header)`` — a LIST of per-shard pytrees —
    with each sub-frame verified by its own CRC."""
    if len(data) < 12 or data[:4] != MAGIC:
        raise KVStoreCorrupt("bad magic (torn write?)")
    ver, hlen = struct.unpack("<II", data[4:12])
    if ver not in (FORMAT_VERSION, SHARDED_FORMAT_VERSION):
        raise KVStoreCorrupt(f"unsupported format version {ver}")
    if len(data) < 12 + hlen:
        raise KVStoreCorrupt("torn write: truncated header")
    try:
        header = json.loads(data[12:12 + hlen])
    except ValueError as exc:
        raise KVStoreCorrupt(f"corrupt header: {exc}") from exc
    if ver == SHARDED_FORMAT_VERSION:
        return _unpack_sharded(data[12 + hlen:], header)
    payload = data[12 + hlen:]
    if len(payload) != header["nbytes"]:
        raise KVStoreCorrupt(
            f"torn write: payload {len(payload)} != {header['nbytes']}")
    if zlib.crc32(payload) != header["crc"]:
        raise KVStoreCorrupt("checksum mismatch (bit flip?)")
    leaves, off = [], 0
    for leaf_spec in _iter_array_specs(header["spec"]):
        dt = _np_dtype(leaf_spec["dtype"])
        n = int(np.prod(leaf_spec["shape"], dtype=np.int64)) * dt.itemsize
        arr = np.frombuffer(payload[off:off + n], dtype=dt)
        leaves.append(arr.reshape(leaf_spec["shape"]))
        off += n
    return _unflatten(header["spec"], leaves), header


@dataclasses.dataclass
class _Entry:
    key: str
    nbytes: int          # host-copy payload bytes (host budget charge unit)
    crc: int
    pinned: bool         # session entries: durable, eviction-protected
    seq: int             # LRU clock (monotonic touch counter)
    version: int = 1
    blob: object = None  # host-tier copy (None = aged out / never adopted)
    meta: dict = dataclasses.field(default_factory=dict)
    # False for opaque caller blobs (non-pytree): host-resident only,
    # unverifiable, never spillable — the pre-tiering HostSwapStore accepted
    # arbitrary objects and the compat facade keeps that contract
    serializable: bool = True
    # durable snapshot {path, nbytes, crc, version, meta} — DECOUPLED from
    # the host copy: a degraded re-pin (new disk write failed) keeps the
    # PREVIOUS version's page file here while the host tier serves the new
    # one, so a restart still recovers the older, shorter context instead
    # of nothing.  None = no disk copy.  disk["nbytes"] is the disk budget
    # charge unit.
    disk: Optional[dict] = None


class TieredKVStore:
    """The engine's KV backing store: swap (preemption) and session
    (cross-turn pinning) entries over a host-RAM tier aging to a disk tier
    of checksummed page files.  Thread-safe; every public method takes the
    store lock (slow-disk chaos therefore serializes against scrapes —
    acceptable for a correctness substrate, see module docstring)."""

    def __init__(self, config: KVStoreConfig = KVStoreConfig(),
                 on_event: Optional[Callable[[str, str], None]] = None):
        self.config = config
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._seq = 0
        self._on_event = on_event
        self.chaos = (StorageChaos(config.chaos)
                      if config.chaos is not None else None)
        self.host_used = 0
        self.disk_used = 0
        self._disk_enabled = config.disk_max_bytes > 0
        self._disk_dir: Optional[str] = None
        # a private auto-created dir is EPHEMERAL: created LAZILY on the
        # first disk write (most engines never spill or pin, and must not
        # litter the tempdir with empty dirs) and deleted by close() — no
        # future store could ever find it again.  An explicit disk_dir is
        # the durability contract: created now, manifest replayed, and
        # always survives close().
        self._ephemeral = self._disk_enabled and config.disk_dir is None
        if self._disk_enabled and config.disk_dir is not None:
            self._disk_dir = config.disk_dir
            os.makedirs(self._disk_dir, exist_ok=True)
        # ---- swap accounting (Engine.stats compat keys; reset on engine
        # restart via clear_swap so a new epoch never reports phantom
        # traffic from before the restart)
        self.swapped_out = 0
        self.swapped_in = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.rejected = 0
        # ---- tier/session counters (monotonic across restarts)
        self.spills = 0            # host copies aged to disk
        self.host_evictions = 0    # host copies dropped (disk copy existed)
        self.disk_evictions = 0    # unpinned disk entries evicted for room
        self.session_evictions = 0  # pinned sessions evicted under pressure
        self.verify_failures = 0   # torn/flipped/missing at restore
        self.restores = {"host": 0, "disk": 0}
        self.pins = 0
        self.last_evicted_sessions: List[str] = []
        if self._disk_dir:
            self._load_manifest()

    # ------------------------------------------------------------ internals

    def _event(self, tier: str, event: str) -> None:
        if self._on_event is not None:
            try:
                self._on_event(tier, event)
            except Exception:  # noqa: BLE001 — metrics must not sink the store
                pass

    def _touch(self, e: _Entry) -> None:
        self._seq += 1
        e.seq = self._seq

    def _ensure_disk_dir(self) -> str:
        if self._disk_dir is None:
            self._disk_dir = tempfile.mkdtemp(prefix="engine_kvstore_")
        return self._disk_dir

    def _file_for(self, key: str, version: int) -> str:
        safe = hashlib.sha1(key.encode()).hexdigest()[:16]
        return os.path.join(self._ensure_disk_dir(),
                            f"{safe}-v{version}.kvpg")

    def _write_file(self, e: _Entry, spec: dict, leaves: list) -> None:
        """Serialize + atomically land one entry's CURRENT host state as a
        page file, then swing ``e.disk`` to the new snapshot (old file
        unlinked only after the new one is fully visible — there is no
        crash instant with neither on disk).  Raises OSError (incl.
        injected ENOSPC) on failure, leaving ``e.disk`` untouched; the tmp
        file never becomes visible.  A chaos torn write truncates the byte
        stream BEFORE the atomic rename — modeling a write the filesystem
        acknowledged but never fully persisted (the crash-consistency
        case the verifier exists for).  Caller owns disk_used accounting."""
        data = _frame_bytes({
            "v": FORMAT_VERSION, "key": e.key, "spec": spec, "meta": e.meta,
            "nbytes": e.nbytes, "crc": e.crc, "version": e.version,
        }, leaves)
        if self.chaos is not None:
            data = self.chaos.on_write(data)  # may truncate or raise ENOSPC
        path = self._file_for(e.key, e.version)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        old = e.disk
        e.disk = {"path": path, "nbytes": e.nbytes, "crc": e.crc,
                  "version": e.version, "meta": dict(e.meta)}
        if old and old["path"] != path:
            try:
                os.unlink(old["path"])
            except OSError:
                pass

    def _read_file(self, e: _Entry):
        """Load + verify one entry's page file -> (blob, header).  Raises
        KVStoreCorrupt on ANY verification failure (missing, torn,
        truncated, bit-flipped, header mismatch).  The header carries the
        file's OWN meta/nbytes/version — which may lag the entry's host
        state by a version after a degraded re-pin."""
        if not e.disk:
            raise KVStoreCorrupt("no disk copy")
        try:
            with open(e.disk["path"], "rb") as f:
                data = f.read()
        except OSError as exc:
            raise KVStoreCorrupt(f"missing/unreadable file: {exc}") from exc
        if self.chaos is not None:
            data = self.chaos.on_read(data)  # may sleep or flip a bit
        return unpack_frame(data)

    def _drop(self, e: _Entry, unlink: bool = True) -> None:
        """Remove an entry entirely, releasing both tiers' budget."""
        if e.blob is not None:
            self.host_used -= e.nbytes
            e.blob = None
        if e.disk:
            self.disk_used -= e.disk["nbytes"]
            if unlink:
                try:
                    os.unlink(e.disk["path"])
                except OSError:
                    pass
            e.disk = None
        self._entries.pop(e.key, None)

    def _demote(self, e: _Entry) -> bool:
        """Age one entry's host copy to disk (write-if-absent-or-stale,
        then drop the RAM copy).  False when the CURRENT version cannot be
        made durable — the caller must NOT drop the host copy in that
        case (a stale durable snapshot is kept, never silently served in
        place of the newer host bytes)."""
        if e.disk is None or e.disk["version"] != e.version:
            if not self._disk_enabled or not e.serializable:
                return False
            old_charge = e.disk["nbytes"] if e.disk else 0
            if not self._make_disk_room(e.nbytes - old_charge,
                                        for_pinned=e.pinned, keep=e.key):
                return False
            spec, leaves, total, crc = _crc_blob(e.blob)
            e.crc, e.nbytes = crc, total  # recompute defensively
            try:
                self._write_file(e, spec, leaves)
            except OSError:
                return False
            self.disk_used += e.nbytes - old_charge
            self.spills += 1
            self._event("disk", "spill")
            if e.pinned:
                # a session that just became durable (its pin had degraded
                # to host-only) must reach the recovery manifest too
                self._save_manifest()
        else:
            self.host_evictions += 1
            self._event("host", "evict")
        self.host_used -= e.nbytes
        e.blob = None
        return True

    def _make_host_room(self, n: int, keep: Optional[str] = None) -> bool:
        while self.host_used + n > self.config.host_max_bytes:
            cands = [e for e in self._entries.values()
                     if e.blob is not None and e.key != keep]
            if not cands:
                return False
            victim = min(cands, key=lambda e: e.seq)
            if not self._demote(victim):
                return False
        return True

    def _make_disk_room(self, n: int, for_pinned: bool,
                        keep: Optional[str] = None,
                        evicted_out: Optional[List[str]] = None) -> bool:
        """Evict disk entries until ``n`` bytes fit: unpinned (swap spill)
        LRU first; pinned sessions only yield to ANOTHER pinned entry —
        and then LRU among sessions, the eviction-ordering contract the
        tier-1 suite asserts.  ``keep`` (a key) is never a victim — a
        session re-pin must not evict its own previous version out from
        under the crash-safe replace sequence.  A pinned eviction rewrites
        the manifest IMMEDIATELY: even if the operation that wanted the
        room subsequently fails, the manifest never points at an unlinked
        file (a restart would otherwise replay a phantom session and
        charge its bytes against the disk budget forever)."""
        while self.disk_used + n > self.config.disk_max_bytes:
            cands = [e for e in self._entries.values()
                     if e.disk and e.key != keep]
            unpinned = [e for e in cands if not e.pinned]
            pool = unpinned or ([e for e in cands if e.pinned]
                                if for_pinned else [])
            if not pool:
                return False
            victim = min(pool, key=lambda e: e.seq)
            if victim.pinned:
                self.session_evictions += 1
                sid = victim.key.split("/", 1)[-1]
                # per-call report for the caller (pin_session's eviction
                # count) PLUS the bounded ops ring — the ring's trim must
                # not be the caller's bookkeeping, or the per-pin report
                # goes permanently empty once 16 lifetime evictions have
                # accumulated (exactly when pressure is highest)
                if evicted_out is not None:
                    evicted_out.append(sid)
                self.last_evicted_sessions.append(sid)
                del self.last_evicted_sessions[:-16]
                self._event("disk", "session_evict")
                self._drop(victim)
                self._save_manifest()
            else:
                self.disk_evictions += 1
                self._event("disk", "evict")
                self._drop(victim)
        return True

    # -------------------------------------------------------------- manifest

    def _save_manifest(self) -> None:
        """Atomic session index for restart recovery (call with the lock
        held).  Deliberately not chaos-wrapped: it is the recovery index
        the injected page-file faults are measured against."""
        if not self._disk_enabled or self._disk_dir is None:
            return
        sessions = {}
        for e in self._entries.values():
            if e.pinned and e.disk:
                # record the DURABLE snapshot (possibly older than the
                # host copy after a degraded re-pin) — it is what a
                # restart can actually read back
                sessions[e.key] = {
                    "file": os.path.basename(e.disk["path"]),
                    "nbytes": e.disk["nbytes"], "crc": e.disk["crc"],
                    "version": e.disk["version"], "meta": e.disk["meta"],
                }
        path = os.path.join(self._disk_dir, MANIFEST)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"v": 1, "sessions": sessions}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _load_manifest(self) -> None:
        """Replay the session index at boot: entries register disk-only
        (blob=None) and their bytes are read + verified lazily on first
        touch — engine boot never blocks on (or trusts) old page files."""
        path = os.path.join(self._disk_dir, MANIFEST)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        for key, rec in (data.get("sessions") or {}).items():
            try:
                path = os.path.join(self._disk_dir, rec["file"])
                if not os.path.exists(path):
                    # wiped behind our back: registering it would only
                    # charge phantom bytes against the disk budget — the
                    # session is a plain miss either way
                    continue
                e = _Entry(key=key, nbytes=int(rec["nbytes"]),
                           crc=int(rec["crc"]), pinned=True, seq=0,
                           version=int(rec.get("version", 1)),
                           meta=dict(rec.get("meta") or {}),
                           disk={"path": path,
                                 "nbytes": int(rec["nbytes"]),
                                 "crc": int(rec["crc"]),
                                 "version": int(rec.get("version", 1)),
                                 "meta": dict(rec.get("meta") or {})})
            except (KeyError, TypeError, ValueError):
                continue  # one bad record must not sink recovery
            self._entries[key] = e
            self.disk_used += e.disk["nbytes"]

    # ------------------------------------------------------------- swap API

    def put_swap(self, rid: int, blob, nbytes: int,
                 count: bool = True) -> bool:
        """Host-tier insert for a preempted slot's KV (spilling LRU
        entries to disk for room).  False = could not fit anywhere; the
        engine falls back to drop-and-recompute.  ``nbytes`` is advisory
        (the caller's tree-size estimate); for array pytrees the
        serialized payload size is what the budgets charge.  Opaque
        (non-pytree) blobs are accepted at face value for the pre-tiering
        HostSwapStore contract — host-resident, unspillable.
        ``count=False`` skips the swap-traffic counters: a disaggregation
        KV import parks its pulled blob here for the admission path to
        scatter (engine.py), and stats must not report it as preemption
        swap the engine never performed."""
        key = f"swap/{rid}"
        try:
            _, _, total, crc = _crc_blob(blob)
            serializable = True
        except TypeError:
            total, crc, serializable = int(nbytes), 0, False
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                self._drop(old)
            if not self._make_host_room(total, keep=key):
                self.rejected += 1
                self._event("host", "reject")
                return False
            self._seq += 1
            self._entries[key] = _Entry(
                key=key, nbytes=total, crc=crc, pinned=False,
                seq=self._seq, blob=blob, serializable=serializable)
            self.host_used += total
            if count:
                self.swapped_out += 1
                self.bytes_out += total
            self._event("host", "put")
            return True

    def pop_swap(self, rid: int, count: bool = True):
        """-> (blob, nbytes) or None; removes the entry and releases its
        budget.  A disk-resident blob is read + verified; verification
        failure returns None (the engine's existing blob-lost path
        recomputes from the committed context).  ``count=False``: the
        handoff-import twin of ``put_swap(count=False)``."""
        key = f"swap/{rid}"
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            blob = e.blob
            if blob is None:
                try:
                    blob, _ = self._read_file(e)
                    self._event("disk", "hit")
                except KVStoreCorrupt:
                    self.verify_failures += 1
                    self._event("disk", "verify_fail")
                    self._drop(e)
                    return None
            else:
                self._event("host", "hit")
            nbytes = e.nbytes
            self._drop(e)
            if count:
                self.swapped_in += 1
                self.bytes_in += nbytes
            return blob, nbytes

    def discard_swap(self, rid: int) -> None:
        """Drop a swap blob without the swap-in accounting (terminal
        request)."""
        with self._lock:
            e = self._entries.get(f"swap/{rid}")
            if e is not None:
                self._drop(e)

    def clear_swap(self) -> None:
        """Engine-restart reconciliation: every swap blob belongs to a
        pre-restart epoch (its request was failed wholesale), so drop
        them AND reset the swap counters — post-restart ``stats`` must
        not report phantom swap traffic the new epoch never performed.
        Pinned sessions are untouched: they are durable state, exactly
        what must SURVIVE a restart."""
        with self._lock:
            for e in [e for e in self._entries.values() if not e.pinned]:
                self._drop(e)
            self.swapped_out = 0
            self.swapped_in = 0
            self.bytes_out = 0
            self.bytes_in = 0
            self.rejected = 0

    # ---------------------------------------------------------- session API

    def pin_session(self, sid: str, blob, nbytes: int, meta: dict) -> dict:
        """Pin one finished turn's KV pages under ``sid``: host-tier copy
        for the fast next turn plus a write-through page file + manifest
        record for durability.  Replaces any previous pin crash-safely:
        the new version lands in its OWN file before the old entry (and
        file) is dropped, so there is no instant with neither on disk.
        Degrades, never raises: when the new disk write fails (no room /
        ENOSPC) the new context is still served from the host tier while
        the PREVIOUS version's durable snapshot is CARRIED OVER — a
        restart recovers the older, shorter context rather than nothing
        (``durable: False``, ``stale_durable: True``).  No host room
        either -> the previous pin is kept untouched and this turn
        reports ``pinned: False``."""
        key = f"session/{sid}"
        spec, leaves, total, crc = _crc_blob(blob)
        with self._lock:
            evicted: List[str] = []
            old = self._entries.get(key)
            version = (old.version + 1) if old is not None else 1
            self._seq += 1
            e = _Entry(key=key, nbytes=total, crc=crc, pinned=True,
                       seq=self._seq, version=version, blob=None,
                       meta=dict(meta))
            error = None
            durable = False
            # the old version's charges are released moments from now
            # (its entry drops once the new version lands), so room-making
            # must DISCOUNT them — otherwise a session larger than half a
            # budget could never re-pin into that tier (the old copy is
            # both charged and, via keep=key, un-evictable)
            old_disk_charge = old.disk["nbytes"] if (old and old.disk) else 0
            old_host_charge = (old.nbytes
                               if (old and old.blob is not None) else 0)
            if not self._disk_enabled:
                error = "disk tier disabled"
            elif not self._make_disk_room(total - old_disk_charge,
                                          for_pinned=True, keep=key,
                                          evicted_out=evicted):
                error = "disk budget exhausted"
            else:
                try:
                    self._write_file(e, spec, leaves)  # sets e.disk
                    durable = True
                except OSError as exc:
                    error = f"{type(exc).__name__}: {exc}"
            host = self._make_host_room(total - old_host_charge, keep=key)
            if not host and not durable:
                # total failure: keep the previous pin untouched (best
                # available state — incl. its durable copy and manifest
                # record); the orphaned new-version file cannot exist
                # here (durable would be True)
                self._event("host", "reject")
                return {"pinned": False, "durable": False,
                        "evicted": evicted,
                        "error": error or "host budget exhausted"}
            stale_durable = False
            if old is not None:
                if not durable and old.disk is not None:
                    # carry the previous version's durable snapshot: a
                    # restart restores the older, shorter context (its
                    # hashes are a prefix of the new one) instead of
                    # losing the conversation outright
                    e.disk, old.disk = old.disk, None
                    stale_durable = True
                self._drop(old)  # releases old host (+ old disk if kept)
            if durable:
                self.disk_used += total
            if host:
                e.blob = blob
                self.host_used += total
            self._entries[key] = e
            self.pins += 1
            self._event("host" if host else "disk", "pin")
            self._save_manifest()
            return {"pinned": True, "durable": durable,
                    "stale_durable": stale_durable, "evicted": evicted,
                    "error": error, "nbytes": total, "version": version}

    def restore_session(self, sid: str):
        """-> (outcome, payload): outcome in {"host", "disk", "miss",
        "corrupt"}; payload = (blob, nbytes, meta) on a hit, else None.
        The entry STAYS pinned (the turn's finish re-pins the longer
        context).  A disk hit serves the FILE's own meta (the durable
        snapshot may lag the host state by a version after a degraded
        re-pin) and re-adopts the blob into the host tier only when it
        fits WITHOUT displacing anything and matches the entry's current
        version — lazy promotion must never trigger spill I/O on the
        admission path nor alias stale bytes under fresh metadata."""
        key = f"session/{sid}"
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._event("host", "miss")
                return "miss", None
            self._touch(e)
            if e.blob is not None:
                # paranoia-verify the RAM copy too: "every restore is
                # verified" includes the fast path (copy-free: uint8
                # views, not tobytes — this runs per warm turn)
                leaves: list = []
                _flatten(e.blob, leaves)
                total, crc = _crc_leaves_view(leaves)
                if total == e.nbytes and crc == e.crc:
                    self.restores["host"] += 1
                    self._event("host", "hit")
                    return "host", (e.blob, e.nbytes, dict(e.meta))
                self.verify_failures += 1
                self._event("host", "verify_fail")
                self.host_used -= e.nbytes
                e.blob = None  # fall through to the disk copy, if any
            try:
                blob, header = self._read_file(e)
            except KVStoreCorrupt:
                self.verify_failures += 1
                self._event("disk", "verify_fail")
                self._drop(e)
                self._save_manifest()
                return "corrupt", None
            if (header["version"] == e.version
                    and self.host_used + e.nbytes
                    <= self.config.host_max_bytes):
                e.blob = blob
                self.host_used += e.nbytes
            self.restores["disk"] += 1
            self._event("disk", "hit")
            return "disk", (blob, header["nbytes"], dict(header["meta"]))

    def drop_session(self, sid: str) -> bool:
        with self._lock:
            e = self._entries.get(f"session/{sid}")
            if e is None:
                return False
            self._drop(e)
            self._save_manifest()
            return True

    def session_list(self) -> dict:
        with self._lock:
            out = {}
            for e in self._entries.values():
                if not e.pinned:
                    continue
                sid = e.key.split("/", 1)[-1]
                out[sid] = {
                    "nbytes": e.nbytes, "version": e.version,
                    "tiers": [t for t, ok in (("host", e.blob is not None),
                                              ("disk", bool(e.disk)))
                              if ok],
                    "context_len": e.meta.get("context_len"),
                    "pages": e.meta.get("pages"),
                }
            return out

    # -------------------------------------------------------------- surface

    @property
    def disk_dir(self) -> Optional[str]:
        return self._disk_dir

    def close(self) -> None:
        """Release the store (Engine.stop calls this): host memory is
        freed; an EPHEMERAL private disk dir (``disk_dir=None`` in the
        config) is deleted outright — no future store could ever find it,
        so keeping its page files would only orphan bytes in the tempdir.
        An explicit ``disk_dir`` keeps its page files and manifest: that
        path IS the durability contract a restarted engine recovers
        from."""
        with self._lock:
            for e in list(self._entries.values()):
                if e.blob is not None:
                    e.blob = None
            self._entries.clear()
            self.host_used = 0
            if self._ephemeral and self._disk_dir:
                shutil.rmtree(self._disk_dir, ignore_errors=True)
                self._disk_dir = None
                self.disk_used = 0

    def stats(self) -> dict:
        with self._lock:
            pinned = [e for e in self._entries.values() if e.pinned]
            swap_bytes = sum(e.nbytes for e in self._entries.values()
                             if not e.pinned)
            return {
                # PR 4 compat keys (preemption swap traffic)
                "swap_used_bytes": swap_bytes,
                "swapped_out": self.swapped_out,
                "swapped_in": self.swapped_in,
                "swap_bytes_out": self.bytes_out,
                "swap_bytes_in": self.bytes_in,
                "swap_rejected": self.rejected,
                # tiered-store surface (ISSUE 7)
                "kv_host_used_bytes": self.host_used,
                "kv_disk_used_bytes": self.disk_used,
                "kv_spills": self.spills,
                "kv_host_evictions": self.host_evictions,
                "kv_disk_evictions": self.disk_evictions,
                "kv_verify_failures": self.verify_failures,
                "sessions_pinned": len(pinned),
                "session_bytes": sum(e.nbytes for e in pinned),
                "session_evictions": self.session_evictions,
                "session_pins": self.pins,
                "session_restores": dict(self.restores),
                **({"storage_chaos": self.chaos.stats()}
                   if self.chaos is not None else {}),
            }


def _iter_array_specs(spec: dict):
    """Array leaf specs in index order (the payload's layout order)."""
    out: list = []

    def walk(s):
        if s["t"] == "a":
            out.append(s)
        elif s["t"] == "d":
            for v in s["k"].values():
                walk(v)
        else:
            for v in s["v"]:
                walk(v)

    walk(spec)
    out.sort(key=lambda s: s["i"])
    return out


class HostSwapStore:
    """PR 4 compatibility facade: the old flat host-RAM swap interface,
    now backed by a host-only ``TieredKVStore`` (disk tier disabled, so a
    put past the budget rejects exactly as before)."""

    def __init__(self, max_bytes: int = 1 << 30):
        self._kv = TieredKVStore(
            KVStoreConfig(host_max_bytes=max_bytes, disk_max_bytes=0))
        self.max_bytes = max_bytes

    @property
    def used_bytes(self) -> int:
        return self._kv.host_used

    @property
    def rejected(self) -> int:
        return self._kv.rejected

    def put(self, rid: int, blob, nbytes: int) -> bool:
        return self._kv.put_swap(rid, blob, nbytes)

    def pop(self, rid: int):
        return self._kv.pop_swap(rid)

    def discard(self, rid: int) -> None:
        self._kv.discard_swap(rid)

    def clear(self) -> None:
        self._kv.clear_swap()

    def stats(self) -> dict:
        s = self._kv.stats()
        return {k: s[k] for k in ("swap_used_bytes", "swapped_out",
                                  "swapped_in", "swap_bytes_out",
                                  "swap_bytes_in", "swap_rejected")}
