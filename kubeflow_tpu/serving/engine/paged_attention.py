"""Paged attention: Pallas TPU decode kernel over the KV page pool.

Role (SURVEY.md §2b Triton row, §3.4 hot path): the decode-step attention of
the JetStream-class engine.  The XLA path in model.py gathers each slot's
pages into a contiguous [B, T, Hkv, hd] cache every step — that gather WRITES
a full KV copy to HBM before attention reads it back, tripling the memory
traffic of the step's roofline term.  This kernel instead walks the pool
pages in place, one page per grid step, with the page ids scalar-prefetched
(``pltpu.PrefetchScalarGridSpec``) so the data-dependent page lookup happens
in the BlockSpec index_map, not as an HBM gather.

Design (pallas_guide.md):
  * grid = (slots, kv_heads, max_pages); the last axis is sequential on TPU,
    so the online-softmax accumulator lives in VMEM scratch across page
    steps and the output is written on the final page;
  * GQA: the q block per (slot, kv head) is the [group, hd] bundle of query
    heads sharing that KV head;
  * pages past the slot's length are masked per-position and skipped as
    whole blocks via ``pl.when`` (no FLOPs for dead pages — the paged
    analogue of flash attention's causal block skip);
  * ``interpret=`` auto-selects: compiled on TPU, interpreter on the CPU
    test mesh, same numerics either way.

Engine integration is env-gated (``ENGINE_PAGED_KERNEL=1``): the XLA gather
path stays the default until the kernel is re-validated on real hardware
(the TPU tunnel was down for all of round 2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


def _auto_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _kernel(page_table_ref, seq_lens_ref,  # scalar-prefetch (SMEM)
            q_ref, k_ref, v_ref, o_ref,    # blocks
            acc_ref, m_ref, l_ref,         # VMEM scratch
            *, page_size, scale):
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_pages = pl.num_programs(2)
    seq_len = seq_lens_ref[b]

    @pl.when(j == 0)
    def _reset():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # whole pages past the sequence contribute nothing: skip their FLOPs
    @pl.when(j * page_size < seq_len)
    def _page():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [group, hd]
        k = k_ref[0, :, 0, :].astype(jnp.float32)            # [ps, hd]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                    # [group, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        logits = jnp.where(pos < seq_len, logits, NEG_INF)
        m_new = jnp.maximum(m_ref[...], logits.max(axis=1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_ref[...] - m_new)
        l_ref[...] = l_ref[...] * corr + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(j == num_pages - 1)
    def _write():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention(q, k_pool, v_pool, page_table, seq_lens,
                           page_size: int, interpret: bool | None = None):
    """One decode step of attention directly over the page pool.

    q: [B, Hq, hd] (current token per slot); k_pool/v_pool:
    [P, page_size, Hkv, hd] (ONE layer's pool); page_table: [B, max_pages]
    int32; seq_lens: [B] int32 (0 = inactive slot → zeros out).
    Returns [B, Hq, hd].
    """
    if interpret is None:
        interpret = _auto_interpret()
    B, Hq, hd = q.shape
    Hkv = k_pool.shape[2]
    group = Hq // Hkv
    max_pages = page_table.shape[1]
    scale = hd ** -0.5
    # [B, Hq, hd] -> [B, Hkv, group, hd]: queries grouped by their KV head
    qg = q.reshape(B, Hkv, group, hd)

    grid = (B, Hkv, max_pages)
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=page_size, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # page_table, seq_lens
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, group, hd), lambda b, h, j, pt, sl: (b, h, 0, 0)),
                # the data-dependent page lookup: block = pool page pt[b, j]
                pl.BlockSpec((1, page_size, 1, hd), lambda b, h, j, pt, sl: (pt[b, j], 0, h, 0)),
                pl.BlockSpec((1, page_size, 1, hd), lambda b, h, j, pt, sl: (pt[b, j], 0, h, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, group, hd), lambda b, h, j, pt, sl: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, hd), jnp.float32),
                pltpu.VMEM((group, 1), jnp.float32),
                pltpu.VMEM((group, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, group, hd), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, qg, k_pool, v_pool)
    return out.reshape(B, Hq, hd)
