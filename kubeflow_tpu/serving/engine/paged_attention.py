"""Paged attention: Pallas TPU decode kernel over the KV page pool.

Role (SURVEY.md §2b Triton row, §3.4 hot path): the decode-step attention of
the JetStream-class engine.  The XLA path in model.py gathers each slot's
pages into a contiguous [B, T, Hkv, hd] cache every step — that gather WRITES
a full KV copy to HBM before attention reads it back, tripling the memory
traffic of the step's roofline term.  This kernel instead walks the pool
pages in place, one page per grid step, with the page ids scalar-prefetched
(``pltpu.PrefetchScalarGridSpec``) so the data-dependent page lookup happens
in the BlockSpec index_map, not as an HBM gather.

Design (pallas_guide.md):
  * grid = (slots, kv_heads, max_pages); the last axis is sequential on TPU,
    so the online-softmax accumulator lives in VMEM scratch across page
    steps and the output is written on the final page;
  * GQA: the q block per (slot, kv head) is the [K*group, hd] bundle of the
    query heads sharing that KV head — K > 1 is the speculative-verify case
    (1 committed + K-1 draft tokens in one pass), with each query row's
    causal horizon offset by its draft index.  This per-row horizon is the
    whole verify-pass contract, so BOTH speculative entry points — the sync
    ``decode_step_k`` and the pipelined fused ``decode_step_verify_sample``
    (ISSUE 9) — run through this same kernel unchanged when paged=True;
  * pages past every query's horizon are masked per-position and skipped as
    whole blocks via ``pl.when`` (no FLOPs for dead pages — the paged
    analogue of flash attention's causal block skip);
  * int8 KV pools ({"q": int8, "s": bf16 scales} — model.py) dequantize
    inside the kernel: the pool stays int8 in HBM, so the bandwidth win of
    quantization COMPOSES with the no-gather win of paging;
  * tensor parallelism wraps the same kernel in ``shard_map`` over the
    engine's 1-D ``tensor`` mesh (sharding.py): attention is per-KV-head
    independent, so each chip runs the kernel on its own heads' pages with
    zero collectives;
  * ``interpret=`` auto-selects: compiled on TPU, interpreter on the CPU
    test mesh, same numerics either way.

Engine integration is env-gated (``ENGINE_PAGED_KERNEL=1``): the XLA gather
path stays the default until the kernel is re-validated on real hardware
(the TPU tunnel was down for all of round 2).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


def _auto_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _kernel(page_table_ref, seq_lens_ref,  # scalar-prefetch (SMEM)
            *refs, page_size, scale, group, num_q, quantized):
    """refs: q, k, v, [k_scale, v_scale,] o, acc, m, l."""
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    j = pl.program_id(2)
    num_pages = pl.num_programs(2)
    seq_len = seq_lens_ref[b]
    # kv blocks are [1, 1, ps, hd] — one (page, head)'s contiguous tile; the
    # pool layout keeps the head axis BEFORE the token-in-page axis exactly
    # so this block's trailing dims are (ps, hd): divisible-by-(8,128)
    # Mosaic tiles (head-last made the trailing dims (1, hd), which Mosaic
    # rejects unless the block spans every head)

    @pl.when(j == 0)
    def _reset():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # pages past EVERY query's horizon contribute nothing: skip their FLOPs.
    # Query row r (of K*group) has draft index r//group and sees positions
    # < seq_len + r//group, so the furthest horizon is seq_len + num_q - 1.
    @pl.when(j * page_size < seq_len + (num_q - 1))
    def _page():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # [K*group, hd]
        k = k_ref[0, 0].astype(jnp.float32)                  # [ps, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0].astype(jnp.float32)         # [ps, 1] bcast
            v = v * vs_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                    # [K*group, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        qi = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0) // group
        logits = jnp.where(pos < seq_len + qi, logits, NEG_INF)
        m_new = jnp.maximum(m_ref[...], logits.max(axis=1, keepdims=True))
        p = jnp.exp(logits - m_new)
        corr = jnp.exp(m_ref[...] - m_new)
        l_ref[...] = l_ref[...] * corr + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(j == num_pages - 1)
    def _write():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _call_kernel(q, k_pool, v_pool, page_table, seq_lens,
                 page_size: int, interpret: bool):
    """Single-device kernel invocation.  q: [B, K, Hq, hd]; pools: one
    layer's pool, bf16 [P, Hkv, ps, hd] or {"q": int8, "s": bf16 scales};
    returns [B, K, Hq, hd]."""
    B, K, Hq, hd = q.shape
    quantized = isinstance(k_pool, dict)
    Hkv = (k_pool["q"] if quantized else k_pool).shape[1]
    group = Hq // Hkv
    max_pages = page_table.shape[1]
    scale = hd ** -0.5
    # [B, K, Hq, hd] -> [B, Hkv, K*group, hd]: rows ordered draft-major so
    # row r is (draft r//group, group member r%group) of kv head h
    qg = (q.reshape(B, K, Hkv, group, hd)
           .transpose(0, 2, 1, 3, 4)
           .reshape(B, Hkv, K * group, hd))

    grid = (B, Hkv, max_pages)
    rows = K * group
    # (page, head) block = trailing [ps, hd] — Mosaic-legal (8,128) tiles
    kv_specs = [
        pl.BlockSpec((1, 1, page_size, hd), lambda b, h, j, pt, sl: (pt[b, j], h, 0, 0)),
        pl.BlockSpec((1, 1, page_size, hd), lambda b, h, j, pt, sl: (pt[b, j], h, 0, 0)),
    ]
    inputs = [qg]
    if quantized:
        scale_spec = pl.BlockSpec((1, 1, page_size, 1),
                                  lambda b, h, j, pt, sl: (pt[b, j], h, 0, 0))
        in_specs = ([pl.BlockSpec((1, 1, rows, hd), lambda b, h, j, pt, sl: (b, h, 0, 0))]
                    + kv_specs + [scale_spec, scale_spec])
        inputs += [k_pool["q"], v_pool["q"], k_pool["s"], v_pool["s"]]
    else:
        in_specs = ([pl.BlockSpec((1, 1, rows, hd), lambda b, h, j, pt, sl: (b, h, 0, 0))]
                    + kv_specs)
        inputs += [k_pool, v_pool]
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=page_size, scale=scale,
                          group=group, num_q=K, quantized=quantized),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # page_table, seq_lens
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, 1, rows, hd), lambda b, h, j, pt, sl: (b, h, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((rows, hd), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
                pltpu.VMEM((rows, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rows, hd), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, *inputs)
    return (out.reshape(B, Hkv, K, group, hd)
               .transpose(0, 2, 1, 3, 4)
               .reshape(B, K, Hq, hd))


# head-axis specs for the shard_map TP wrapper: attention is independent per
# KV head, so q/pools/out shard on their head axes and nothing communicates
_Q_SPEC = P(None, None, "tensor", None)      # q: [B, K, Hq, hd]
_POOL_SPEC = P(None, "tensor", None, None)   # pool: [P, Hkv, ps, hd]


def paged_attention(q, k_pool, v_pool, page_table, seq_lens, page_size: int,
                    mesh: Mesh | None = None, interpret: bool | None = None):
    """Attention for K query tokens per slot directly over the page pool.

    q: [B, K, Hq, hd] — query K=0 is the slot's current committed token and
    rows 1..K-1 are draft tokens at the following positions (speculative
    verify); K=1 is the plain decode step.  seq_lens: [B] int32 counting
    committed tokens INCLUDING query 0's position (query row r sees
    positions < seq_lens + r).  k_pool/v_pool: ONE layer's pool —
    [P, Hkv, page_size, hd] bf16 or the int8 {"q", "s"} pytree (model.py).
    page_table: [B, max_pages] int32.  ``mesh``: a 1-D ``tensor`` mesh runs
    the kernel per-shard via shard_map (heads independent, no collectives).
    Returns [B, K, Hq, hd].  Pools are [P, Hkv, page_size, hd] (ONE layer).
    """
    if interpret is None:
        interpret = _auto_interpret()
    call = functools.partial(_call_kernel, page_size=page_size,
                             interpret=interpret)
    if mesh is None:
        return call(q, k_pool, v_pool, page_table, seq_lens)
    pool_spec = ({"q": _POOL_SPEC, "s": _POOL_SPEC}
                 if isinstance(k_pool, dict) else _POOL_SPEC)
    shard = jax.shard_map(
        call, mesh=mesh,
        in_specs=(_Q_SPEC, pool_spec, pool_spec, P(), P()),
        out_specs=_Q_SPEC,
        check_vma=False,
    )
    return shard(q, k_pool, v_pool, page_table, seq_lens)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention(q, k_pool, v_pool, page_table, seq_lens,
                           page_size: int, interpret: bool | None = None):
    """One decode step of attention over the page pool (K=1 wrapper).

    q: [B, Hq, hd] (current token per slot); k_pool/v_pool:
    [P, Hkv, page_size, hd] (ONE layer's pool); page_table: [B, max_pages]
    int32; seq_lens: [B] int32 (0 = inactive slot → zeros out).
    Returns [B, Hq, hd].
    """
    return paged_attention(q[:, None], k_pool, v_pool, page_table, seq_lens,
                           page_size, interpret=interpret)[:, 0]
