"""Storage initializer: fetch a model from a storage URI to a local dir.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "KServe: storage initializer"):
``kserve/python/kserve/kserve/storage`` — an init container that downloads
``gs://``/``s3://``/``pvc://``/``hf://`` models to ``/mnt/models`` before the
server starts.  Here the same dispatch runs as a real init container process
(core/kubelet.py runs initContainers sequentially).

This sandbox has zero network egress, so remote schemes resolve ONLY through a
local mirror: set ``KSERVE_STORAGE_MIRROR=/path`` and ``gs://bucket/x`` maps to
``$KSERVE_STORAGE_MIRROR/gs/bucket/x`` (same for s3/hf).  ``file://`` and
``pvc://`` are served directly.  This keeps the URI surface identical to the
reference while being honest about egress.
"""

from __future__ import annotations

import os
import shutil
import sys

MOUNT_PATH = "/tmp/kubeflow-tpu-models"  # the simulator's /mnt/models
MIRROR_ENV = "KSERVE_STORAGE_MIRROR"
PVC_ROOT_ENV = "KSERVE_PVC_ROOT"


class StorageError(RuntimeError):
    pass


def _copy_tree_or_file(src: str, dest: str) -> None:
    if not os.path.exists(src):
        raise StorageError(f"source path does not exist: {src}")
    os.makedirs(dest, exist_ok=True)
    if os.path.isdir(src):
        shutil.copytree(src, dest, dirs_exist_ok=True)
    else:
        shutil.copy2(src, os.path.join(dest, os.path.basename(src)))


def download(uri: str, dest: str) -> str:
    """Materialize `uri` under directory `dest`; returns dest."""
    if "://" not in uri:
        raise StorageError(f"not a storage URI: {uri!r}")
    scheme, rest = uri.split("://", 1)
    rest = rest.rstrip("/")
    if scheme == "file":
        _copy_tree_or_file(rest if rest.startswith("/") else "/" + rest, dest)
    elif scheme == "pvc":
        # pvc://<claim-name>/<path> — claims live under KSERVE_PVC_ROOT/<claim>
        root = os.environ.get(PVC_ROOT_ENV)
        if not root:
            raise StorageError(f"pvc:// needs {PVC_ROOT_ENV} set")
        claim, _, path = rest.partition("/")
        _copy_tree_or_file(os.path.join(root, claim, path), dest)
    elif scheme in ("gs", "s3", "hf"):
        mirror = os.environ.get(MIRROR_ENV)
        if not mirror:
            raise StorageError(
                f"{scheme}:// has no network egress here; set {MIRROR_ENV} to a local mirror root"
            )
        _copy_tree_or_file(os.path.join(mirror, scheme, rest), dest)
    else:
        raise StorageError(f"unsupported storage scheme: {scheme}://")
    return dest


def main(argv: list[str]) -> int:
    if len(argv) != 3:
        print("usage: python -m kubeflow_tpu.serving.storage <uri> <dest>", file=sys.stderr)
        return 2
    try:
        download(argv[1], argv[2])
    except StorageError as e:
        print(f"storage-initializer: {e}", file=sys.stderr)
        return 1
    print(f"storage-initializer: {argv[1]} -> {argv[2]}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
