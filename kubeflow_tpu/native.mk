# Shared build rules for the first-party C++ cores (include from a component
# Makefile after setting NAME and SRC). The Python bindings auto-build on
# import via utils/native_build.py (content-hashed cache _$(NAME)_<hash>.so);
# these targets are the manual + sanitizer builds (SURVEY.md §5).
CXX ?= g++
CXXFLAGS ?= -O2 -fPIC -shared -std=c++17 -Wall -Wextra -pthread

all: _$(NAME).so

_$(NAME).so: $(SRC)
	$(CXX) $(CXXFLAGS) $< -o $@

asan: $(SRC)
	$(CXX) $(CXXFLAGS) -fsanitize=address -g $< -o _$(NAME)_asan.so

tsan: $(SRC)
	$(CXX) $(CXXFLAGS) -fsanitize=thread -g $< -o _$(NAME)_tsan.so

# precise: never touch the import-time build cache (_$(NAME)_<hash>.so)
clean:
	rm -f _$(NAME).so _$(NAME)_asan.so _$(NAME)_tsan.so

.PHONY: all asan tsan clean
