"""ctypes bindings for the C++ ring-collective core (transport_core.cc).

The PyTorchJob-compat DDP path uses this the way the reference uses NCCL
(SURVEY.md §2b): the controller injects MASTER_ADDR/RANK/WORLD_SIZE, each
worker opens a RingTransport on a port derived from MASTER_PORT, and the
gradient sync goes through ``allreduce`` (mean) instead of an XLA psum.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Iterable, Optional

import numpy as np

from ..utils.native_build import load_native

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "transport_core.cc")
_LOCK = threading.Lock()
_LIB = None

_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def load_library() -> ctypes.CDLL:
    global _LIB
    with _LOCK:
        if _LIB is None:
            lib = load_native(_SRC, "transport", extra_flags=["-pthread"])
            i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
            lib.tr_create.restype = p
            lib.tr_create.argtypes = [i32, i32, ctypes.c_char_p, i32]
            lib.tr_destroy.argtypes = [p]
            lib.tr_allreduce_f32.restype = i32
            lib.tr_allreduce_f32.argtypes = [p, _f32p, i64]
            lib.tr_reduce_scatter_f32.restype = i32
            lib.tr_reduce_scatter_f32.argtypes = [p, _f32p, i64, _f32p]
            lib.tr_allgather.restype = i32
            lib.tr_allgather.argtypes = [p, _u8p, i64, _u8p]
            lib.tr_broadcast.restype = i32
            lib.tr_broadcast.argtypes = [p, _u8p, i64, i32]
            lib.tr_barrier.restype = i32
            lib.tr_barrier.argtypes = [p]
            _LIB = lib
    return _LIB


class RingTransport:
    """Ring collectives among ``world`` processes; rank r listens on
    base_port+r and connects to base_port+(r+1)%world on the RIGHT
    neighbor's host.

    ``host`` is where rank (r+1)%world listens.  Single-host gangs (the
    simulator's pods share the network namespace) pass one address for
    everyone; multi-pod gangs pass ``hosts`` — the full per-rank address
    list (the hostfile analogue) — and each rank dials its own neighbor.
    """

    def __init__(self, rank: int, world: int, host: str = "127.0.0.1",
                 base_port: int = 23456, hosts: Optional[list[str]] = None):
        self.lib = load_library()
        self.rank, self.world = rank, world
        if hosts is not None:
            if len(hosts) != world:
                raise ValueError(f"hosts list has {len(hosts)} entries for world {world}")
            host = hosts[(rank + 1) % world]
        self._h = self.lib.tr_create(rank, world, host.encode(), base_port)
        if not self._h:
            raise ConnectionError(
                f"transport rendezvous failed (rank {rank}/{world} @ {host}:{base_port})"
            )

    @classmethod
    def from_env(cls) -> "RingTransport":
        """Open from the PyTorchJob-injected rendezvous env.

        ``TRANSPORT_HOSTS`` (comma-separated, one address per rank — the
        controller's hostfile analogue) enables multi-pod rings; without it
        every rank dials MASTER_ADDR, which is correct only when the gang
        shares one host/network namespace (the simulator's pods do).
        """
        env = os.environ
        hosts = env.get("TRANSPORT_HOSTS")
        return cls(
            rank=int(env.get("RANK", "0")),
            world=int(env.get("WORLD_SIZE", "1")),
            host=env.get("MASTER_ADDR", "127.0.0.1"),
            # offset from the coordinator port: it stays free for jax.distributed
            base_port=int(env.get("MASTER_PORT", "29500")) + 1000,
            hosts=hosts.split(",") if hosts else None,
        )

    def close(self) -> None:
        if self._h:
            self.lib.tr_destroy(self._h)
            self._h = None

    def _check(self, rc: int, op: str) -> None:
        if rc != 0:
            raise ConnectionError(f"transport {op} failed (rc={rc})")

    def allreduce(self, x: np.ndarray, mean: bool = False) -> np.ndarray:
        """In-place sum (or mean) allreduce of a float32 array; returns it."""
        flat = np.ascontiguousarray(x, np.float32).reshape(-1)
        self._check(self.lib.tr_allreduce_f32(self._h, flat, flat.size), "allreduce")
        if mean:
            flat /= self.world
        return flat.reshape(x.shape)

    def reduce_scatter(self, x: np.ndarray) -> np.ndarray:
        """Sum-reduce a flat f32 array; return this rank's chunk
        (chunk (rank+1) % world of the near-equal split)."""
        flat = np.ascontiguousarray(x, np.float32).reshape(-1)
        base, rem = divmod(flat.size, self.world)
        mine = (self.rank + 1) % self.world
        out = np.zeros(base + (1 if mine < rem else 0), np.float32)
        self._check(
            self.lib.tr_reduce_scatter_f32(self._h, flat, flat.size, out),
            "reduce_scatter",
        )
        return out

    def allgather(self, x: np.ndarray) -> np.ndarray:
        """Gather equal-shaped arrays from all ranks → stacked [world, ...]."""
        buf = np.ascontiguousarray(x).view(np.uint8).reshape(-1)
        out = np.zeros(self.world * buf.size, np.uint8)
        self._check(self.lib.tr_allgather(self._h, buf, buf.size, out), "allgather")
        return out.view(x.dtype).reshape((self.world,) + x.shape)

    def broadcast(self, x: np.ndarray, root: int = 0) -> np.ndarray:
        buf = np.ascontiguousarray(x).view(np.uint8).reshape(-1)
        self._check(self.lib.tr_broadcast(self._h, buf, buf.size, root), "broadcast")
        return buf.view(x.dtype).reshape(x.shape)

    def barrier(self) -> None:
        self._check(self.lib.tr_barrier(self._h), "barrier")

    def __enter__(self) -> "RingTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - defensive
        try:
            self.close()
        except Exception:
            pass


def grad_allreduce(transport: RingTransport, grads) -> "object":
    """Mean-allreduce a pytree of gradients through the shim (one flat buffer
    per call — the NCCL-bucket analogue), preserving structure and dtypes."""
    import jax

    leaves, treedef = jax.tree.flatten(grads)
    arrs = [np.asarray(g, np.float32) for g in leaves]
    flat = np.concatenate([a.reshape(-1) for a in arrs]) if arrs else np.zeros(0, np.float32)
    transport.allreduce(flat, mean=True)
    out, off = [], 0
    for a, leaf in zip(arrs, leaves):
        n = a.size
        out.append(flat[off:off + n].reshape(a.shape).astype(np.asarray(leaf).dtype))
        off += n
    return jax.tree.unflatten(treedef, out)
