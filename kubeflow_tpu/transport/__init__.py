"""Collective-transport shim: the NCCL-stand-in for the torch-compat path.

SURVEY.md §2b NCCL row: the reference platform's DDP rides NCCL, a native
collective library.  The TPU rebuild keeps "native stays native": ring
allreduce/allgather/reduce-scatter implemented in C++ (transport_core.cc)
over TCP between the gang's processes, bound via ctypes.
"""

from .transport import RingTransport, grad_allreduce

__all__ = ["RingTransport", "grad_allreduce"]
