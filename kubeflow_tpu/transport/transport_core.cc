// Collective-transport core: ring collectives over TCP for N local processes.
//
// Role (SURVEY.md §2b NCCL row): the reference's PyTorchJob path rides NCCL —
// a *native* collective library the operator bootstraps via MASTER_ADDR env.
// On TPU the intra-slice collectives are XLA-compiled over ICI, so the only
// native piece the platform still owes is the torch-compat transport the
// PyTorchJob controller wires up for CPU-side DDP (gloo's role).  This core
// is that shim: rank r listens on base_port+r, connects to (r+1)%world, and
// runs ring reduce-scatter / allgather / allreduce with a poll()-based
// full-duplex exchange (no deadlock at any message size, single thread).
//
// C ABI (ctypes-bound by transport.py; no pybind11 in this image):
//   tr_create(rank, world, host, base_port) -> handle (NULL on error)
//   tr_allreduce_f32 / tr_reduce_scatter_f32 / tr_allgather / tr_broadcast
//   tr_barrier, tr_destroy — all return 0 on success, negative errno-ish codes.
//
// Build: make [asan|tsan] here, or build-on-import via utils/native_build.py.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <string>
#include <thread>
#include <vector>

namespace {

// Generous: gang members on an oversubscribed host can be compute-starved for
// minutes (e.g. N ranks serializing XLA compiles on few cores) while a peer
// waits in a collective.
constexpr int kConnectTimeoutSec = 300;

struct Transport {
  int rank = 0;
  int world = 1;
  int send_fd = -1;  // to (rank+1) % world
  int recv_fd = -1;  // from (rank-1+world) % world
};

int set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// CRITICAL: the exchange() loop assumes partial writes.  On a blocking fd,
// send() of a large chunk parks the thread in sk_stream_wait_memory until the
// WHOLE chunk is buffered — with every rank sending at once that deadlocks
// the ring.  Non-blocking fds make send/recv return what fits, which is what
// the poll loop is built around.
int set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags < 0 ? -1 : fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

// Blocking-socket full-duplex exchange driven by poll(): pushes send_buf out
// and drains recv_buf in whatever order the kernel allows.  This is the piece
// that makes a blocking ring safe at any chunk size (everyone can be "in send"
// simultaneously without deadlock because reads still drain).
int exchange(Transport* t, const char* send_buf, size_t send_n, char* recv_buf,
             size_t recv_n) {
  size_t sent = 0, rcvd = 0;
  while (sent < send_n || rcvd < recv_n) {
    struct pollfd fds[2];
    int nfds = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < send_n) {
      fds[nfds] = {t->send_fd, POLLOUT, 0};
      send_idx = nfds++;
    }
    if (rcvd < recv_n) {
      fds[nfds] = {t->recv_fd, POLLIN, 0};
      recv_idx = nfds++;
    }
    int rc = poll(fds, nfds, kConnectTimeoutSec * 1000);
    if (rc == 0) return -2;  // peer stalled
    if (rc < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    if (send_idx >= 0 && (fds[send_idx].revents & (POLLOUT | POLLERR | POLLHUP))) {
      ssize_t n = send(t->send_fd, send_buf + sent, send_n - sent, MSG_NOSIGNAL);
      if (n < 0 && errno != EAGAIN && errno != EINTR) return -1;
      if (n > 0) sent += static_cast<size_t>(n);
    }
    if (recv_idx >= 0 && (fds[recv_idx].revents & (POLLIN | POLLERR | POLLHUP))) {
      ssize_t n = recv(t->recv_fd, recv_buf + rcvd, recv_n - rcvd, 0);
      if (n == 0) return -3;  // peer closed
      if (n < 0 && errno != EAGAIN && errno != EINTR) return -1;
      if (n > 0) rcvd += static_cast<size_t>(n);
    }
  }
  return 0;
}

// Chunk c of a length-n vector split into `world` near-equal pieces.
void chunk_bounds(int64_t n, int world, int c, int64_t* lo, int64_t* len) {
  int64_t base = n / world, rem = n % world;
  *lo = c * base + (c < rem ? c : rem);
  *len = base + (c < rem ? 1 : 0);
}

}  // namespace

extern "C" {

void* tr_create(int rank, int world, const char* host, int base_port) {
  if (rank < 0 || world <= 0 || rank >= world || base_port <= 0) return nullptr;
  auto* t = new Transport{rank, world, -1, -1};
  if (world == 1) return t;

  // Listen for the left neighbor on base_port + rank.
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) { delete t; return nullptr; }
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(base_port + rank));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(lfd, 1) < 0) {
    close(lfd); delete t; return nullptr;
  }

  // Connect to the right neighbor (retry while it boots).
  int right = (rank + 1) % world;
  sockaddr_in raddr{};
  raddr.sin_family = AF_INET;
  raddr.sin_port = htons(static_cast<uint16_t>(base_port + right));
  if (inet_pton(AF_INET, host && *host ? host : "127.0.0.1", &raddr.sin_addr) != 1) {
    close(lfd); delete t; return nullptr;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::seconds(kConnectTimeoutSec);
  int sfd = -1;
  while (true) {
    sfd = socket(AF_INET, SOCK_STREAM, 0);
    if (sfd >= 0 &&
        connect(sfd, reinterpret_cast<sockaddr*>(&raddr), sizeof(raddr)) == 0)
      break;
    if (sfd >= 0) close(sfd);
    sfd = -1;
    if (std::chrono::steady_clock::now() > deadline) {
      close(lfd); delete t; return nullptr;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }

  // Accept the left neighbor.
  struct pollfd pfd = {lfd, POLLIN, 0};
  int rc = poll(&pfd, 1, kConnectTimeoutSec * 1000);
  if (rc <= 0) { close(sfd); close(lfd); delete t; return nullptr; }
  int afd = accept(lfd, nullptr, nullptr);
  close(lfd);
  if (afd < 0) { close(sfd); delete t; return nullptr; }
  set_nodelay(sfd);
  set_nodelay(afd);
  if (set_nonblocking(sfd) < 0 || set_nonblocking(afd) < 0) {
    close(sfd); close(afd); delete t; return nullptr;
  }
  t->send_fd = sfd;
  t->recv_fd = afd;
  return t;
}

void tr_destroy(void* h) {
  auto* t = static_cast<Transport*>(h);
  if (!t) return;
  if (t->send_fd >= 0) close(t->send_fd);
  if (t->recv_fd >= 0) close(t->recv_fd);
  delete t;
}

int tr_reduce_scatter_f32(void* h, const float* in, int64_t n, float* out) {
  auto* t = static_cast<Transport*>(h);
  if (!t || n < 0) return -4;
  int w = t->world, r = t->rank;
  if (w == 1) { std::memcpy(out, in, sizeof(float) * n); return 0; }
  std::vector<float> acc(in, in + n);
  int64_t base = n / w + 1;
  std::vector<float> inbox(base);
  // w-1 steps: send chunk (r - s), receive + accumulate chunk (r - s - 1).
  for (int s = 0; s < w - 1; ++s) {
    int send_c = ((r - s) % w + w) % w;
    int recv_c = ((r - s - 1) % w + w) % w;
    int64_t slo, slen, rlo, rlen;
    chunk_bounds(n, w, send_c, &slo, &slen);
    chunk_bounds(n, w, recv_c, &rlo, &rlen);
    int rc = exchange(t, reinterpret_cast<const char*>(acc.data() + slo),
                      sizeof(float) * slen,
                      reinterpret_cast<char*>(inbox.data()), sizeof(float) * rlen);
    if (rc != 0) return rc;
    for (int64_t i = 0; i < rlen; ++i) acc[rlo + i] += inbox[i];
  }
  int64_t mlo, mlen;
  chunk_bounds(n, w, (r + 1) % w, &mlo, &mlen);
  std::memcpy(out, acc.data() + mlo, sizeof(float) * mlen);
  return 0;
}

int tr_allgather(void* h, const char* in, int64_t bytes, char* out) {
  auto* t = static_cast<Transport*>(h);
  if (!t || bytes < 0) return -4;
  int w = t->world, r = t->rank;
  std::memcpy(out + r * bytes, in, bytes);
  // w-1 steps: pass blocks around the ring.
  for (int s = 0; s < w - 1; ++s) {
    int send_b = ((r - s) % w + w) % w;
    int recv_b = ((r - s - 1) % w + w) % w;
    int rc = exchange(t, out + send_b * bytes, bytes, out + recv_b * bytes, bytes);
    if (rc != 0) return rc;
  }
  return 0;
}

int tr_allreduce_f32(void* h, float* data, int64_t n) {
  auto* t = static_cast<Transport*>(h);
  if (!t || n < 0) return -4;
  int w = t->world, r = t->rank;
  if (w == 1 || n == 0) return 0;
  // Phase 1: reduce-scatter (this rank ends owning chunk (r+1)%w, reduced).
  std::vector<float> mine(n / w + 1);
  int rc = tr_reduce_scatter_f32(h, data, n, mine.data());
  if (rc != 0) return rc;
  int own = (r + 1) % w;
  int64_t olo, olen;
  chunk_bounds(n, w, own, &olo, &olen);
  std::memcpy(data + olo, mine.data(), sizeof(float) * olen);
  // Phase 2: allgather of the reduced chunks (variable-size ring pass).
  for (int s = 0; s < w - 1; ++s) {
    int send_c = ((own - s) % w + w) % w;
    int recv_c = ((own - s - 1) % w + w) % w;
    int64_t slo, slen, rlo, rlen;
    chunk_bounds(n, w, send_c, &slo, &slen);
    chunk_bounds(n, w, recv_c, &rlo, &rlen);
    rc = exchange(t, reinterpret_cast<const char*>(data + slo), sizeof(float) * slen,
                  reinterpret_cast<char*>(data + rlo), sizeof(float) * rlen);
    if (rc != 0) return rc;
  }
  return 0;
}

int tr_broadcast(void* h, char* data, int64_t bytes, int root) {
  auto* t = static_cast<Transport*>(h);
  if (!t || bytes < 0 || root < 0 || root >= t->world) return -4;
  int w = t->world, r = t->rank;
  if (w == 1) return 0;
  // Pass along the ring root → root+1 → …; the rank just before root only
  // receives.  Distance from root determines order.
  int dist = ((r - root) % w + w) % w;
  if (dist != 0) {  // receive from left first
    int rc = exchange(t, nullptr, 0, data, bytes);
    if (rc != 0) return rc;
  }
  if (dist != w - 1) {  // forward to right
    int rc = exchange(t, data, bytes, nullptr, 0);
    if (rc != 0) return rc;
  }
  return 0;
}

int tr_barrier(void* h) {
  auto* t = static_cast<Transport*>(h);
  if (!t) return -4;
  if (t->world == 1) return 0;
  char token = 1;
  std::vector<char> all(static_cast<size_t>(t->world));
  return tr_allgather(h, &token, 1, all.data());
}

int tr_rank(void* h) { return h ? static_cast<Transport*>(h)->rank : -1; }
int tr_world(void* h) { return h ? static_cast<Transport*>(h)->world : -1; }

}  // extern "C"
