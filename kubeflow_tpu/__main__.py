"""``python -m kubeflow_tpu`` — the kfctl/kubectl-shaped CLI.

Upstream analogues (UNVERIFIED, SURVEY.md §3.1/§3.2): ``kfctl apply -V -f
kfdef.yaml`` deploys the platform from a KfDef spec, and ``kubectl apply -f
tfjob.yaml`` submits a workload CR that the operators reconcile.  Here both
verbs drive ONE in-process cluster session: bring it up, install the
pillars (KfAdm), apply every document in the given files, optionally wait
for each object's terminal/ready condition, print a ``kubectl get``-style
summary (and pod logs with ``--logs``), then tear the cluster down.

The session is one-shot because the "cluster" is in-process by design
(SURVEY.md §7: API simulator + local-process kubelet, no daemons); a file
can carry a whole scenario — KfDef + Profile + TPUJob + InferenceService —
as multi-doc YAML, exactly like a kubectl manifest bundle.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core.conditions import get_condition
from .training import api as tapi

TRAINING_KINDS = set(tapi.JOB_KINDS)
# kinds whose wait target is a terminal Succeeded/Failed condition
TERMINAL_KINDS = TRAINING_KINDS | {"Experiment", "Trial"}
# kinds whose wait target is Ready=True (steady-state services)
READY_KINDS = {"InferenceService", "Notebook"}


def _load_docs(paths: list[str]) -> list[dict]:
    import yaml

    docs: list[dict] = []
    for path in paths:
        text = sys.stdin.read() if path == "-" else open(path).read()
        for doc in yaml.safe_load_all(text):
            if doc is None:
                continue
            if not isinstance(doc, dict) or "kind" not in doc:
                raise SystemExit(f"{path}: every document needs a 'kind' (got {type(doc).__name__})")
            docs.append(doc)
    return docs


def _parse_slice(arg: str) -> tuple[str, str, str]:
    parts = arg.split(":")
    if len(parts) != 3:
        raise SystemExit(f"--tpu-slice wants NAME:ACCELERATOR:TOPOLOGY, got {arg!r}")
    return (parts[0], parts[1], parts[2])


def _status_of(obj: dict) -> str:
    """One word for the summary table, kubectl-style."""
    kind = obj.get("kind", "")
    status = obj.get("status") or {}
    if kind == "Pod":
        return status.get("phase", "Pending")
    for ctype in ("Succeeded", "Failed", "Ready", "Running", "Created"):
        c = get_condition(status, ctype)
        if c is not None and c.get("status") == "True":
            return ctype
    return status.get("phase", "Applied")


def _wait_one(cluster, obj: dict, timeout: float) -> str:
    kind = obj["kind"]
    name = obj["metadata"]["name"]
    ns = obj["metadata"].get("namespace", "default")

    def fresh() -> dict:
        return cluster.api.try_get(kind, name, ns) or obj

    if kind in TERMINAL_KINDS:
        def done() -> bool:
            return _status_of(fresh()) in ("Succeeded", "Failed")
    elif kind in READY_KINDS:
        def done() -> bool:
            return _status_of(fresh()) == "Ready"
    elif kind == "Pod":
        def done() -> bool:
            return _status_of(fresh()) in ("Succeeded", "Failed")
    else:
        cluster.settle()
        return _status_of(fresh())
    cluster.wait_for(done, timeout=timeout)
    return _status_of(fresh())


def _pod_logs(cluster, obj: dict) -> dict[str, str]:
    kind, ns = obj["kind"], obj["metadata"].get("namespace", "default")
    name = obj["metadata"]["name"]
    if kind == "Pod":
        return {name: cluster.logs(name, ns)}
    if kind in TRAINING_KINDS:
        selector = {tapi.LABEL_JOB_NAME: name}
    else:
        return {}
    pods = cluster.api.list("Pod", namespace=ns, label_selector=selector)
    return {p["metadata"]["name"]: cluster.logs(p["metadata"]["name"], ns) for p in pods}


def cmd_apply(args: argparse.Namespace) -> int:
    from .core.cluster import Cluster
    from .platform.kfadm import APPLICATIONS, KfAdm, kfdef

    docs = _load_docs(args.filename)
    cluster = Cluster(
        cpu_nodes=args.cpu_nodes,
        tpu_slices=tuple(_parse_slice(s) for s in args.tpu_slice),
    )
    exit_code = 0
    try:
        kfadm = KfAdm(cluster)
        apps = tuple(args.apps.split(",")) if args.apps else APPLICATIONS
        # platform bringup first: either the file's own KfDef docs, or (by
        # default) everything — workload CRDs must exist before apply
        kfdef_docs = [d for d in docs if d.get("kind") == "KfDef"] or [kfdef(applications=apps)]
        for d in kfdef_docs:
            applied = kfadm.apply(d)
            for app in applied["status"]["applications"]:
                print(f"kfadm: application {app['name']}: {app['status']}")

        applied_objs = []
        for doc in docs:
            if doc.get("kind") == "KfDef":
                continue
            obj = cluster.apply(doc)
            applied_objs.append(obj)
            print(f"applied {obj['kind']}/{obj['metadata']['name']}")

        results = []
        for obj in applied_objs:
            state = _wait_one(cluster, obj, args.timeout) if args.wait else _status_of(obj)
            results.append((obj, state))

        if results:
            width = max(len(f"{o['kind']}/{o['metadata']['name']}") for o, _ in results)
            print(f"\n{'NAME':<{width + 2}}{'NAMESPACE':<14}STATUS")
            for obj, state in results:
                ident = f"{obj['kind']}/{obj['metadata']['name']}"
                ns = obj["metadata"].get("namespace", "default")
                print(f"{ident:<{width + 2}}{ns:<14}{state}")
                wait_missed = args.wait and (
                    (obj["kind"] in TERMINAL_KINDS and state not in ("Succeeded", "Failed"))
                    or (obj["kind"] in READY_KINDS and state != "Ready")
                    or (obj["kind"] == "Pod" and state not in ("Succeeded", "Failed")))
                if state == "Failed" or wait_missed:
                    exit_code = 1

        if args.logs:
            for obj, _ in results:
                for pod, text in sorted(_pod_logs(cluster, obj).items()):
                    print(f"\n--- logs {pod} ---")
                    print(text.rstrip() if text else "<no output>")
    finally:
        cluster.shutdown()
    return exit_code


def cmd_components(_args: argparse.Namespace) -> int:
    """What a KfDef can install, and the workload kinds each app serves."""
    from .platform.kfadm import APPLICATIONS

    kinds = {
        "platform": ["Profile", "Notebook", "PodDefault", "KfDef"],
        "training": sorted(TRAINING_KINDS),
        "katib": ["Experiment", "Suggestion", "Trial"],
        "serving": ["InferenceService", "ServingRuntime", "ClusterServingRuntime", "TrainedModel"],
        "pipelines": ["Pipeline", "PipelineRun (via pipelines service API)"],
    }
    print(json.dumps({app: kinds[app] for app in APPLICATIONS}, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu",
        description="TPU-native Kubeflow-capability platform CLI",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_apply = sub.add_parser(
        "apply", help="bring up a cluster session, apply manifests, report status")
    p_apply.add_argument("-f", "--filename", action="append", required=True,
                         help="manifest file (YAML/JSON, multi-doc; '-' = stdin); repeatable")
    p_apply.add_argument("--wait", action="store_true",
                         help="wait for terminal/ready conditions before reporting")
    p_apply.add_argument("--logs", action="store_true", help="print pod logs at the end")
    p_apply.add_argument("--timeout", type=float, default=300.0,
                         help="per-object wait timeout seconds (default 300)")
    p_apply.add_argument("--cpu-nodes", type=int, default=1)
    p_apply.add_argument("--tpu-slice", action="append", default=[],
                         metavar="NAME:ACC:TOPO",
                         help="add a TPU slice, e.g. slice-a:v5e:2x4; repeatable")
    p_apply.add_argument("--apps", default="",
                         help="comma-separated KfDef applications (default: all)")
    p_apply.set_defaults(func=cmd_apply)

    p_comp = sub.add_parser("components", help="list installable applications and their kinds")
    p_comp.set_defaults(func=cmd_components)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
