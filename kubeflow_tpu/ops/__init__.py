"""TPU compute ops (SURVEY.md §2c/§5): attention family + MoE.

  * ``attention`` — dense MHA (MXU-shaped einsums), the reference impl;
  * ``flash_attention`` — Pallas TPU kernel, blockwise-recompute backward;
  * ``ring_attention`` — context parallelism over the ICI ring (``seq`` axis);
  * ``ulysses`` — head all-to-all sequence parallelism (short-context CP);
  * ``moe`` — expert-parallel mixture-of-experts FFN (``expert`` axis).
"""

from .attention import multihead_attention  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
from .moe import MoEConfig, init_moe, moe_ffn  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
