"""Ring attention: context parallelism over the ICI ring (``seq`` mesh axis).

Role (SURVEY.md §2c "CP / context parallel", §5 long-context): the reference
platform has NO sequence scaling — this is the TPU-native capability add.
Each device owns one sequence block of Q/K/V; K/V blocks rotate around the
ring via ``ppermute`` (one ICI hop per step, bandwidth-optimal), and each device
folds each visiting block into a numerically-stable online-softmax
accumulator (blockwise attention).  Peak memory per device stays
O(S/n · S/n) — sequence length scales linearly with ring size.

The op is plain differentiable JAX (``lax.scan`` + ``ppermute``): autodiff
derives the reverse ring pass, so it composes with jit/grad/fsdp unchanged.
Causal masking is block-level: a visiting block strictly in the future is
skipped entirely; the diagonal block gets the intra-block triangle.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, PartitionSpec as P

from .shard_map_compat import shard_map

NEG_INF = -1e9


def _ring_block(q, k, v, my_idx, src_idx, block_len, causal, scale):
    """One online-softmax update: q attends to the visiting (k, v) block."""
    # q,k,v: [B, s, H, D]; returns the partial (logits-exp, weighted-V) stats
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        s = q.shape[1]
        q_pos = my_idx * block_len + jnp.arange(s)
        k_pos = src_idx * block_len + jnp.arange(s)
        logits = jnp.where(q_pos[:, None] >= k_pos[None, :], logits, NEG_INF)
    return logits


def _ring_attention_sharded(q, k, v, *, axis_name, causal):
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = d ** -0.5
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, r):
        kv, m, l, acc = carry
        k_r, v_r = kv
        src = (my_idx - r) % n

        def fold(args):
            m, l, acc = args
            logits = _ring_block(q, k_r, v_r, my_idx, src, s, causal, scale)  # [B,H,s,t]
            m_new = jnp.maximum(m, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[..., None])
            corr = jnp.exp(m - m_new)                                         # [B,H,s]
            l_new = l * corr + p.sum(axis=-1)
            pv = jnp.einsum("bhst,bthd->bshd", p, v_r.astype(jnp.float32))
            return m_new, l_new, acc * corr.transpose(0, 2, 1)[..., None] + pv

        if causal:
            # a visiting block strictly in the future contributes nothing —
            # skip BOTH einsums, not just mask them (half the ring on average)
            m, l, acc = jax.lax.cond(src > my_idx, lambda args: args, fold, (m, l, acc))
        else:
            m, l, acc = fold((m, l, acc))
        kv_next = jax.lax.ppermute((k_r, v_r), axis_name, perm)
        return (kv_next, m, l, acc), None

    m0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    acc0 = jnp.zeros((b, s, h, d), jnp.float32)
    (kv, m, l, acc), _ = jax.lax.scan(step, ((k, v), m0, l0, acc0), jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] — S sharded over `axis` in the caller's mesh
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis: str = "seq",
    qkv_spec: Optional[P] = None,
) -> jax.Array:
    """Context-parallel attention; call under jit with S-sharded operands.

    ``qkv_spec`` defaults to ``P(None, axis, None, None)`` (batch replicated
    over the ring); give the full spec if batch/heads ride other axes too.
    """
    spec = qkv_spec if qkv_spec is not None else P(None, axis, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
