"""Flash attention: Pallas TPU forward kernel + blockwise-recompute backward.

Role (SURVEY.md §5 long-context, §7 phase 9): the single-chip building block
the long-context layer composes — ring attention runs this per KV block, the
workload layer uses it directly for seq ≤ a few k.

Design (pallas_guide.md):
  * forward: grid over (batch·heads, q blocks); K/V for the row live in VMEM,
    inner ``fori_loop`` walks K blocks with an online-softmax accumulator in
    f32 scratch; causal blocks beyond the diagonal are skipped via ``pl.when``
    on whole blocks (the main win over dense attention);
  * the kernel also emits the log-sum-exp rows, so backward can recompute
    probabilities blockwise in plain XLA (standard flash backward) — memory
    stays O(S·block) and the op is fully differentiable without a second
    hand-written kernel;
  * ``interpret=`` auto-selects: compiled on TPU, interpreter elsewhere
    (the CPU test mesh), same numerics either way.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9


def _auto_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


# ------------------------------------------------------------------- forward


def _fwd_kernel(*refs, scale, causal, masked, block_q, block_k, seq_k):
    if masked:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        mask_ref = None
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale              # [block_q, d]
    num_kb = seq_k // block_k

    def body(kb, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)   # [block_k, d]
        v = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )                                                  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        if masked:
            # key-side padding mask (same side the dense path masks):
            # mask_ref is [1, 1, seq_k] f32, 0.0 = padded key
            km = mask_ref[0, 0, pl.ds(kb * block_k, block_k)]
            logits = jnp.where(km[None, :] > 0.5, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return acc_new, m_new, l_new

    if causal:
        # blocks entirely above the diagonal contribute nothing — skip them
        last_kb = jnp.minimum(((qi + 1) * block_q - 1) // block_k + 1, num_kb)
    else:
        last_kb = num_kb
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, last_kb, body, (acc0, m0, l0))

    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse is [1, block_q, 1]: the trailing singleton keeps the block's last two
    # dims TPU-legal ((block_q, 1) = (divisible by 8, equal to array dim));
    # a 2-D (1, block_q) block fails Mosaic's layout check on real hardware
    lse_ref[0, :, 0] = m + jnp.log(l)


def _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k, interpret, heads):
    """mask: [B, 1, seq_k] f32 key-side padding mask or None.  ``heads`` maps
    a bh grid row to its batch row (bh // heads) for the mask lookup."""
    bh, seq_q, d = q.shape
    seq_k = k.shape[1]
    grid = (bh, seq_q // block_q)
    masked = mask is not None
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, seq_k, d), lambda b, i: (b, 0, 0)),
    ]
    inputs = [q, k, v]
    if masked:
        # block (1, 1, seq_k): the trailing two dims equal the array dims,
        # keeping the block TPU-legal (same trick as the lse output)
        in_specs.append(pl.BlockSpec((1, 1, seq_k),
                                     lambda b, i: (b // heads, 0, 0)))
        inputs.append(mask)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, masked=masked,
            block_q=block_q, block_k=block_k, seq_k=seq_k,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out, lse[..., 0]


# ---------------------------------------------------- backward (blockwise XLA)


def _flash_bwd(q, k, v, mask, out, lse, do, scale, causal, block_k, heads):
    """Standard flash backward: recompute P per K block from saved lse.
    ``mask``: [B, 1, seq_k] f32 key-side padding mask or None (masked logits
    recompute to NEG_INF exactly as the forward kernel saw them)."""
    f32 = jnp.float32
    q32, k32, v32 = q.astype(f32), k.astype(f32), v.astype(f32)
    o32, do32 = out.astype(f32), do.astype(f32)
    seq_q, seq_k = q.shape[1], k.shape[1]
    delta = jnp.sum(o32 * do32, axis=-1)                    # [bh, seq_q]
    num_kb = seq_k // block_k
    if mask is not None:
        # [B, 1, seq_k] -> [bh, seq_k] rows aligned with q's bh rows
        mask_bh = jnp.repeat(mask[:, 0, :], heads, axis=0)

    q_pos = jnp.arange(seq_q)

    def body(kb, dq):
        ks = jax.lax.dynamic_slice_in_dim(k32, kb * block_k, block_k, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(v32, kb * block_k, block_k, axis=1)
        logits = jnp.einsum("bqd,bkd->bqk", q32, ks) * scale
        if causal:
            k_pos = kb * block_k + jnp.arange(block_k)
            logits = jnp.where(q_pos[:, None] >= k_pos[None, :], logits, NEG_INF)
        if mask is not None:
            ms = jax.lax.dynamic_slice_in_dim(mask_bh, kb * block_k, block_k, axis=1)
            logits = jnp.where(ms[:, None, :] > 0.5, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, :, None])               # [bh, q, blk]
        dv = jnp.einsum("bqk,bqd->bkd", p, do32)
        dp = jnp.einsum("bqd,bkd->bqk", do32, vs)
        ds = p * (dp - delta[:, :, None]) * scale
        dk = jnp.einsum("bqk,bqd->bkd", ds, q32)
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, ks)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(q32)
    dq, (dks, dvs) = jax.lax.scan(
        lambda c, kb: body(kb, c), dq0, jnp.arange(num_kb)
    )
    dk = jnp.moveaxis(dks, 0, 1).reshape(k.shape[0], seq_k, k.shape[2])
    dv = jnp.moveaxis(dvs, 0, 1).reshape(v.shape)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ----------------------------------------------------------------- public op


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, causal, block_q, block_k, interpret, heads):
    scale = q.shape[-1] ** -0.5
    out, _ = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                        interpret, heads)
    return out


def _flash_vjp_fwd(q, k, v, mask, causal, block_q, block_k, interpret, heads):
    scale = q.shape[-1] ** -0.5
    out, lse = _flash_fwd(q, k, v, mask, scale, causal, block_q, block_k,
                          interpret, heads)
    return out, (q, k, v, mask, out, lse)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, heads, res, do):
    q, k, v, mask, out, lse = res
    scale = q.shape[-1] ** -0.5
    dq, dk, dv = _flash_bwd(q, k, v, mask, out, lse, do, scale, causal,
                            block_k, heads)
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, T, H, D]
    v: jax.Array,  # [B, T, H, D]
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    kv_mask: Optional[jax.Array] = None,  # [B, T] {0,1}: 0 = padded key
) -> jax.Array:
    """Drop-in for ops.attention.multihead_attention.

    ``kv_mask`` is the key-side padding mask (the side the dense path's
    ``padding_mask`` masks): padded keys are excluded from every query's
    softmax, so real variable-length batches run through the kernel —
    VERDICT r2 #5 closed.  Padded QUERY rows still compute (over real keys
    only); their outputs are garbage the loss masks out, exactly as dense.
    """
    if interpret is None:
        interpret = _auto_interpret()
    b, s, h, d = q.shape
    t = k.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, t)
    if s % block_q or t % block_k:
        raise ValueError(f"seq lengths ({s},{t}) must divide blocks ({block_q},{block_k})")
    # [B, S, H, D] -> [B*H, S, D] rows for the kernel grid
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    mask = (None if kv_mask is None
            else kv_mask.reshape(b, 1, t).astype(jnp.float32))
    of = _flash(qf, kf, vf, mask, causal, block_q, block_k, interpret, h)
    return of.reshape(b, h, s, d).transpose(0, 2, 1, 3)
