"""Ulysses sequence parallelism: head all-to-all over the ``seq`` mesh axis.

Role (SURVEY.md §2c "Ulysses" row): the short-context alternative to ring CP.
Operands arrive sequence-sharded ([B, S/n, H, D] per device); one all-to-all
re-shards them head-wise ([B, S, H/n, D]) so every device runs *full-length*
attention on its head subset, then a second all-to-all restores sequence
sharding.  Two collectives total (vs. n-1 ppermute steps for ring) — cheaper
while S/n blocks still fit in memory; ring wins when they don't.

Requires heads % ring-size == 0.  Differentiable end-to-end (all_to_all has
a transpose rule), so grads flow without custom VJPs.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax

from jax.sharding import Mesh, PartitionSpec as P

from .shard_map_compat import shard_map

from .attention import multihead_attention


def _ulysses_sharded(q, k, v, *, axis_name, causal, inner):
    # [B, S/n, H, D] --all_to_all--> [B, S, H/n, D]
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    out = inner(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal)
    return heads_to_seq(out)


def ulysses_attention(
    q: jax.Array,  # [B, S, H, D] — S sharded over `axis`
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis: str = "seq",
    qkv_spec: Optional[P] = None,
    inner: Optional[Callable] = None,
) -> jax.Array:
    """Head-scattered full attention; ``inner`` defaults to dense MHA and can
    be the flash kernel (ops.flash_attention) on TPU."""
    n = mesh.shape[axis]
    h = q.shape[2]
    if h % n:
        raise ValueError(f"heads ({h}) must divide the {axis!r} axis size ({n}) for Ulysses")
    if inner is None:
        def inner(q_, k_, v_, c):
            return multihead_attention(q_, k_, v_, causal=c)
    spec = qkv_spec if qkv_spec is not None else P(None, axis, None, None)
    fn = shard_map(
        functools.partial(_ulysses_sharded, axis_name=axis, causal=causal, inner=inner),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
