"""One shared shard_map import for every jax on the support matrix.

jax >= 0.6 exports ``shard_map`` at the top level and spells the
replication-check kwarg ``check_vma``; jax 0.4.x (this image) has it under
``jax.experimental.shard_map`` with the kwarg spelled ``check_rep``.  Both
callers (ring_attention, ulysses) import from here so the translation can
never drift between them.
"""

from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, **kw):
        kw["check_rep"] = kw.pop("check_vma", False)
        return _shard_map(f, **kw)

__all__ = ["shard_map"]
