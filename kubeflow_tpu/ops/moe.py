"""Mixture-of-Experts FFN with expert parallelism (``expert`` mesh axis).

Role (SURVEY.md §2c "EP" row): absent from the reference; a capability add of
the TPU rebuild.  Switch-Transformer-style top-k routing with capacity:

  * routing, dispatch and combine are dense one-hot einsums — static shapes,
    MXU-friendly, no gathers (the TPU idiom for MoE);
  * expert weights and the dispatched token buffer are sharding-constrained
    onto the ``expert`` axis, so under jit XLA lowers the dispatch/combine
    einsums into the all-to-alls of classic expert parallelism;
  * tokens over an expert's capacity are dropped (residual passes through),
    reported via the aux losses dict — load-balance loss (Switch eq. 4) and
    router z-loss keep the router honest.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    d_model: int = 512
    d_ff: int = 2048


def init_moe(key: jax.Array, config: MoEConfig) -> dict:
    kr, k1, k2 = jax.random.split(key, 3)
    E, d, f = config.num_experts, config.d_model, config.d_ff
    s = d ** -0.5
    return {
        "router": jax.random.normal(kr, (d, E), jnp.float32) * 0.02,
        "wi": (jax.random.normal(k1, (E, d, f), jnp.float32) * s).astype(jnp.bfloat16),
        "wo": (jax.random.normal(k2, (E, f, d), jnp.float32) * (f ** -0.5)).astype(jnp.bfloat16),
    }


def moe_ffn(
    params: dict,
    x: jax.Array,  # [B, S, d_model]
    config: MoEConfig,
    shard: bool = True,
) -> tuple[jax.Array, dict]:
    """Returns (output [B,S,d], aux {load_balance_loss, router_z_loss, fraction_dropped})."""
    b, s, d = x.shape
    E, k = config.num_experts, config.top_k
    T = b * s
    cap = max(1, int(config.capacity_factor * T * k / E))
    xt = x.reshape(T, d)

    # ---- routing (f32: router logits are precision-sensitive)
    logits = xt.astype(jnp.float32) @ params["router"]          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k dispatch: iterate k choices, masking previous picks
    combine = jnp.zeros((T, E, cap), jnp.float32)
    dispatch = jnp.zeros((T, E, cap), bool)
    fills = jnp.zeros((E,), jnp.int32)
    masked = probs
    for _ in range(k):
        choice = masked.argmax(axis=-1)                          # [T]
        gate = jnp.take_along_axis(masked, choice[:, None], axis=1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)      # [T, E]
        pos = fills[None, :] + jnp.cumsum(onehot, axis=0) - onehot  # position within expert
        keep = (pos < cap) & (onehot > 0)
        posc = jnp.clip(pos, 0, cap - 1)
        oh_cap = jax.nn.one_hot(posc, cap, dtype=jnp.float32) * keep[..., None]  # [T,E,cap]
        combine = combine + oh_cap * gate[:, None, None]
        dispatch = dispatch | (oh_cap > 0)
        fills = fills + jnp.sum(onehot * keep, axis=0)
        masked = masked * (1.0 - onehot)

    # ---- dispatch -> expert compute -> combine (dense einsums)
    disp_f = dispatch.astype(x.dtype)
    expert_in = jnp.einsum("tec,td->ecd", disp_f, xt)            # [E, cap, d]
    if shard:
        expert_in = jax.lax.with_sharding_constraint(expert_in, P("expert", None, None))
    h = jnp.einsum("ecd,edf->ecf", expert_in, params["wi"])
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["wo"])     # [E, cap, d]
    if shard:
        expert_out = jax.lax.with_sharding_constraint(expert_out, P("expert", None, None))
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)

    # ---- aux losses
    # Switch load-balance: E * sum_e fraction_tokens_e * mean_router_prob_e
    top1 = jax.nn.one_hot(probs.argmax(axis=-1), E, dtype=jnp.float32)
    load_balance = E * jnp.sum(top1.mean(axis=0) * probs.mean(axis=0))
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    routed = jnp.sum(combine > 0, axis=(1, 2))                   # assignments kept per token
    dropped = 1.0 - jnp.sum(routed) / (T * k)

    return out.reshape(b, s, d), {
        "load_balance_loss": load_balance,
        "router_z_loss": z_loss,
        "fraction_dropped": dropped,
    }
