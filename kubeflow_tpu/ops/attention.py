"""Attention ops, written for the MXU.

Design (pallas_guide-informed): keep the contraction shapes large and static,
let XLA fuse softmax into the matmuls; heads ride the ``tensor`` mesh axis via
the models' sharding rules, sequence rides ``seq``.  A Pallas flash-attention
kernel (ops/flash_attention.py) plugs in behind the same signature for long
sequences; ring attention (ops/ring_attention.py) extends it across the ICI
ring for context parallelism.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # bf16-safe large negative (not -inf: softmax of all-masked rows)


def multihead_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, T, H, D]
    v: jax.Array,  # [B, T, H, D]
    mask: Optional[jax.Array] = None,  # broadcastable to [B, H, S, T]; True = attend
    causal: bool = False,
) -> jax.Array:
    """Plain softmax attention over [batch, seq, heads, head_dim] tensors."""
    *_, s, h, d = q.shape
    t = k.shape[1]
    scale = d ** -0.5
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((s, t), dtype=bool))
        logits = jnp.where(causal_mask[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    # softmax in fp32 for stability, output back in input dtype
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def attention_flops(batch: int, seq: int, heads: int, head_dim: int, causal: bool = False) -> float:
    """Matmul FLOPs of one attention call (fwd only): QK^T + PV."""
    f = 2 * 2 * batch * heads * seq * seq * head_dim
    return f / 2 if causal else f


def padding_mask(attention_mask: jax.Array) -> jax.Array:
    """[B, T] {0,1} token mask → [B, 1, 1, T] broadcastable boolean."""
    return attention_mask[:, None, None, :].astype(bool)
