"""EventRecorder — k8s Events on every reconcile decision.

Upstream analogue (UNVERIFIED): client-go ``record.EventRecorder``; SURVEY.md
§5 notes events+conditions are the platform's observability UX and must be
kept verbatim.
"""

from __future__ import annotations

import time
import uuid

from .api import APIServer, Obj


class EventRecorder:
    def __init__(self, api: APIServer, component: str):
        self.api = api
        self.component = component

    def event(self, obj: Obj, etype: str, reason: str, message: str) -> None:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default")
        self.api.create(
            {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {
                    "name": f"{meta.get('name', 'unknown')}.{uuid.uuid4().hex[:12]}",
                    "namespace": ns,
                },
                "type": etype,  # Normal | Warning
                "reason": reason,
                "message": message,
                "source": {"component": self.component},
                "involvedObject": {
                    "kind": obj.get("kind"),
                    "name": meta.get("name"),
                    "namespace": ns,
                    "uid": meta.get("uid"),
                },
                "firstTimestamp": time.time(),
            }
        )

    def normal(self, obj: Obj, reason: str, message: str) -> None:
        self.event(obj, "Normal", reason, message)

    def warning(self, obj: Obj, reason: str, message: str) -> None:
        self.event(obj, "Warning", reason, message)


def events_for(api: APIServer, obj: Obj) -> list[Obj]:
    uid = obj["metadata"]["uid"]
    return [
        e
        for e in api.list("Event", namespace=obj["metadata"].get("namespace", "default"))
        if e.get("involvedObject", {}).get("uid") == uid
    ]
