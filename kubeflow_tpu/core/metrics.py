"""Controller metrics: Prometheus-style registry + /metrics endpoint.

Upstream analogue (UNVERIFIED, SURVEY.md §5): controller-runtime's
``controller_runtime_reconcile_total``/``_errors_total`` plus
training-operator's jobs created/successful/failed counters, exposed on each
manager's /metrics.  One process-global registry (controllers in this
simulator share a process), text exposition format, optional HTTP server.
"""

from __future__ import annotations

import math
import threading
from typing import Optional, Sequence

# Prometheus client-library default latency buckets (seconds).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0)


def escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition is unparseable."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(key: tuple) -> str:
    return ",".join(f'{k}="{escape_label_value(val)}"' for k, val in key)


def add_const_labels(text: str, labels: dict) -> str:
    """Rewrite rendered exposition so every sample carries extra constant
    labels.  The multi-registry merge case: two engines in one server each
    render ``engine_ttft_seconds`` — without a distinguishing label the
    combined scrape has duplicate series and Prometheus rejects it whole.
    Comment/blank lines pass through; labels are appended after existing
    ones (label order is not significant to scrapers)."""
    if not labels:
        return text
    import re

    extra = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    sample = re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (.*)$')
    out = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            out.append(line)
            continue
        m = sample.match(line)
        if m is None:  # not a sample line: leave untouched
            out.append(line)
            continue
        name, labs, value = m.group(1), m.group(2), m.group(3)
        merged = f"{labs},{extra}" if labs else extra
        out.append(f"{name}{{{merged}}} {value}")
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


def _fmt_value(v: float) -> str:
    """Exact sample rendering: %g keeps 6 significant digits, which would
    round counters/sums past ~1e6 at the SOURCE exposition and break the
    fleet merge's sum-exact contract before merging even starts.  Integral
    values render as ints, everything else at full precision."""
    if math.isfinite(v) and v == int(v):
        return str(int(v))
    return repr(v)


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def labels_key(self, labels: dict) -> tuple:
        return tuple(sorted(labels.items()))

    def series(self) -> dict:
        """Snapshot of every label set's current value, keyed by the sorted
        (label, value) tuple — counter/gauge introspection for tests and
        benches without parsing the text exposition (the fleet bench reads
        retry/ejection counters this way)."""
        with self._lock:
            return dict(self._values)

    def remove(self, **labels) -> None:
        """Drop one label set's sample entirely.  A gauge whose underlying
        signal has no data must STOP exporting, not freeze at its last
        value (the SLO exporter uses this when a series' samples age out
        of every window)."""
        with self._lock:
            self._values.pop(self.labels_key(labels), None)

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                label_s = _fmt_labels(key)
                v_s = _fmt_value(v)
                lines.append(f"{self.name}{{{label_s}}} {v_s}" if label_s else f"{self.name} {v_s}")
        return "\n".join(lines)


class Counter(_Metric):
    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "counter")

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self.labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self.labels_key(labels), 0.0)


class Gauge(_Metric):
    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "gauge")

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self.labels_key(labels)] = float(value)

    def value(self, **labels) -> float:
        return self._values.get(self.labels_key(labels), 0.0)


class Histogram(_Metric):
    """Prometheus histogram: cumulative ``_bucket{le=...}`` counts plus
    ``_sum``/``_count``, per label set.  Buckets are fixed at construction
    (upper bounds, seconds by convention); ``+Inf`` is implicit."""

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, "histogram")
        self.buckets = tuple(sorted(set(float(b) for b in buckets)))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        # label key -> [per-bucket counts (non-cumulative), sum, count]
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = self.labels_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            counts, _, _ = s
            # first bucket whose upper bound holds the value; the trailing
            # slot is +Inf
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s[1] += value
            s[2] += 1

    def remove(self, **labels) -> None:
        # histogram samples live in _series, not the base class's _values —
        # without this override remove() would silently no-op
        with self._lock:
            self._series.pop(self.labels_key(labels), None)

    def snapshot(self, **labels) -> dict:
        """(cumulative bucket counts, sum, count) for one label set —
        test/bench introspection without parsing the text format."""
        key = self.labels_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return {"buckets": {}, "sum": 0.0, "count": 0}
            cum, acc = {}, 0
            for b, c in zip(self.buckets, s[0]):
                acc += c
                cum[b] = acc
            return {"buckets": cum, "sum": s[1], "count": s[2]}

    def quantile(self, q: float, **labels) -> float:
        """Estimate the q-quantile (0..1) by linear interpolation inside the
        owning bucket — the PromQL histogram_quantile estimator."""
        snap = self.snapshot(**labels)
        n = snap["count"]
        if n == 0:
            return 0.0
        rank = q * n
        lo = 0.0
        prev_c = 0
        for b, c in snap["buckets"].items():
            if c >= rank:
                width = b - lo
                frac = (rank - prev_c) / max(1, c - prev_c)
                return lo + width * frac
            lo, prev_c = b, c
        return self.buckets[-1]

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, (counts, sum_, count) in sorted(self._series.items()):
                base = _fmt_labels(key)
                acc = 0
                for b, c in zip(self.buckets, counts):
                    acc += c
                    lab = (base + "," if base else "") + f'le="{b:g}"'
                    lines.append(f"{self.name}_bucket{{{lab}}} {acc}")
                lab = (base + "," if base else "") + 'le="+Inf"'
                lines.append(f"{self.name}_bucket{{{lab}}} {count}")
                sfx = f"{{{base}}}" if base else ""
                lines.append(f"{self.name}_sum{sfx} {_fmt_value(sum_)}")
                lines.append(f"{self.name}_count{sfx} {count}")
        return "\n".join(lines)


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name, help_)
            return m  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name, help_)
            return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Histogram(name, help_, buckets)
            return m  # type: ignore[return-value]

    def names(self) -> list[str]:
        """Registered metric names — the metrics-conformance test walks
        these against the README metric table."""
        with self._lock:
            return sorted(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"


# ------------------------------------------------ fleet-scrape merge helpers

_EXPO_SAMPLE = None  # compiled lazily (merge is a debug/scrape-time path)


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text exposition into
    ``{name: {"type": kind|None, "help": str|None, "samples":
    [(labels_dict, value)]}}``.  Histogram component series
    (``_bucket``/``_sum``/``_count``) are grouped under their base name's
    entry when a ``# TYPE <base> histogram`` line declared them."""
    global _EXPO_SAMPLE
    if _EXPO_SAMPLE is None:
        import re
        _EXPO_SAMPLE = (
            re.compile(r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (\S+)$'),
            re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"'),
            re.compile(r'\\(.)'))
    sample_re, label_re, esc_re = _EXPO_SAMPLE
    # single-pass unescape: chained str.replace would decode the \\ of a
    # literal backslash FIRST or LAST and either way corrupt sequences
    # like backslash-then-n (escaped as \\n, which must NOT become \n)
    unescape = lambda v: esc_re.sub(  # noqa: E731
        lambda m: {"n": "\n"}.get(m.group(1), m.group(1)), v)
    out: dict = {}

    def entry(name: str) -> dict:
        return out.setdefault(name, {"type": None, "help": None,
                                     "samples": []})

    hist_bases: set = set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) >= 4:
                entry(parts[2])["type"] = parts[3].strip()
                if parts[3].strip() == "histogram":
                    hist_bases.add(parts[2])
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) >= 3:
                entry(parts[2])["help"] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        if m is None:
            continue
        name, labs, val = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(val)
        except ValueError:
            continue
        labels = {k: unescape(v) for k, v in label_re.findall(labs)}
        # histogram component samples file under the BASE name so merge
        # logic sees one histogram, not three pseudo-metrics
        base = name
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and name[:-len(sfx)] in hist_bases:
                base = name[:-len(sfx)]
                labels["__series__"] = sfx
                break
        entry(base)["samples"].append((labels, value))
    return out


def merge_expositions(replica_texts: dict) -> str:
    """Merge per-replica /metrics expositions into one fleet view
    (``GET /fleet/metrics`` on the service proxy).

    Counters and histograms are ADDITIVE across replicas: series with the
    same label set sum sample-by-sample — histogram buckets, ``_sum`` and
    ``_count`` are all plain sums, so the merged histogram is exactly the
    histogram of the union of observations (sum-exact, tested).  Gauges
    are NOT additive (two replicas' occupancy ratios don't add): each
    gauge sample instead keeps its replica as a ``replica`` label.
    Untyped samples (the model server's flat extra_metrics gauges) are
    treated as gauges.  ``replica_texts``: {replica_label: exposition}."""
    merged: dict = {}
    for replica in sorted(replica_texts):
        parsed = parse_exposition(replica_texts[replica])
        for name, rec in parsed.items():
            m = merged.setdefault(name, {"type": rec["type"],
                                         "help": rec["help"],
                                         "series": {}})
            if m["type"] is None:
                m["type"] = rec["type"]
            if m["help"] is None:
                m["help"] = rec["help"]
            kind = rec["type"] or "gauge"
            additive = kind in ("counter", "histogram")
            for labels, value in rec["samples"]:
                labels = dict(labels)
                if not additive:
                    labels["replica"] = replica
                key = tuple(sorted(labels.items()))
                if additive:
                    m["series"][key] = m["series"].get(key, 0.0) + value
                else:
                    m["series"][key] = value
    lines = []
    for name in sorted(merged):
        m = merged[name]
        kind = m["type"] or "gauge"
        if m["help"] is not None:
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {kind}")
        for key in sorted(m["series"], key=_series_sort_key):
            labels = dict(key)
            sfx = labels.pop("__series__", "")
            label_s = _fmt_labels(tuple(sorted(labels.items())))
            sample_name = name + sfx
            body = f"{sample_name}{{{label_s}}}" if label_s else sample_name
            lines.append(f"{body} {_fmt_value(m['series'][key])}")
    return "\n".join(lines) + "\n"


def _series_sort_key(key: tuple) -> tuple:
    """Stable series ordering for the merged exposition: histogram
    component (_bucket < _sum < _count), then bucket bound numerically,
    then the remaining labels lexically — so merged buckets render in
    ascending-le order like a native histogram."""
    labels = dict(key)
    sfx = labels.pop("__series__", "")
    sfx_rank = {"": 0, "_bucket": 0, "_sum": 1, "_count": 2}.get(sfx, 3)
    le = labels.pop("le", None)
    if le == "+Inf":
        le_rank = float("inf")
    else:
        try:
            le_rank = float(le) if le is not None else float("-inf")
        except ValueError:
            le_rank = float("inf")
    return (tuple(sorted(labels.items())), sfx_rank, le_rank)


REGISTRY = Registry()

# the controller-runtime-equivalent core metrics
RECONCILE_TOTAL = REGISTRY.counter(
    "controller_runtime_reconcile_total", "reconciles per controller kind and result"
)
RECONCILE_ERRORS = REGISTRY.counter(
    "controller_runtime_reconcile_errors_total", "reconcile panics/errors per kind"
)
JOBS_CREATED = REGISTRY.counter("training_operator_jobs_created_total", "jobs accepted")
JOBS_SUCCESSFUL = REGISTRY.counter("training_operator_jobs_successful_total", "jobs succeeded")
JOBS_FAILED = REGISTRY.counter("training_operator_jobs_failed_total", "jobs failed")
JOBS_RESTARTED = REGISTRY.counter("training_operator_jobs_restarted_total", "job pod restarts")


def serve(port: int = 0) -> tuple[int, object]:
    """Expose /metrics over HTTP; returns (bound_port, server). port=0 picks
    a free port.  Runs in a daemon thread (shutdown() the server to stop)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API)
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            body = REGISTRY.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # pragma: no cover - silence stdlib
            pass

    server = HTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server.server_address[1], server
