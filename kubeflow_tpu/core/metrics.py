"""Controller metrics: Prometheus-style registry + /metrics endpoint.

Upstream analogue (UNVERIFIED, SURVEY.md §5): controller-runtime's
``controller_runtime_reconcile_total``/``_errors_total`` plus
training-operator's jobs created/successful/failed counters, exposed on each
manager's /metrics.  One process-global registry (controllers in this
simulator share a process), text exposition format, optional HTTP server.
"""

from __future__ import annotations

import threading
from typing import Optional


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def labels_key(self, labels: dict) -> tuple:
        return tuple(sorted(labels.items()))

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                label_s = ",".join(f'{k}="{val}"' for k, val in key)
                lines.append(f"{self.name}{{{label_s}}} {v:g}" if label_s else f"{self.name} {v:g}")
        return "\n".join(lines)


class Counter(_Metric):
    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "counter")

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self.labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self.labels_key(labels), 0.0)


class Gauge(_Metric):
    def __init__(self, name: str, help_: str):
        super().__init__(name, help_, "gauge")

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self.labels_key(labels)] = float(value)

    def value(self, **labels) -> float:
        return self._values.get(self.labels_key(labels), 0.0)


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Counter(name, help_)
            return m  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = Gauge(name, help_)
            return m  # type: ignore[return-value]

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.render() for m in metrics) + "\n"


REGISTRY = Registry()

# the controller-runtime-equivalent core metrics
RECONCILE_TOTAL = REGISTRY.counter(
    "controller_runtime_reconcile_total", "reconciles per controller kind and result"
)
RECONCILE_ERRORS = REGISTRY.counter(
    "controller_runtime_reconcile_errors_total", "reconcile panics/errors per kind"
)
JOBS_CREATED = REGISTRY.counter("training_operator_jobs_created_total", "jobs accepted")
JOBS_SUCCESSFUL = REGISTRY.counter("training_operator_jobs_successful_total", "jobs succeeded")
JOBS_FAILED = REGISTRY.counter("training_operator_jobs_failed_total", "jobs failed")
JOBS_RESTARTED = REGISTRY.counter("training_operator_jobs_restarted_total", "job pod restarts")


def serve(port: int = 0) -> tuple[int, object]:
    """Expose /metrics over HTTP; returns (bound_port, server). port=0 picks
    a free port.  Runs in a daemon thread (shutdown() the server to stop)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API)
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            body = REGISTRY.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # pragma: no cover - silence stdlib
            pass

    server = HTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server.server_address[1], server
