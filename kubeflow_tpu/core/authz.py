"""Authn/z on the API surface: profile-RBAC authorization + scoped clients.

Upstream analogue (UNVERIFIED, SURVEY.md §1 X-row): Istio terminates authn at
the mesh edge (the `kubeflow-userid` header) and authorization is the
RoleBindings the Profile controller / KFAM materialize per namespace.  Here
the same trust boundary lands on ``AuthenticatedAPI`` — a per-user view over
the APIServer that SubjectAccessReview-checks every verb before delegating —
so UIs/SDK services can serve multi-tenant requests without each inventing
its own checks.

Roles (KFAM's ClusterRole set): ``admin``/``edit`` may mutate, ``view`` may
only read; a profile's OWNER is implicitly admin in its namespace; members of
``cluster_admins`` are admin everywhere (including non-namespaced kinds).
"""

from __future__ import annotations

from typing import Iterable, Optional

from .api import APIServer, Obj

READ_VERBS = ("get", "list", "watch")
WRITE_VERBS = ("create", "update", "patch", "delete")

_ROLE_VERBS = {
    "admin": READ_VERBS + WRITE_VERBS,
    "edit": READ_VERBS + WRITE_VERBS,
    "view": READ_VERBS,
}


class Forbidden(PermissionError):
    pass


class ProfileRBACAuthorizer:
    """KFAM-materialized RoleBindings + profile ownership → allow/deny."""

    def __init__(self, api: APIServer, cluster_admins: Iterable[str] = ()):
        self.api = api
        self.cluster_admins = set(cluster_admins)

    def roles_for(self, user: str, namespace: str) -> set[str]:
        roles = set()
        # try_get already returns None for a missing object or unregistered
        # kind, so no guard is needed there
        prof = self.api.try_get("Profile", namespace)
        if prof is not None and prof["spec"].get("owner", {}).get("name") == user:
            roles.add("admin")
        # a partially-installed platform (kfadm subsets) may not register the
        # RoleBinding CRD at all — api.list raises bare KeyError for an
        # unregistered kind; that means "no grants", not an authorizer crash
        # (cluster_admins still pass in authorize())
        try:
            bindings = self.api.list("RoleBinding", namespace=namespace)
        except KeyError:
            bindings = []
        for b in bindings:
            labels = b["metadata"].get("labels", {})
            if labels.get("user") == user and labels.get("role") in _ROLE_VERBS:
                roles.add(labels["role"])
        return roles

    def authorize(self, user: str, verb: str, kind: str,
                  namespace: Optional[str]) -> bool:
        if user in self.cluster_admins:
            return True
        if namespace is None:
            # non-namespaced kinds (Nodes, Profiles, …): cluster admins only
            # — except reads of Profiles, which every authenticated user may
            # list (the dashboard's namespace picker needs it, as upstream)
            return kind == "Profile" and verb in READ_VERBS
        for role in self.roles_for(user, namespace):
            if verb in _ROLE_VERBS[role]:
                return True
        return False


class AuthenticatedAPI:
    """A per-user facade over APIServer: every call is authorized first.

    The SelfSubjectAccessReview-shaped hop every UI backend goes through;
    construct one per request (cheap) with the identity the ingress
    authenticated.
    """

    def __init__(self, api: APIServer, user: str, authorizer: ProfileRBACAuthorizer):
        self.api = api
        self.user = user
        self.authorizer = authorizer

    def _check(self, verb: str, kind: str, namespace: Optional[str]) -> None:
        crd = self.api.crd_for(kind)
        ns = namespace if crd.namespaced else None
        if not self.authorizer.authorize(self.user, verb, kind, ns):
            raise Forbidden(
                f"user {self.user!r} cannot {verb} {kind}"
                + (f" in namespace {ns!r}" if ns else " (cluster-scoped)"))

    # -------------------------------------------------------------- verbs

    def create(self, obj: Obj) -> Obj:
        self._check("create", obj["kind"], obj["metadata"].get("namespace", "default"))
        return self.api.create(obj)

    def update(self, obj: Obj) -> Obj:
        self._check("update", obj["kind"], obj["metadata"].get("namespace", "default"))
        return self.api.update(obj)

    def patch(self, kind: str, name: str, patch: dict, namespace: str = "default") -> Obj:
        self._check("patch", kind, namespace)
        return self.api.patch(kind, name, patch, namespace)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        self._check("delete", kind, namespace)
        self.api.delete(kind, name, namespace)

    def get(self, kind: str, name: str, namespace: str = "default") -> Obj:
        self._check("get", kind, namespace)
        return self.api.get(kind, name, namespace)

    def try_get(self, kind: str, name: str, namespace: str = "default") -> Optional[Obj]:
        self._check("get", kind, namespace)
        return self.api.try_get(kind, name, namespace)

    def list(self, kind: str, namespace: Optional[str] = "default", **kw) -> list[Obj]:
        crd = self.api.crd_for(kind)
        if not crd.namespaced:
            namespace = None
        if crd.namespaced and namespace is None:
            # cross-namespace list: filter to the namespaces the user can
            # read; memoize per namespace (one decision per ns, not per obj)
            decided: dict[str, bool] = {}
            out = []
            for obj in self.api.list(kind, namespace=None, **kw):
                ns = obj["metadata"].get("namespace", "default")
                if ns not in decided:
                    decided[ns] = self.authorizer.authorize(self.user, "list", kind, ns)
                if decided[ns]:
                    out.append(obj)
            return out
        self._check("list", kind, namespace)
        return self.api.list(kind, namespace=namespace, **kw)

    def watch(self, kind: str, namespace: Optional[str] = None, **kw):
        self._check("watch", kind, namespace)
        return self.api.watch(kind, namespace=namespace, **kw)
