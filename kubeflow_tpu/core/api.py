"""In-process Kubernetes-compatible API machinery.

This is the L0 substrate of the platform (SURVEY.md §1, §7 phase 1): a typed
object store with the semantics controllers rely on upstream —
``resourceVersion`` optimistic concurrency, list/watch streams, labels and
selectors, ownerReference cascade deletion, namespaces, and Events.

Design notes (TPU-first rebuild, not a port):
  * Objects are plain dicts shaped exactly like Kubernetes resources
    (``apiVersion``/``kind``/``metadata``/``spec``/``status``) so specs written
    as YAML/JSON round-trip unmodified; typed dataclass builders live in each
    component's ``api.py``.
  * The server is deliberately synchronous and thread-safe.  Controllers run on
    a deterministic single-threaded manager (see controller.py) which makes
    reconcile-driven tests reproducible — the upstream analogue is
    controller-runtime's envtest, but here the "cluster" is in-process.
  * Upstream analogue (UNVERIFIED, reference mount empty — see SURVEY.md):
    k8s apiserver + etcd; controller-runtime client.
"""

from __future__ import annotations

import copy
import fnmatch
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

Obj = dict  # a Kubernetes-shaped resource body


class ApiError(Exception):
    """Base class for API errors."""

    code = 500


class NotFound(ApiError):
    code = 404


class AlreadyExists(ApiError):
    code = 409


class Conflict(ApiError):
    """resourceVersion mismatch on update."""

    code = 409


class Invalid(ApiError):
    code = 422


@dataclass(frozen=True)
class CRD:
    """A registered resource type (built-ins are registered the same way)."""

    group: str
    version: str
    kind: str
    plural: str
    namespaced: bool = True
    validator: Optional[Callable[[Obj], None]] = None   # raise Invalid on bad spec
    defaulter: Optional[Callable[[Obj], None]] = None   # mutate obj in place

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


@dataclass(frozen=True)
class GVK:
    group: str
    kind: str

    @staticmethod
    def of(obj: Obj) -> "GVK":
        api_version = obj.get("apiVersion", "")
        group = api_version.split("/")[0] if "/" in api_version else ""
        return GVK(group, obj["kind"])


def _split_api_version(api_version: str) -> tuple[str, str]:
    if "/" in api_version:
        g, v = api_version.split("/", 1)
        return g, v
    return "", api_version


def match_labels(labels: Optional[dict], selector: Optional[dict]) -> bool:
    """Equality-based selector match (the subset upstream controllers use)."""
    if not selector:
        return True
    labels = labels or {}
    return all(labels.get(k) == v for k, v in selector.items())


class WatchEvent:
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"

    __slots__ = ("type", "object")

    def __init__(self, type_: str, object_: Obj):
        self.type = type_
        self.object = object_

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        m = self.object.get("metadata", {})
        return (
            f"WatchEvent({self.type}, {self.object.get('kind')} "
            f"{m.get('namespace')}/{m.get('name')} rv={m.get('resourceVersion')})"
        )


class Watcher:
    """A watch stream: a queue of WatchEvents for one (kind, namespace) scope."""

    def __init__(self, kind: str, namespace: Optional[str], label_selector: Optional[dict]):
        self.kind = kind
        self.namespace = namespace
        self.label_selector = label_selector
        self._q: "queue.Queue[WatchEvent]" = queue.Queue()
        self.closed = False

    def _offer(self, ev: WatchEvent) -> None:
        if self.closed:
            return
        meta = ev.object.get("metadata", {})
        if self.namespace is not None and meta.get("namespace") != self.namespace:
            return
        if not match_labels(meta.get("labels"), self.label_selector):
            return
        self._q.put(ev)

    def poll(self) -> Optional[WatchEvent]:
        """Non-blocking: next event or None."""
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def stop(self) -> None:
        self.closed = True


class APIServer:
    """The in-process apiserver + store.

    Storage model: ``self._objects[kind][(namespace, name)] = obj``.  All
    returned objects are deep copies — mutating a returned object never
    touches the store (same value semantics a REST roundtrip gives you).
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._crds: dict[str, CRD] = {}          # by kind
        self._objects: dict[str, dict[tuple, Obj]] = {}
        self._watchers: dict[str, list[Watcher]] = {}
        self._mutating_webhooks: dict[str, list[Callable[[Obj], None]]] = {}
        # resources installed components want released at cluster shutdown
        # (e.g. the Katib db-manager's listening socket) — Cluster.shutdown
        # runs these; installers register via add_teardown
        self._teardowns: list[Callable[[], None]] = []
        self._rv = 0
        self.register_crd(CRD(group="", version="v1", kind="Namespace", plural="namespaces", namespaced=False))
        self.register_crd(CRD(group="", version="v1", kind="Pod", plural="pods"))
        self.register_crd(CRD(group="", version="v1", kind="Service", plural="services"))
        self.register_crd(CRD(group="", version="v1", kind="ConfigMap", plural="configmaps"))
        self.register_crd(CRD(group="", version="v1", kind="Secret", plural="secrets"))
        self.register_crd(CRD(group="", version="v1", kind="Event", plural="events"))
        self.register_crd(CRD(group="", version="v1", kind="Node", plural="nodes", namespaced=False))
        self.register_crd(CRD(group="", version="v1", kind="PersistentVolumeClaim", plural="persistentvolumeclaims"))
        self.register_crd(CRD(group="apps", version="v1", kind="Deployment", plural="deployments"))
        self.register_crd(CRD(group="apps", version="v1", kind="StatefulSet", plural="statefulsets"))
        self.ensure_namespace("default")
        self.ensure_namespace("kubeflow")

    # ------------------------------------------------------------------ CRDs

    def register_crd(self, crd: CRD) -> None:
        with self._lock:
            self._crds[crd.kind] = crd
            self._objects.setdefault(crd.kind, {})
            self._watchers.setdefault(crd.kind, [])

    def crd_for(self, kind: str) -> CRD:
        try:
            return self._crds[kind]
        except KeyError:
            raise NotFound(f"no resource type registered for kind {kind!r}")

    def add_teardown(self, fn: Callable[[], None]) -> None:
        """Register a cleanup hook run by Cluster.shutdown (idempotence is
        the hook's responsibility)."""
        with self._lock:
            self._teardowns.append(fn)

    def run_teardowns(self) -> None:
        with self._lock:
            hooks, self._teardowns = list(self._teardowns), []
        for fn in reversed(hooks):
            try:
                fn()
            except Exception:  # noqa: BLE001 — teardown must not mask teardown
                import traceback

                traceback.print_exc()

    def register_mutating_webhook(self, kind: str, fn: Callable[[Obj], None]) -> None:
        """Admission-webhook equivalent: fn mutates the object at create time
        (after defaulting, before validation) — upstream analogue is the
        PodDefaults mutating webhook (SURVEY.md §2a)."""
        with self._lock:
            self.crd_for(kind)
            self._mutating_webhooks.setdefault(kind, []).append(fn)

    # ------------------------------------------------------------- namespaces

    def ensure_namespace(self, name: str) -> None:
        with self._lock:
            if ("", name) not in self._objects["Namespace"]:
                self.create({"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": name}})

    # ------------------------------------------------------------------ CRUD

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def store_version(self) -> int:
        """Monotonic write counter: bumps on every create/update/status
        write/delete.  Read-heavy callers (the ingress relay path reads
        the Service and ready-pod list per request) key snapshot caches
        on this — an unchanged version guarantees list()/get() would
        return byte-identical objects, so the per-call deepcopy can be
        skipped entirely."""
        with self._lock:
            return self._rv

    def _key(self, crd: CRD, meta: dict) -> tuple:
        ns = meta.get("namespace", "default") if crd.namespaced else ""
        return (ns, meta["name"])

    def create(self, obj: Obj) -> Obj:
        with self._lock:
            obj = copy.deepcopy(obj)
            kind = obj.get("kind")
            if not kind:
                raise Invalid("object has no kind")
            crd = self.crd_for(kind)
            obj.setdefault("apiVersion", crd.api_version)
            meta = obj.setdefault("metadata", {})
            if "name" not in meta and "generateName" in meta:
                meta["name"] = meta["generateName"] + uuid.uuid4().hex[:8]
            if "name" not in meta:
                raise Invalid(f"{kind} has no metadata.name")
            if crd.namespaced:
                meta.setdefault("namespace", "default")
                self.ensure_namespace(meta["namespace"])
            key = self._key(crd, meta)
            if key in self._objects[kind]:
                raise AlreadyExists(f"{kind} {key[0]}/{key[1]} already exists")
            meta["uid"] = uuid.uuid4().hex
            meta["creationTimestamp"] = time.time()
            meta["resourceVersion"] = self._next_rv()
            meta.setdefault("labels", {})
            meta.setdefault("annotations", {})
            if crd.defaulter:
                crd.defaulter(obj)
            for hook in self._mutating_webhooks.get(kind, []):
                hook(obj)
            if crd.validator:
                crd.validator(obj)
            self._objects[kind][key] = obj
            self._notify(WatchEvent(WatchEvent.ADDED, copy.deepcopy(obj)), kind)
            return copy.deepcopy(obj)

    def get(self, kind: str, name: str, namespace: str = "default") -> Obj:
        with self._lock:
            crd = self.crd_for(kind)
            key = (namespace if crd.namespaced else "", name)
            try:
                return copy.deepcopy(self._objects[kind][key])
            except KeyError:
                raise NotFound(f"{kind} {key[0]}/{key[1]} not found")

    def try_get(self, kind: str, name: str, namespace: str = "default") -> Optional[Obj]:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict] = None,
        field_selector: Optional[Callable[[Obj], bool]] = None,
    ) -> list[Obj]:
        with self._lock:
            out = []
            for (ns, _), obj in sorted(self._objects[kind].items()):
                if namespace is not None and ns != namespace:
                    continue
                if not match_labels(obj["metadata"].get("labels"), label_selector):
                    continue
                if field_selector is not None and not field_selector(obj):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def update(self, obj: Obj) -> Obj:
        """Full-object update with resourceVersion optimistic concurrency."""
        with self._lock:
            obj = copy.deepcopy(obj)
            kind = obj["kind"]
            crd = self.crd_for(kind)
            meta = obj["metadata"]
            key = self._key(crd, meta)
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFound(f"{kind} {key[0]}/{key[1]} not found")
            if meta.get("resourceVersion") != current["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{kind} {key[1]}: resourceVersion {meta.get('resourceVersion')} "
                    f"!= {current['metadata']['resourceVersion']}"
                )
            # immutable fields
            meta["uid"] = current["metadata"]["uid"]
            meta["creationTimestamp"] = current["metadata"]["creationTimestamp"]
            meta["resourceVersion"] = self._next_rv()
            if crd.validator:
                crd.validator(obj)
            self._objects[kind][key] = obj
            self._notify(WatchEvent(WatchEvent.MODIFIED, copy.deepcopy(obj)), kind)
            return copy.deepcopy(obj)

    def update_status(self, obj: Obj) -> Obj:
        """Status-subresource style update: only .status (+rv bump) is applied."""
        with self._lock:
            kind = obj["kind"]
            crd = self.crd_for(kind)
            meta = obj["metadata"]
            key = self._key(crd, meta)
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFound(f"{kind} {key[0]}/{key[1]} not found")
            if meta.get("resourceVersion") != current["metadata"]["resourceVersion"]:
                raise Conflict(f"{kind} {key[1]}: stale resourceVersion on status update")
            updated = copy.deepcopy(current)
            updated["status"] = copy.deepcopy(obj.get("status", {}))
            updated["metadata"]["resourceVersion"] = self._next_rv()
            self._objects[kind][key] = updated
            self._notify(WatchEvent(WatchEvent.MODIFIED, copy.deepcopy(updated)), kind)
            return copy.deepcopy(updated)

    def patch(self, kind: str, name: str, patch: dict, namespace: str = "default") -> Obj:
        """Strategic-merge-ish patch: recursive dict merge; None deletes a key."""
        with self._lock:
            current = self.get(kind, name, namespace)
            merged = _merge(current, patch)
            merged["metadata"]["resourceVersion"] = current["metadata"]["resourceVersion"]
            return self.update(merged)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            crd = self.crd_for(kind)
            key = (namespace if crd.namespaced else "", name)
            obj = self._objects[kind].get(key)
            if obj is None:
                raise NotFound(f"{kind} {key[0]}/{key[1]} not found")
            uid = obj["metadata"]["uid"]
            del self._objects[kind][key]
            # deletions must advance the store version too, or
            # store_version()-keyed snapshot caches would keep serving
            # the deleted object
            self._next_rv()
            self._notify(WatchEvent(WatchEvent.DELETED, copy.deepcopy(obj)), kind)
            # ownerReference cascade (synchronous "background" GC)
            self._cascade_delete(uid)

    def try_delete(self, kind: str, name: str, namespace: str = "default") -> bool:
        try:
            self.delete(kind, name, namespace)
            return True
        except NotFound:
            return False

    def _cascade_delete(self, owner_uid: str) -> None:
        doomed: list[tuple[str, str, str]] = []
        for kind, objs in self._objects.items():
            for (ns, name), obj in objs.items():
                for ref in obj["metadata"].get("ownerReferences", []):
                    if ref.get("uid") == owner_uid:
                        doomed.append((kind, name, ns))
        for kind, name, ns in doomed:
            try:
                self.delete(kind, name, ns)
            except NotFound:
                pass

    # ----------------------------------------------------------------- watch

    def watch(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict] = None,
        send_initial: bool = False,
    ) -> Watcher:
        with self._lock:
            self.crd_for(kind)
            w = Watcher(kind, namespace, label_selector)
            if send_initial:
                for obj in self.list(kind, namespace, label_selector):
                    w._offer(WatchEvent(WatchEvent.ADDED, obj))
            self._watchers[kind].append(w)
            return w

    def _notify(self, ev: WatchEvent, kind: str) -> None:
        live = []
        for w in self._watchers[kind]:
            if w.closed:
                continue
            w._offer(ev)
            live.append(w)
        self._watchers[kind] = live


def _merge(base: dict, patch: dict) -> dict:
    out = copy.deepcopy(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = copy.deepcopy(v)
    return out


def owner_reference(owner: Obj, controller: bool = True) -> dict:
    return {
        "apiVersion": owner["apiVersion"],
        "kind": owner["kind"],
        "name": owner["metadata"]["name"],
        "uid": owner["metadata"]["uid"],
        "controller": controller,
    }


def is_owned_by(obj: Obj, owner: Obj) -> bool:
    return any(
        r.get("uid") == owner["metadata"]["uid"]
        for r in obj["metadata"].get("ownerReferences", [])
    )
