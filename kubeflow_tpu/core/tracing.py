"""Distributed trace context + bounded span storage (ISSUE 8).

A fleet request crosses ingress, N engine replicas (retries, hedges,
mid-stream failovers), and — across session turns — time.  This module is
the correlation currency: a W3C-traceparent-style context minted at
ingress, propagated hop by hop, and adopted by the engine's RequestSpan so
one trace id names the whole journey.

  * ``TraceContext`` — (trace_id, span_id, parent_id).  ``mint()`` starts a
    trace; ``child()`` derives the next hop (same trace, fresh span, parent
    = the deriving span).  ``traceparent()``/``parse_traceparent`` speak
    the W3C header format (``00-<32 hex>-<16 hex>-01``) so external
    tracers interoperate.
  * ``TraceStore`` — bounded (entries AND bytes) store of finished span
    dicts keyed by trace id.  Whole traces evict oldest-first; the
    ``on_evict`` hook feeds the eviction counters
    (``ingress_trace_evictions_total`` / ``engine_trace_evictions_total``)
    so a long-lived fleet run can watch its own history pressure instead
    of growing without bound.
  * ``build_tree`` — nests a flat span list by ``parent_id`` into the hop
    tree the ``GET /debug/trace/<id>`` endpoint returns.

Span dicts are schema-light on purpose (component/name/outcome plus
whatever annotations the hop found interesting); the only structural keys
the tree builder needs are ``span_id`` and ``parent_id``.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Callable, Optional

TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

# a bare span id (e.g. the X-Resume-From header value): surfaces that
# store client-supplied ids must reject anything else, or budget
# accounting that assumes fixed-size ids undercounts
SPAN_ID_RE = re.compile(r"^[0-9a-f]{16}$")


class TraceContext:
    """One hop's identity inside a trace: ids only, no timing state."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def mint(cls) -> "TraceContext":
        """Start a new trace (the ingress does this when no inbound
        traceparent exists; the engine does it for direct API callers)."""
        return cls(new_trace_id(), new_span_id())

    def child(self) -> "TraceContext":
        """The next hop: same trace, fresh span id, this span as parent."""
        return TraceContext(self.trace_id, new_span_id(), self.span_id)

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return (f"TraceContext({self.trace_id[:8]}…, {self.span_id}, "
                f"parent={self.parent_id})")


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(value) -> Optional[TraceContext]:
    """Parse a W3C traceparent header; None on anything malformed (a bad
    header must degrade to a fresh trace, never fail the request).  The
    all-zero trace/span ids are invalid per the spec."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id, span_id)


def span_nbytes(span: dict) -> int:
    """Budget-accounting size of one span dict.  json.dumps is the honest
    estimator (these spans are served as JSON anyway) with a cheap floor
    for the unserializable-degenerate case."""
    try:
        return len(json.dumps(span, default=str))
    except (TypeError, ValueError):  # pragma: no cover - defensive
        return 256


class TraceStore:
    """Bounded trace-id -> [span dicts] store.

    Budgeted in BOTH entries (distinct traces) and bytes (sum of span
    sizes): a fleet soak with many short traces hits the entry cap, a few
    huge traces (long retries, deep session chains) hit the byte cap.
    Whole traces evict least-recently-WRITTEN-first (every ``put`` touches
    its trace to the back): insertion-order eviction made a long-lived
    trace that keeps receiving spans — a multi-turn session, a mid-stream
    failover, exactly the traces an incident bundle cites — the "oldest"
    entry, evicted while still actively written, while idle one-shot
    traces survived behind it.  Whole traces, never spans — a half-evicted
    trace would assemble into a tree that silently lies.  A trace
    STILL BEING WRITTEN when it was evicted (another thread's long stream
    under churn) re-creates with a synthetic ``evicted_history`` marker
    span, so the partial tree reads as "history truncated", never as "one
    clean attempt".  ``on_evict(n_traces)`` fires outside any per-span hot
    path."""

    def __init__(self, max_traces: int = 256, max_bytes: int = 1_000_000,
                 on_evict: Optional[Callable[[int], None]] = None):
        self.max_traces = max(1, int(max_traces))
        self.max_bytes = max(1, int(max_bytes))
        self.on_evict = on_evict
        self._traces: dict[str, list] = {}
        self._sizes: dict[str, int] = {}
        # tombstones of recently evicted trace ids (bounded FIFO): a put
        # landing on one means earlier spans of that trace were dropped
        self._tombstones: dict[str, None] = {}
        self._bytes = 0
        self._evicted = 0
        self._lock = threading.Lock()

    _TOMBSTONE_CAP = 4096

    def put(self, trace_id: str, span: dict) -> None:
        nb = span_nbytes(span)
        evicted = 0
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is not None:
                # LRU by last write: an actively-written trace moves to
                # the back so the eviction loop's next(iter(...)) finds
                # the trace that stopped receiving spans longest ago
                self._traces[trace_id] = self._traces.pop(trace_id)
            if spans is None:
                spans = self._traces[trace_id] = []
                self._sizes[trace_id] = 0
                if self._tombstones.pop(trace_id, "miss") is None:
                    marker = {"trace_id": trace_id, "span_id": None,
                              "parent_id": None, "name": "evicted_history",
                              "note": "earlier spans of this trace were "
                                      "evicted by the store budget"}
                    spans.append(marker)
                    mb = span_nbytes(marker)
                    self._sizes[trace_id] += mb
                    self._bytes += mb
            spans.append(span)
            self._sizes[trace_id] += nb
            self._bytes += nb
            while ((len(self._traces) > self.max_traces
                    or self._bytes > self.max_bytes)
                   and len(self._traces) > 1):
                # never evict the trace being written (it would make the
                # store lose the span it was just handed); the >1 guard
                # means a single over-budget trace is kept whole
                oldest = next(iter(self._traces))
                if oldest == trace_id:
                    oldest = next(i for i in self._traces if i != trace_id)
                self._traces.pop(oldest)
                self._bytes -= self._sizes.pop(oldest)
                self._tombstones[oldest] = None
                evicted += 1
            while len(self._tombstones) > self._TOMBSTONE_CAP:
                self._tombstones.pop(next(iter(self._tombstones)))
            self._evicted += evicted
        if evicted and self.on_evict is not None:
            self.on_evict(evicted)

    def get(self, trace_id: str) -> list:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def stats(self) -> dict:
        with self._lock:
            return {"traces": len(self._traces), "bytes": self._bytes,
                    "evicted": self._evicted}


def build_tree(spans: list) -> list:
    """Nest a flat span list into the hop tree: each node is the span dict
    plus a ``children`` list, ordered by start time where present.  Spans
    whose parent is absent (the root, or a parent evicted/unreachable)
    surface at the top level — a partial trace still renders."""
    by_id = {}
    for s in spans:
        node = dict(s)
        node["children"] = []
        sid = node.get("span_id")
        if sid is not None:
            by_id[sid] = node
        else:  # pragma: no cover - defensive: keep malformed spans visible
            by_id[id(node)] = node
    roots = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def order(nodes):
        nodes.sort(key=lambda n: (n.get("t_start_s") or 0.0,
                                  str(n.get("span_id"))))
        for n in nodes:
            order(n["children"])
    order(roots)
    return roots
