"""Local-process kubelet: runs Pods as real OS subprocesses.

Upstream analogue (UNVERIFIED): the kubelet + container runtime.  This is the
piece that lets the rebuild go *further* than upstream CI (SURVEY.md §4): pods
are actual processes, so a TPUJob reconcile path ends in a genuine
multi-process ``jax.distributed`` rendezvous on localhost rather than a fake.

Supported Pod surface: ``spec.initContainers`` (sequential), the first entry of
``spec.containers``, ``env``/``command``/``args``/``workingDir``,
``restartPolicy`` (Always | OnFailure | Never), deletion → SIGTERM/SIGKILL,
ConfigMap volumes (rendered as files under a per-pod root, with the k8s
``$(VAR)`` dependent-env expansion so specs can reference the mount root),
and ``POD_VOLUME_ROOT`` exported to the process.
"""

from __future__ import annotations

import os
import re
import signal
import subprocess
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

from .api import APIServer, NotFound, Obj

_ENV_REF = re.compile(r"\$\(([A-Za-z_][A-Za-z0-9_]*)\)")


@dataclass
class _PodRun:
    namespace: str
    name: str
    uid: str
    init_remaining: list[dict] = field(default_factory=list)
    current: Optional[subprocess.Popen] = None
    in_init: bool = False
    main_container: Optional[dict] = None
    # containers[1:] run as sidecars: spawned with the main container, killed
    # (stop file + SIGTERM, so they can flush) when the main terminates — the
    # k8s semantics Katib's injected metrics collector relies on
    sidecar_containers: list[dict] = field(default_factory=list)
    sidecars: list[subprocess.Popen] = field(default_factory=list)
    # main exited; waiting (non-blocking, across sync ticks) for sidecars to
    # flush before the pod goes terminal
    draining: bool = False
    drain_rc: int = 0
    drain_sigterm_at: float = 0.0
    drain_deadline: float = 0.0
    log_path: str = ""
    # uid-scoped stop-file path: the log path is NAME-scoped (user-visible,
    # stable across recreates) but the stop signal must die with the run — a
    # reaped old incarnation's _stop_sidecars would otherwise re-create a
    # name-scoped stop file AFTER the recreated pod started, and the new
    # pod's sidecars would flush-and-exit at startup
    stop_path: str = ""
    restart_count: int = 0
    next_restart_at: float = 0.0
    terminating: bool = False
    kill_at: float = 0.0
    volume_root: str = ""


class LocalProcessKubelet:
    def __init__(
        self,
        api: APIServer,
        node_name: str = "local-0",
        workdir: Optional[str] = None,
        base_env: Optional[dict] = None,
    ):
        self.api = api
        self.node_name = node_name
        self.workdir = workdir or tempfile.mkdtemp(prefix="kubelet-")
        self.logdir = os.path.join(self.workdir, "logs")
        os.makedirs(self.logdir, exist_ok=True)
        self.base_env = dict(base_env or {})
        self._runs: dict[str, _PodRun] = {}  # by uid
        if api.try_get("Node", node_name) is None:
            api.create(
                {
                    "apiVersion": "v1",
                    "kind": "Node",
                    "metadata": {"name": node_name, "labels": {"kubernetes.io/hostname": node_name}},
                    "status": {"phase": "Ready"},
                }
            )

    # ------------------------------------------------------------------ sync

    def sync(self) -> bool:
        """One kubelet sync pass; returns True if any pod state changed."""
        changed = False
        pods = self.api.list("Pod", field_selector=lambda p: p.get("spec", {}).get("nodeName") == self.node_name)
        live_uids = set()
        for pod in pods:
            live_uids.add(pod["metadata"]["uid"])
            if self._sync_pod(pod):
                changed = True
        # pods we were running that no longer exist in the store → kill
        for uid, run in list(self._runs.items()):
            if uid not in live_uids:
                self._terminate(run, grace=0.5)
                if run.current is None:
                    del self._runs[uid]
                changed = True
        return changed

    def _sync_pod(self, pod: Obj) -> bool:
        uid = pod["metadata"]["uid"]
        phase = pod.get("status", {}).get("phase", "Pending")
        run = self._runs.get(uid)
        if run is None:
            if phase in ("Succeeded", "Failed"):
                return False
            run = self._start(pod)
            return True
        return self._poll(pod, run)

    # ----------------------------------------------------------------- start

    def _start(self, pod: Obj) -> _PodRun:
        meta = pod["metadata"]
        spec = pod["spec"]
        run = _PodRun(
            namespace=meta.get("namespace", "default"),
            name=meta["name"],
            uid=meta["uid"],
            init_remaining=list(spec.get("initContainers", [])),
            main_container=spec["containers"][0],
            sidecar_containers=list(spec["containers"][1:]),
        )
        run.log_path = os.path.join(self.logdir, f"{run.namespace}_{run.name}.log")
        run.stop_path = run.log_path + f".{run.uid}.stop"
        # stale stop files are uid-scoped litter from reaped runs; the LOG is
        # truncated only for SIDECAR-bearing pods — a freshly injected
        # metrics collector starts at offset 0 and would re-push the previous
        # incarnation's objective values into the new trial.  Sidecar-less
        # pods keep the name-scoped accumulate behavior: gang-restarted
        # TPUJob workers append across incarnations, which is how the
        # resume-continuity tests (and operators reading logs) observe that
        # a restart actually resumed from the checkpoint.
        import glob as _glob
        # only unlink stop files of runs that are GONE from self._runs: a
        # previous same-named incarnation still draining needs its stop file
        # for the race-free sidecar stop signal (else its sidecars only exit
        # via the SIGTERM/kill escalation)
        stale = []
        for path in _glob.glob(run.log_path + ".*.stop"):
            uid = path[len(run.log_path) + 1:-len(".stop")]
            if uid not in self._runs:
                stale.append(path)
        if run.sidecar_containers:
            stale.append(run.log_path)
        for path in stale:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._runs[meta["uid"]] = run
        try:
            self._render_volumes(pod, run)
            self._advance(run)
        except (ValueError, OSError) as e:
            self._set_status(
                run,
                {
                    "phase": "Failed",
                    "reason": "StartError",
                    "message": str(e),
                    "containerStatuses": [
                        {
                            "name": run.main_container.get("name", "main"),
                            "state": {"terminated": {"exitCode": 128, "reason": "StartError"}},
                        }
                    ],
                },
            )
            self._runs.pop(meta["uid"], None)
            return run
        self._set_status(
            run,
            {
                "phase": "Running",
                "startTime": time.time(),
                "podIP": "127.0.0.1",
                "hostIP": "127.0.0.1",
            },
        )
        return run

    def _render_volumes(self, pod: Obj, run: _PodRun) -> None:
        """Materialize ConfigMap volumes as files under a per-pod root.

        Containers are plain processes here, so absolute ``mountPath``s are
        re-rooted at ``<workdir>/pods/<uid>``; the process finds them via the
        exported ``POD_VOLUME_ROOT`` (specs reference it with the k8s
        ``$(VAR)`` dependent-env syntax, expanded in ``_spawn``).
        """
        spec = pod["spec"]
        volumes = {v["name"]: v for v in spec.get("volumes", []) if "configMap" in v}
        if not volumes:
            return
        run.volume_root = os.path.join(self.workdir, "pods", run.uid)
        for container in list(spec.get("initContainers", [])) + spec["containers"]:
            for mount in container.get("volumeMounts", []):
                vol = volumes.get(mount["name"])
                if vol is None:
                    continue
                cm = self.api.try_get("ConfigMap", vol["configMap"]["name"], run.namespace)
                if cm is None:
                    raise ValueError(
                        f"pod {run.name}: ConfigMap {vol['configMap']['name']!r} not found")
                target = run.volume_root + os.path.abspath(mount["mountPath"])
                os.makedirs(target, exist_ok=True)
                for key, content in (cm.get("data") or {}).items():
                    with open(os.path.join(target, key), "w") as f:
                        f.write(content)

    def _spawn(self, run: _PodRun, container: dict,
               log_suffix: str = "") -> subprocess.Popen:
        cmd = list(container.get("command", [])) + list(container.get("args", []))
        if not cmd:
            raise ValueError(f"pod {run.name}: container has no command (images are not pullable here)")
        env = dict(os.environ)
        env.update(self.base_env)
        if run.volume_root:
            env["POD_VOLUME_ROOT"] = run.volume_root
        # sidecars (e.g. the Katib metrics collector) tail the main
        # container's log through this; their own output goes to a
        # per-container file so it cannot pollute the parsed stream.
        # POD_STOP_FILE appears when the pod is shutting down — the
        # race-free companion to the SIGTERM sidecars also receive.
        env["POD_LOG_PATH"] = run.log_path
        env["POD_STOP_FILE"] = run.stop_path
        # k8s dependent-env semantics: $(VAR) in a value resolves against the
        # base env plus PREVIOUSLY-declared container vars only — forward
        # references stay verbatim, exactly like a real kubelet
        for e in container.get("env", []):
            if "value" not in e:  # valueFrom (fieldRef/secretKeyRef) not resolvable here
                continue
            value = str(e["value"])
            if "$(" in value:
                value = _ENV_REF.sub(lambda m: env.get(m.group(1), m.group(0)), value)
            env[e["name"]] = value
        env.setdefault("POD_NAME", run.name)
        env.setdefault("POD_NAMESPACE", run.namespace)
        log_path = (run.log_path if not log_suffix
                    else f"{run.log_path}.{log_suffix}")
        log = open(log_path, "ab")
        return subprocess.Popen(
            cmd,
            env=env,
            cwd=container.get("workingDir") or self.workdir,
            stdout=log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )

    def _advance(self, run: _PodRun) -> None:
        """Start the next container (init chain, then main + sidecars)."""
        if run.init_remaining:
            run.in_init = True
            run.current = self._spawn(run, run.init_remaining.pop(0))
        else:
            run.in_init = False
            run.current = self._spawn(run, run.main_container)
            if run.sidecar_containers and not run.sidecars:
                # sidecars start alongside the FIRST main start and survive
                # main crash-restarts (upstream pod semantics)
                try:
                    for c in run.sidecar_containers:
                        run.sidecars.append(
                            self._spawn(run, c, log_suffix=c.get("name", "sidecar")))
                except (ValueError, OSError):
                    # a bad sidecar spec must not leak the already-started
                    # main process (or earlier sidecars): the StartError
                    # handlers up-stack only mark the pod Failed
                    try:
                        os.killpg(run.current.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    run.current = None
                    self._stop_sidecars(run, grace=0.0)
                    raise

    def _stop_sidecars(self, run: _PodRun, grace: float) -> None:
        """Stop sidecars so they can flush, then the pod may go terminal.

        Shutdown signal ordering matters: SIGTERM delivered while a sidecar
        interpreter is still starting up (main exited fast) kills it before
        any handler is installed — flushing nothing.  So the stop FILE at
        ``POD_STOP_FILE`` is the primary signal (a polling sidecar of any
        age sees it); SIGTERM goes out only halfway into the grace window,
        by which point a live sidecar has long installed its handler; at
        the deadline stragglers are SIGKILLed."""
        if not run.sidecars:
            return
        try:
            with open(run.stop_path, "w"):
                pass
        except OSError:
            pass
        deadline = time.monotonic() + grace
        sigterm_at = time.monotonic() + grace / 2
        sigtermed = grace <= 0
        if sigtermed:
            self._signal_sidecars(run, signal.SIGTERM)
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in run.sidecars):
                break
            if not sigtermed and time.monotonic() >= sigterm_at:
                self._signal_sidecars(run, signal.SIGTERM)
                sigtermed = True
            time.sleep(0.02)
        for p in run.sidecars:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        run.sidecars.clear()

    def _signal_sidecars(self, run: _PodRun, sig: int) -> None:
        for p in run.sidecars:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, sig)
                except ProcessLookupError:
                    pass

    # ------------------------------------------------------------------ poll

    def _poll(self, pod: Obj, run: _PodRun) -> bool:
        if run.terminating:
            if run.current is not None and run.current.poll() is None:
                if time.monotonic() >= run.kill_at:
                    try:
                        os.killpg(run.current.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                return False
            run.current = None
            self._stop_sidecars(run, grace=0.5)
            self._runs.pop(run.uid, None)
            return True

        if run.draining:
            return self._poll_drain(pod, run)

        if run.current is None:
            # waiting out a crash-restart backoff
            if time.monotonic() >= run.next_restart_at:
                try:
                    self._advance(run)
                except (ValueError, OSError) as e:
                    self._set_status(run, {"phase": "Failed", "reason": "StartError", "message": str(e)})
                    self._runs.pop(run.uid, None)
                return True
            return False

        rc = run.current.poll()
        if rc is None:
            return False

        if run.in_init:
            if rc == 0:
                try:
                    self._advance(run)
                except (ValueError, OSError) as e:
                    self._set_status(run, {"phase": "Failed", "reason": "StartError", "message": str(e)})
                    self._runs.pop(run.uid, None)
                return True
            self._set_status(run, self._terminated_status(pod, "Failed", rc, init=True))
            run.current = None
            self._runs.pop(run.uid, None)
            return True

        restart = pod["spec"].get("restartPolicy", "Always")
        if restart == "Always" or (restart == "OnFailure" and rc != 0):
            run.restart_count += 1
            run.current = None
            run.next_restart_at = time.monotonic() + min(0.2 * run.restart_count, 2.0)
            self._set_status(
                run,
                {
                    "phase": "Running",
                    "containerStatuses": [
                        {
                            "name": run.main_container.get("name", "main"),
                            "restartCount": run.restart_count,
                            "lastState": {"terminated": {"exitCode": rc, "finishedAt": time.time()}},
                            "state": {"waiting": {"reason": "CrashLoopBackOff" if rc else "Restarting"}},
                        }
                    ],
                },
            )
            return True

        # sidecars flush BEFORE the pod goes terminal: a watcher that sees
        # Succeeded can rely on sidecar-pushed state (metrics) being
        # complete.  The wait is NON-blocking — draining is polled across
        # sync ticks so a slow sidecar never stalls the whole manager.
        run.current = None
        if run.sidecars:
            self._begin_drain(run, rc)
            return self._poll_drain(pod, run)
        self._set_status(run, self._terminated_status(pod, "Succeeded" if rc == 0 else "Failed", rc))
        self._runs.pop(run.uid, None)
        return True

    _DRAIN_GRACE = 8.0

    def _begin_drain(self, run: _PodRun, rc: int) -> None:
        run.draining = True
        run.drain_rc = rc
        now = time.monotonic()
        run.drain_sigterm_at = now + self._DRAIN_GRACE / 2
        run.drain_deadline = now + self._DRAIN_GRACE
        try:
            with open(run.stop_path, "w"):
                pass
        except OSError:
            pass

    def _poll_drain(self, pod: Obj, run: _PodRun) -> bool:
        now = time.monotonic()
        alive = [p for p in run.sidecars if p.poll() is None]
        if alive:
            # stop-file first; SIGTERM only mid-grace (a sidecar signalled
            # during interpreter startup dies handler-less, flushing nothing)
            if now >= run.drain_deadline:
                self._signal_sidecars(run, signal.SIGKILL)
            elif now >= run.drain_sigterm_at:
                self._signal_sidecars(run, signal.SIGTERM)
            if now < run.drain_deadline:
                return False
        run.sidecars.clear()
        run.draining = False
        rc = run.drain_rc
        self._set_status(run, self._terminated_status(pod, "Succeeded" if rc == 0 else "Failed", rc))
        self._runs.pop(run.uid, None)
        return True

    def _terminated_status(self, pod: Obj, phase: str, rc: int, init: bool = False) -> dict:
        run = self._runs[pod["metadata"]["uid"]]
        return {
            "phase": phase,
            "startTime": pod.get("status", {}).get("startTime"),
            "containerStatuses": [
                {
                    "name": ("init" if init else run.main_container.get("name", "main")),
                    "restartCount": run.restart_count,
                    "state": {"terminated": {"exitCode": rc, "finishedAt": time.time()}},
                }
            ],
        }

    # ------------------------------------------------------------- lifecycle

    def _terminate(self, run: _PodRun, grace: float) -> None:
        if run.current is not None and run.current.poll() is None:
            run.terminating = True
            run.kill_at = time.monotonic() + grace
            try:
                os.killpg(run.current.pid, signal.SIGTERM)
            except ProcessLookupError:
                run.current = None
        else:
            run.current = None
        if run.current is None:
            self._stop_sidecars(run, grace=min(grace, 0.5))

    def _set_status(self, run: _PodRun, status: dict) -> None:
        try:
            pod = self.api.get("Pod", run.name, run.namespace)
        except NotFound:
            return
        merged = dict(pod.get("status", {}))
        merged.update(status)
        pod["status"] = merged
        self.api.update_status(pod)

    def logs(self, name: str, namespace: str = "default") -> str:
        path = os.path.join(self.logdir, f"{namespace}_{name}.log")
        if not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def shutdown(self) -> None:
        for run in list(self._runs.values()):
            self._terminate(run, grace=0.0)
            if run.current is not None:
                try:
                    run.current.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(run.current.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            self._stop_sidecars(run, grace=0.2)
        self._runs.clear()
