"""The in-process "cluster": apiserver + scheduler + kubelets + controllers.

Upstream analogue (UNVERIFIED): a kind/envtest cluster with the full operator
set installed (SURVEY.md §4).  ``Cluster`` is the single entry point tests and
the CLI use: ``apply()`` a spec, ``wait_for()`` a condition, read logs.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Optional

from .api import APIServer, Obj
from .controller import Manager
from .kubelet import LocalProcessKubelet
from ..scheduler import topology as topo
from ..scheduler.topology import TopologyScheduler, make_cpu_node, make_tpu_slice


class Cluster:
    def __init__(
        self,
        workdir: Optional[str] = None,
        cpu_nodes: int = 1,
        tpu_slices: tuple[tuple[str, str, str], ...] = (),  # (name, accelerator, topology)
        base_env: Optional[dict] = None,
    ):
        self.api = APIServer()
        topo.register(self.api)
        self.workdir = workdir or tempfile.mkdtemp(prefix="kfcluster-")
        os.makedirs(self.workdir, exist_ok=True)
        self.manager = Manager(self.api)
        self.scheduler = TopologyScheduler(self.api)
        self.manager.add_ticker(self.scheduler.sync)
        self.kubelets: dict[str, LocalProcessKubelet] = {}
        for i in range(cpu_nodes):
            self.add_node(make_cpu_node(self.api, f"cpu-{i}"), base_env)
        for name, acc, shape in tpu_slices:
            for node in make_tpu_slice(self.api, name, acc, shape):
                self.add_node(node, base_env)

    def add_node(self, name: str, base_env: Optional[dict] = None) -> None:
        kubelet = LocalProcessKubelet(
            self.api, node_name=name, workdir=os.path.join(self.workdir, name), base_env=base_env
        )
        self.kubelets[name] = kubelet
        self.manager.add_ticker(kubelet.sync)

    # -------------------------------------------------------------- user API

    def apply(self, obj: Obj) -> Obj:
        """Create-or-update, like ``kubectl apply``."""
        existing = self.api.try_get(
            obj["kind"], obj["metadata"]["name"], obj.get("metadata", {}).get("namespace", "default")
        )
        if existing is None:
            return self.api.create(obj)
        merged = dict(existing)
        merged["spec"] = obj.get("spec", merged.get("spec"))
        return self.api.update(merged)

    def wait_for(self, predicate: Callable[[], bool], timeout: float = 120.0) -> bool:
        return self.manager.run_until(predicate, timeout=timeout)

    def settle(self, quiet: float = 0.2, timeout: float = 30.0) -> None:
        self.manager.settle(quiet=quiet, timeout=timeout)

    def logs(self, pod_name: str, namespace: str = "default") -> str:
        for kubelet in self.kubelets.values():
            out = kubelet.logs(pod_name, namespace)
            if out:
                return out
        return ""

    def shutdown(self) -> None:
        for kubelet in self.kubelets.values():
            kubelet.shutdown()
        self.api.run_teardowns()
