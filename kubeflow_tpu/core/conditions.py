"""Status conditions — the platform's user-facing state machine.

Upstream analogue (UNVERIFIED): ``JobCondition`` handling in
training-operator's common controller and the metav1.Condition conventions
used across Kubeflow controllers (SURVEY.md §5 "conditions+events model").
"""

from __future__ import annotations

import time
from typing import Optional


def get_condition(status: dict, ctype: str) -> Optional[dict]:
    for c in status.get("conditions", []):
        if c["type"] == ctype:
            return c
    return None


def has_condition(status: dict, ctype: str, value: str = "True") -> bool:
    c = get_condition(status, ctype)
    return c is not None and c["status"] == value


def set_condition(
    status: dict,
    ctype: str,
    value: str,
    reason: str = "",
    message: str = "",
) -> bool:
    """Upsert a condition. Returns True if anything changed.

    Mirrors upstream semantics: lastTransitionTime only moves when the
    condition's status flips, and setting a terminal/active condition is the
    caller's policy (see training.common for the Job condition rules).
    """
    conditions = status.setdefault("conditions", [])
    now = time.time()
    for c in conditions:
        if c["type"] == ctype:
            changed = c["status"] != value or c.get("reason") != reason or c.get("message") != message
            if c["status"] != value:
                c["lastTransitionTime"] = now
            c["status"] = value
            c["reason"] = reason
            c["message"] = message
            c["lastUpdateTime"] = now
            return changed
    conditions.append(
        {
            "type": ctype,
            "status": value,
            "reason": reason,
            "message": message,
            "lastUpdateTime": now,
            "lastTransitionTime": now,
        }
    )
    return True
