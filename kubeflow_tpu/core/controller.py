"""Deterministic reconcile machinery.

Upstream analogue (UNVERIFIED): controller-runtime's manager/controller/
workqueue.  The crucial design departure (SURVEY.md §4 "implication for the
rebuild"): instead of N goroutines and eventual consistency, a *single-threaded*
manager pumps all watch streams and drains a deduplicating workqueue, so tests
drive the full reconcile path deterministically.  Real concurrency lives only
in pod subprocesses (see kubelet.py) — the same place the real cluster has it.
"""

from __future__ import annotations

import heapq
import time
import traceback
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from .api import APIServer, Obj, Watcher
from .metrics import RECONCILE_ERRORS, RECONCILE_TOTAL


@dataclass(frozen=True)
class Request:
    name: str
    namespace: str = "default"


@dataclass
class Result:
    requeue_after: Optional[float] = None


class Reconciler(Protocol):
    #: primary kind this reconciler owns
    kind: str

    def reconcile(self, req: Request) -> Optional[Result]: ...


class Controller:
    """Watches a primary kind plus owned kinds, maps events to Requests."""

    def __init__(
        self,
        api: APIServer,
        reconciler: Reconciler,
        owns: tuple[str, ...] = (),
        watches: tuple[tuple[str, Callable[[Obj], Optional[Request]]], ...] = (),
    ):
        self.api = api
        self.reconciler = reconciler
        self.kind = reconciler.kind
        self._primary: Watcher = api.watch(self.kind, send_initial=True)
        self._owned: list[tuple[Watcher, str]] = [
            (api.watch(kind, send_initial=True), kind) for kind in owns
        ]
        self._mapped: list[tuple[Watcher, Callable[[Obj], Optional[Request]]]] = [
            (api.watch(kind, send_initial=True), fn) for kind, fn in watches
        ]
        self._queue: list[Request] = []
        self._queued: set[Request] = set()
        self._delayed: list[tuple[float, int, Request]] = []  # heap
        self._seq = 0
        self.errors: list[tuple[Request, BaseException]] = []

    # ------------------------------------------------------------------ queue

    def _enqueue(self, req: Request) -> None:
        if req not in self._queued:
            self._queued.add(req)
            self._queue.append(req)

    def _enqueue_after(self, req: Request, delay: float) -> None:
        self._seq += 1
        heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, req))

    def _owner_request(self, obj: Obj) -> Optional[Request]:
        for ref in obj["metadata"].get("ownerReferences", []):
            if ref.get("controller") and ref.get("kind") == self.kind:
                return Request(ref["name"], obj["metadata"].get("namespace", "default"))
        return None

    def pump(self) -> int:
        """Drain watch streams into the workqueue. Returns #events consumed."""
        n = 0
        while (ev := self._primary.poll()) is not None:
            m = ev.object["metadata"]
            self._enqueue(Request(m["name"], m.get("namespace", "default")))
            n += 1
        for w, _kind in self._owned:
            while (ev := w.poll()) is not None:
                req = self._owner_request(ev.object)
                if req is not None:
                    self._enqueue(req)
                n += 1
        for w, fn in self._mapped:
            while (ev := w.poll()) is not None:
                req = fn(ev.object)
                if req is not None:
                    self._enqueue(req)
                n += 1
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, req = heapq.heappop(self._delayed)
            self._enqueue(req)
        return n

    def process(self, max_items: Optional[int] = None) -> int:
        """Reconcile queued requests. Returns #requests processed."""
        n = 0
        while self._queue and (max_items is None or n < max_items):
            req = self._queue.pop(0)
            self._queued.discard(req)
            try:
                result = self.reconciler.reconcile(req)
            except Exception as e:  # noqa: BLE001 — controller loop must survive
                self.errors.append((req, e))
                RECONCILE_TOTAL.inc(controller=self.kind, result="error")
                RECONCILE_ERRORS.inc(controller=self.kind)
                traceback.print_exc()
                self._enqueue_after(req, 0.2)
            else:
                RECONCILE_TOTAL.inc(
                    controller=self.kind,
                    result="requeue_after" if result and result.requeue_after else "success",
                )
                if result is not None and result.requeue_after is not None:
                    self._enqueue_after(req, result.requeue_after)
            n += 1
        return n

    @property
    def idle(self) -> bool:
        return not self._queue and self._primary._q.empty() and all(
            w._q.empty() for w, _ in self._owned
        ) and all(w._q.empty() for w, _ in self._mapped)

    def next_deadline(self) -> Optional[float]:
        return self._delayed[0][0] if self._delayed else None


class Manager:
    """Runs controllers + tickers (kubelet/scheduler sync fns) to quiescence."""

    def __init__(self, api: APIServer):
        self.api = api
        self.controllers: list[Controller] = []
        self.tickers: list[Callable[[], bool]] = []

    def add(
        self,
        reconciler: Reconciler,
        owns: tuple[str, ...] = (),
        watches: tuple[tuple[str, Callable[[Obj], Optional[Request]]], ...] = (),
    ) -> Controller:
        c = Controller(self.api, reconciler, owns=owns, watches=watches)
        self.controllers.append(c)
        return c

    def add_ticker(self, fn: Callable[[], bool]) -> None:
        """A ticker is a sync function returning True if it changed anything."""
        self.tickers.append(fn)

    def step(self) -> bool:
        """One scheduling round. Returns True if any work happened."""
        worked = False
        for t in self.tickers:
            if t():
                worked = True
        for c in self.controllers:
            if c.pump():
                worked = True
            if c.process():
                worked = True
        return worked

    def run_until(
        self,
        predicate: Callable[[], bool],
        timeout: float = 60.0,
        poll: float = 0.02,
    ) -> bool:
        """Drive the world until predicate() is true (or timeout). Returns
        whether the predicate was met."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            if not self.step():
                # nothing to do right now: honor the nearest delayed requeue,
                # else nap briefly to let pod subprocesses make progress.
                deadlines = [d for c in self.controllers if (d := c.next_deadline())]
                if deadlines:
                    time.sleep(max(0.0, min(min(deadlines) - time.monotonic(), poll * 5)))
                else:
                    time.sleep(poll)
        return predicate()

    def settle(self, quiet: float = 0.2, timeout: float = 30.0) -> None:
        """Run until nothing has happened for `quiet` seconds."""
        deadline = time.monotonic() + timeout
        last_work = time.monotonic()
        while time.monotonic() < deadline:
            if self.step():
                last_work = time.monotonic()
            elif time.monotonic() - last_work > quiet:
                return
            else:
                time.sleep(0.01)

    def raise_errors(self) -> None:
        errs = [e for c in self.controllers for e in c.errors]
        if errs:
            req, e = errs[0]
            raise RuntimeError(f"{len(errs)} reconcile error(s); first at {req}: {e}") from e
