"""PipelineService: the KFP API-server equivalent.

Upstream analogue (UNVERIFIED, SURVEY.md §2/§3.5): the KFP API server keeps
pipelines / experiments / runs in MySQL, submits Argo Workflows, and a
separate persistence agent reports Workflow state back via ReportWorkflow.
Here the records persist in the native metadata store (contexts — the
"MySQL is native, SQLite-equiv acceptable" rule of SURVEY §2b), runs are
Workflow CRs, ``report_workflow`` is the ReportWorkflow RPC stand-in, and
the watch-driven agent lives in pipelines/persistence.py.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Optional, Union

from ..core.api import APIServer, Obj
from . import api as papi
from . import metadata as md
from .artifacts import ObjectStore
from .compiler import Compiler
from .dsl import Pipeline

PIPELINE_CTX = "kfp.pipeline"
EXPERIMENT_CTX = "kfp.experiment"
RUN_CTX = "kfp.run"


class PipelineService:
    def __init__(self, api: APIServer, metadata_store: md.MetadataStore, store: ObjectStore):
        self.api = api
        self.metadata = metadata_store
        self.store = store

    # -------------------------------------------------------------- pipelines

    def upload_pipeline(
        self, pipeline: Union[Pipeline, dict], name: Optional[str] = None, description: str = ""
    ) -> str:
        """Register a pipeline (compiled on upload if given as a dsl.Pipeline)."""
        ir = Compiler().compile(pipeline) if isinstance(pipeline, Pipeline) else pipeline
        pname = name or ir["pipelineInfo"]["name"]
        existing = self.metadata.get_context_by_name(PIPELINE_CTX, pname)
        versions = existing.properties.get("versions", []) if existing else []
        uri = self.store.uri("mlpipeline", f"pipelines/{pname}/v{len(versions) + 1}.json")
        self.store.put_bytes(uri, json.dumps(ir, sort_keys=True).encode())
        versions.append({"version": len(versions) + 1, "uri": uri, "createdAt": time.time()})
        self.metadata.put_context(
            PIPELINE_CTX,
            pname,
            {"description": description or ir["pipelineInfo"].get("description", ""), "versions": versions},
        )
        return pname

    def get_pipeline(self, name: str, version: Optional[int] = None) -> dict:
        ctx = self.metadata.get_context_by_name(PIPELINE_CTX, name)
        if ctx is None:
            raise KeyError(f"pipeline {name!r} not found")
        versions = ctx.properties["versions"]
        if version is None:
            v = versions[-1]
        else:
            if not 1 <= version <= len(versions):
                raise KeyError(
                    f"pipeline {name!r} has versions 1..{len(versions)}, not {version}"
                )
            v = versions[version - 1]
        return json.loads(self.store.get_bytes(v["uri"]).decode())

    def list_pipelines(self) -> list[str]:
        return sorted(c.name for c in self._contexts(PIPELINE_CTX))

    def _contexts(self, ctx_type: str) -> list:
        # "__registry__" was internal bookkeeping in stores written before the
        # contexts_by_type index existed; never surface it as a record
        return [c for c in self.metadata.contexts_by_type(ctx_type) if c.name != "__registry__"]

    # ------------------------------------------------------------ experiments

    def create_experiment(self, name: str, description: str = "") -> str:
        self.metadata.put_context(EXPERIMENT_CTX, name, {"description": description, "createdAt": time.time()})
        return name

    def list_experiments(self) -> list[str]:
        return sorted(c.name for c in self._contexts(EXPERIMENT_CTX))

    # ------------------------------------------------------------------- runs

    def create_run(
        self,
        pipeline: Union[Pipeline, dict, str],
        arguments: Optional[dict] = None,
        run_name: Optional[str] = None,
        experiment: Optional[str] = None,
        namespace: str = "default",
    ) -> str:
        if isinstance(pipeline, str):
            ir = self.get_pipeline(pipeline)
        elif isinstance(pipeline, Pipeline):
            ir = Compiler().compile(pipeline)
        else:
            ir = pipeline
        run_id = run_name or f"run-{uuid.uuid4().hex[:8]}"
        wf = papi.workflow(run_id, ir, arguments=arguments, namespace=namespace, labels={papi.LABEL_RUN: run_id})
        self.api.create(wf)
        self.metadata.put_context(
            RUN_CTX,
            run_id,
            {
                "pipeline": ir["pipelineInfo"]["name"],
                "experiment": experiment or "Default",
                "namespace": namespace,
                "arguments": arguments or {},
                "createdAt": time.time(),
                "phase": papi.PENDING,
            },
        )
        return run_id

    def get_run(self, run_id: str) -> dict:
        ctx = self.metadata.get_context_by_name(RUN_CTX, run_id)
        if ctx is None:
            raise KeyError(f"run {run_id!r} not found")
        rec = dict(ctx.properties)
        wf = self.api.try_get("Workflow", run_id, rec.get("namespace", "default"))
        if wf is not None:
            rec["phase"] = wf.get("status", {}).get("phase", papi.PENDING)
            rec["nodes"] = wf.get("status", {}).get("nodes", {})
        return rec

    def list_runs(self, experiment: Optional[str] = None) -> list[dict]:
        out = []
        for c in self._contexts(RUN_CTX):
            if experiment and c.properties.get("experiment") != experiment:
                continue
            out.append({"run": c.name, **c.properties})
        return sorted(out, key=lambda r: r.get("createdAt", 0))

    # --------------------------------------------------- ReportWorkflow RPC

    def report_workflow(self, wf: dict) -> bool:
        """Fold one Workflow's state into its run record — the stand-in for
        upstream's ReportWorkflow RPC, called by the persistence agent
        (pipelines/persistence.py) on every watched Workflow change."""
        run_id = wf.get("metadata", {}).get("name")
        ctx = self.metadata.get_context_by_name(RUN_CTX, run_id)
        if ctx is None:
            return False  # a Workflow not created through create_run
        props = dict(ctx.properties)
        if props.get("phase") in papi.WORKFLOW_TERMINAL:
            return False
        phase = wf.get("status", {}).get("phase")
        if not phase or phase == props.get("phase"):
            return False
        props["phase"] = phase
        if phase in papi.WORKFLOW_TERMINAL:
            props["finishedAt"] = wf["status"].get("finishedAt")
        self.metadata.put_context(RUN_CTX, run_id, props)
        return True
