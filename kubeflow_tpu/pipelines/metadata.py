"""Python client for the C++ metadata store core (metadata_core.cc).

Upstream analogue (UNVERIFIED, SURVEY.md §2b/§3.5): the ``ml-metadata`` client
API the KFP v2 driver uses — artifacts, executions, contexts, events,
associations/attributions, plus the cache lookup by execution fingerprint
(`[U:pipelines/backend/src/v2/cacheutils]`).  The native core owns storage,
indexes and WAL durability; this client owns JSON property encoding and the
query/read-buffer pairing (serialized under one lock).
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..utils.native_build import load_native

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "metadata_core.cc")
_LIB = None
_BIND_LOCK = threading.Lock()

# execution states (MLMD-equivalent lifecycle)
NEW, RUNNING, COMPLETE, FAILED, CACHED = 0, 1, 2, 3, 4
STATE_NAMES = {NEW: "NEW", RUNNING: "RUNNING", COMPLETE: "COMPLETE", FAILED: "FAILED", CACHED: "CACHED"}
# artifact states
PENDING, LIVE = 0, 1
# event types
INPUT, OUTPUT = 0, 1


def _load() -> ctypes.CDLL:
    global _LIB
    with _BIND_LOCK:
        if _LIB is None:
            lib = load_native(_SRC, "metadata")
            i32, i64, p, c = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p, ctypes.c_char_p
            lib.mds_open.restype = p
            lib.mds_open.argtypes = [c]
            lib.mds_close.argtypes = [p]
            lib.mds_put_artifact.restype = i64
            lib.mds_put_artifact.argtypes = [p, i64, c, c, i32, c, i32]
            lib.mds_put_execution.restype = i64
            lib.mds_put_execution.argtypes = [p, i64, c, i32, c, c, i32]
            lib.mds_put_context.restype = i64
            lib.mds_put_context.argtypes = [p, c, c, c, i32]
            lib.mds_put_event.restype = i32
            lib.mds_put_event.argtypes = [p, i64, i64, i32, c]
            lib.mds_put_association.restype = i32
            lib.mds_put_association.argtypes = [p, i64, i64]
            lib.mds_put_attribution.restype = i32
            lib.mds_put_attribution.argtypes = [p, i64, i64]
            for fn in ("mds_get_artifact", "mds_get_execution", "mds_get_context"):
                getattr(lib, fn).restype = i64
                getattr(lib, fn).argtypes = [p, i64]
            lib.mds_context_id_by_name.restype = i64
            lib.mds_context_id_by_name.argtypes = [p, c, c]
            for fn in (
                "mds_artifacts_by_type",
                "mds_executions_by_type",
                "mds_executions_by_fingerprint",
                "mds_contexts_by_type",
            ):
                getattr(lib, fn).restype = i64
                getattr(lib, fn).argtypes = [p, c]
            for fn in (
                "mds_executions_by_context",
                "mds_artifacts_by_context",
                "mds_events_by_execution",
                "mds_events_by_artifact",
            ):
                getattr(lib, fn).restype = i64
                getattr(lib, fn).argtypes = [p, i64]
            lib.mds_read_buffer.restype = i64
            lib.mds_read_buffer.argtypes = [p, ctypes.c_char_p, i64]
            lib.mds_count.restype = i64
            lib.mds_count.argtypes = [p, i32]
            _LIB = lib
    return _LIB


@dataclass
class ArtifactRecord:
    id: int
    type: str
    uri: str
    state: int
    properties: dict = field(default_factory=dict)


@dataclass
class ExecutionRecord:
    id: int
    type: str
    state: int
    fingerprint: str = ""
    properties: dict = field(default_factory=dict)


@dataclass
class ContextRecord:
    id: int
    type: str
    name: str
    properties: dict = field(default_factory=dict)


@dataclass
class EventRecord:
    execution_id: int
    artifact_id: int
    type: int  # INPUT | OUTPUT
    path: str  # input/output key name


def _lp(buf: bytes, off: int) -> tuple[bytes, int]:
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    return buf[off : off + n], off + n


class MetadataStore:
    """One handle on the native store; all methods are thread-safe."""

    def __init__(self, path: Optional[str] = None):
        self._lib = _load()
        self._h = self._lib.mds_open((path or "").encode())
        if not self._h:
            raise OSError(f"cannot open metadata store at {path!r}")
        self._lock = threading.Lock()  # pairs query + read_buffer atomically

    def close(self) -> None:
        if self._h:
            self._lib.mds_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - defensive
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------------ util

    def _read(self, n: int) -> bytes:
        if n <= 0:
            return b""
        buf = ctypes.create_string_buffer(n)
        got = self._lib.mds_read_buffer(self._h, buf, n)
        return buf.raw[:got]

    @staticmethod
    def _props(blob: bytes) -> dict:
        return json.loads(blob.decode()) if blob else {}

    # ------------------------------------------------------------------ puts

    def put_artifact(
        self,
        type: str,
        uri: str = "",
        state: int = LIVE,
        properties: Optional[dict] = None,
        artifact_id: int = -1,
    ) -> int:
        blob = json.dumps(properties or {}, sort_keys=True).encode()
        rid = self._lib.mds_put_artifact(self._h, artifact_id, type.encode(), uri.encode(), state, blob, len(blob))
        if rid < 0:
            raise KeyError(f"artifact id {artifact_id} not found")
        return rid

    def put_execution(
        self,
        type: str,
        state: int = RUNNING,
        fingerprint: str = "",
        properties: Optional[dict] = None,
        execution_id: int = -1,
    ) -> int:
        blob = json.dumps(properties or {}, sort_keys=True).encode()
        rid = self._lib.mds_put_execution(
            self._h, execution_id, type.encode(), state, fingerprint.encode(), blob, len(blob)
        )
        if rid < 0:
            raise KeyError(f"execution id {execution_id} not found")
        return rid

    def put_context(self, type: str, name: str, properties: Optional[dict] = None) -> int:
        """Create-or-update; (type, name) is the unique key."""
        blob = json.dumps(properties or {}, sort_keys=True).encode()
        return self._lib.mds_put_context(self._h, type.encode(), name.encode(), blob, len(blob))

    def put_event(self, execution_id: int, artifact_id: int, type: int, path: str = "") -> None:
        if self._lib.mds_put_event(self._h, execution_id, artifact_id, type, path.encode()) != 0:
            raise KeyError(f"event references unknown execution {execution_id} or artifact {artifact_id}")

    def put_association(self, context_id: int, execution_id: int) -> None:
        if self._lib.mds_put_association(self._h, context_id, execution_id) != 0:
            raise KeyError(f"association references unknown context {context_id} or execution {execution_id}")

    def put_attribution(self, context_id: int, artifact_id: int) -> None:
        if self._lib.mds_put_attribution(self._h, context_id, artifact_id) != 0:
            raise KeyError(f"attribution references unknown context {context_id} or artifact {artifact_id}")

    # ------------------------------------------------------------------ gets

    def get_artifact(self, artifact_id: int) -> ArtifactRecord:
        with self._lock:
            n = self._lib.mds_get_artifact(self._h, artifact_id)
            buf = self._read(n)
        if not buf:
            raise KeyError(f"artifact {artifact_id} not found")
        (aid, state) = struct.unpack_from("<qI", buf, 0)
        t, off = _lp(buf, 12)
        uri, off = _lp(buf, off)
        props, _ = _lp(buf, off)
        return ArtifactRecord(aid, t.decode(), uri.decode(), state, self._props(props))

    def get_execution(self, execution_id: int) -> ExecutionRecord:
        with self._lock:
            n = self._lib.mds_get_execution(self._h, execution_id)
            buf = self._read(n)
        if not buf:
            raise KeyError(f"execution {execution_id} not found")
        (eid, state) = struct.unpack_from("<qI", buf, 0)
        t, off = _lp(buf, 12)
        fp, off = _lp(buf, off)
        props, _ = _lp(buf, off)
        return ExecutionRecord(eid, t.decode(), state, fp.decode(), self._props(props))

    def get_context(self, context_id: int) -> ContextRecord:
        with self._lock:
            n = self._lib.mds_get_context(self._h, context_id)
            buf = self._read(n)
        if not buf:
            raise KeyError(f"context {context_id} not found")
        (cid, _pad) = struct.unpack_from("<qI", buf, 0)
        t, off = _lp(buf, 12)
        name, off = _lp(buf, off)
        props, _ = _lp(buf, off)
        return ContextRecord(cid, t.decode(), name.decode(), self._props(props))

    def get_context_by_name(self, type: str, name: str) -> Optional[ContextRecord]:
        cid = self._lib.mds_context_id_by_name(self._h, type.encode(), name.encode())
        return None if cid < 0 else self.get_context(cid)

    # ---------------------------------------------------------------- queries

    def _id_query(self, fn_name: str, arg) -> list[int]:
        with self._lock:
            n = getattr(self._lib, fn_name)(self._h, arg)
            buf = self._read(n)
        return list(struct.unpack(f"<{len(buf) // 8}q", buf))

    def artifacts_by_type(self, type: str) -> list[ArtifactRecord]:
        return [self.get_artifact(i) for i in self._id_query("mds_artifacts_by_type", type.encode())]

    def executions_by_type(self, type: str) -> list[ExecutionRecord]:
        return [self.get_execution(i) for i in self._id_query("mds_executions_by_type", type.encode())]

    def contexts_by_type(self, type: str) -> list[ContextRecord]:
        return [self.get_context(i) for i in self._id_query("mds_contexts_by_type", type.encode())]

    def executions_by_fingerprint(self, fingerprint: str) -> list[ExecutionRecord]:
        return [
            self.get_execution(i)
            for i in self._id_query("mds_executions_by_fingerprint", fingerprint.encode())
        ]

    def executions_by_context(self, context_id: int) -> list[ExecutionRecord]:
        return [self.get_execution(i) for i in self._id_query("mds_executions_by_context", context_id)]

    def artifacts_by_context(self, context_id: int) -> list[ArtifactRecord]:
        return [self.get_artifact(i) for i in self._id_query("mds_artifacts_by_context", context_id)]

    def _event_query(self, fn_name: str, arg) -> list[EventRecord]:
        with self._lock:
            n = getattr(self._lib, fn_name)(self._h, arg)
            buf = self._read(n)
        out, off = [], 0
        while off < len(buf):
            rec, off = _lp(buf, off)
            (eid, aid, etype) = struct.unpack_from("<qqI", rec, 0)
            path, _ = _lp(rec, 20)
            out.append(EventRecord(eid, aid, etype, path.decode()))
        return out

    def events_by_execution(self, execution_id: int) -> list[EventRecord]:
        return self._event_query("mds_events_by_execution", execution_id)

    def events_by_artifact(self, artifact_id: int) -> list[EventRecord]:
        return self._event_query("mds_events_by_artifact", artifact_id)

    def counts(self) -> dict:
        return {
            "artifacts": self._lib.mds_count(self._h, 0),
            "executions": self._lib.mds_count(self._h, 1),
            "contexts": self._lib.mds_count(self._h, 2),
            "events": self._lib.mds_count(self._h, 3),
        }

    # ------------------------------------------------- cache lookup (driver)

    def find_cached_execution(self, fingerprint: str) -> Optional[ExecutionRecord]:
        """Latest COMPLETE/CACHED execution with this fingerprint, if any."""
        hits = [
            e
            for e in self.executions_by_fingerprint(fingerprint)
            if e.state in (COMPLETE, CACHED)
        ]
        return hits[-1] if hits else None
