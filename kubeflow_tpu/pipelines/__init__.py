"""Pipelines (KFP-equivalent): DSL, compiler, DAG executor, metadata, client.

SURVEY.md §2/§3.5/§7 phase 7.  Layers:
  * ``dsl`` + ``compiler`` — @component/@pipeline → IR JSON (golden-tested);
  * ``metadata`` — MLMD-equivalent native store (C++ core, WAL-backed);
  * ``artifacts`` — MinIO-equivalent local object store;
  * ``workflow`` — Argo-equivalent DAG controller + embedded v2 driver
    (caching, condition gating) and the step-pod launcher;
  * ``schedule`` — ScheduledWorkflow (cron/interval recurring runs);
  * ``service`` + ``client`` — API server + kfp.Client equivalents.
"""

from . import dsl  # noqa: F401
from .client import Client, install  # noqa: F401
from .compiler import Compiler  # noqa: F401
