"""kfp-style client: compile-and-run pipelines against a Cluster.

Upstream analogue (UNVERIFIED, SURVEY.md §3.5): ``kfp.Client`` —
``create_run_from_pipeline_func`` posts to the API server and the SDK polls
run state.  Here the "API server" is the in-process PipelineService and
polling drives the deterministic Manager.
"""

from __future__ import annotations

import os
from typing import Optional, Union

from . import api as papi
from .artifacts import ObjectStore
from .dsl import Pipeline
from .metadata import MetadataStore
from .persistence import PersistenceAgent
from .service import PipelineService
from .schedule import ScheduledWorkflowController
from .workflow import WorkflowController


def install(api, manager, workdir: str, metadata_path: Optional[str] = None):
    """Wire the pipelines control plane into a Manager (idempotent per api).

    Returns the PipelineService (the user-facing API).  A second install on
    the same apiserver returns the existing service — a second MetadataStore
    on the same WAL would corrupt it (single-writer format).
    """
    existing = getattr(api, "_kfp_service", None)
    if existing is not None:
        if os.path.abspath(os.path.join(workdir, "objects")) != existing.store.root:
            raise ValueError(
                f"pipelines already installed with workdir {existing.store.root!r}; "
                f"refusing a second install at {workdir!r} (one WAL writer per cluster)"
            )
        return existing
    papi.register(api)
    store = ObjectStore(os.path.join(workdir, "objects"))
    metadata = MetadataStore(metadata_path or os.path.join(workdir, "metadata.wal"))
    wf = WorkflowController(api, store, metadata, os.path.join(workdir, "nodes"))
    manager.add(wf, owns=("Pod",))
    manager.add(ScheduledWorkflowController(api), owns=("Workflow",))
    service = PipelineService(api, metadata, store)
    # the persistence agent is its own Workflow watcher (upstream informer →
    # ReportWorkflow architecture), not a service-internal poll ticker
    manager.add(PersistenceAgent(api, service))
    api._kfp_service = service
    return service


class RunHandle:
    def __init__(self, client: "Client", run_id: str):
        self.client = client
        self.run_id = run_id

    @property
    def state(self) -> dict:
        return self.client.service.get_run(self.run_id)

    def wait(self, timeout: float = 120.0) -> dict:
        """Drive the cluster until the run is terminal; returns the run record."""
        ok = self.client.manager.run_until(
            lambda: self.state.get("phase") in papi.WORKFLOW_TERMINAL, timeout=timeout
        )
        rec = self.state
        if not ok:
            raise TimeoutError(f"run {self.run_id} still {rec.get('phase')} after {timeout}s")
        return rec


class Client:
    """One per cluster; install() the control plane first (or let us do it)."""

    def __init__(self, cluster, service: Optional[PipelineService] = None):
        self.cluster = cluster
        self.manager = cluster.manager
        self.service = service or install(cluster.api, cluster.manager, os.path.join(cluster.workdir, "pipelines"))

    def create_run_from_pipeline_func(
        self,
        pipeline: Union[Pipeline, dict, str],
        arguments: Optional[dict] = None,
        run_name: Optional[str] = None,
        experiment: Optional[str] = None,
    ) -> RunHandle:
        run_id = self.service.create_run(pipeline, arguments=arguments, run_name=run_name, experiment=experiment)
        return RunHandle(self, run_id)
