"""Pipelines DSL: ``@component`` + ``@pipeline`` with typed params/artifacts.

Upstream analogue (UNVERIFIED, SURVEY.md §2 "KFP: Python SDK"): ``kfp.dsl`` —
``@dsl.component`` lightweight Python components, ``@dsl.pipeline`` tracing,
``Input[...]``/``Output[...]`` artifact IO, ``dsl.Condition``, ``dsl.ParallelFor``.

Rebuild design (not a port):
  * A component is a plain Python function; its **source is embedded in the
    compiled IR** and re-exec'd by the launcher inside the step pod — the same
    lightweight-component mechanism upstream uses, without container images
    (the process kubelet runs ``python -m …launcher_main``).
  * Tracing is eager and deterministic: calling a component inside a pipeline
    function registers a ``Task``; all naming is insertion-ordered so compiled
    IR is byte-stable (golden tests, SURVEY.md §4 "compiler golden files").
  * ``Condition`` compiles to an expression evaluated by the driver at
    runtime (skipped steps are first-class node phases); ``ParallelFor``
    over a static list is expanded at compile time (cloned sub-DAG per item —
    dynamic fan-out over a task output is rejected at compile time).
  * TPU-first resourcing: ``task.set_tpu("v5e-8")`` requests ``google.com/tpu``
    chips + topology, the scheduler's gang/topology semantics apply
    (scheduler/topology.py) — the analogue of upstream's
    ``set_accelerator_type('nvidia.com/gpu')``, which never appears here.
"""

from __future__ import annotations

import copy
import inspect
import textwrap
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Union

# --------------------------------------------------------------------- types

_PARAM_TYPES = {int: "Int", float: "Float", str: "String", bool: "Bool", dict: "Dict", list: "List"}


class Artifact:
    """A file-backed artifact with a URI, a local path, and metadata.

    Inside a component the launcher hands the function an instance whose
    ``.path`` is a real local file/dir path; metadata written here is
    persisted to the metadata store after the step.
    """

    schema_title = "system.Artifact"

    def __init__(self, name: str = "", uri: str = "", metadata: Optional[dict] = None):
        self.name = name
        self.uri = uri
        self.metadata = dict(metadata or {})
        self.path = ""  # set by the launcher to the local staging path

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"{type(self).__name__}(name={self.name!r}, uri={self.uri!r})"


class Dataset(Artifact):
    schema_title = "system.Dataset"


class Model(Artifact):
    schema_title = "system.Model"


class Metrics(Artifact):
    schema_title = "system.Metrics"

    def log_metric(self, key: str, value: float) -> None:
        self.metadata[key] = float(value)


ARTIFACT_TYPES = {c.schema_title: c for c in (Artifact, Dataset, Model, Metrics)}


class _IOSpec:
    __slots__ = ("artifact_type",)

    def __init__(self, artifact_type: type):
        if not (isinstance(artifact_type, type) and issubclass(artifact_type, Artifact)):
            raise TypeError(f"Input[...]/Output[...] takes an Artifact subclass, got {artifact_type!r}")
        self.artifact_type = artifact_type


class _InputSpec(_IOSpec):
    pass


class _OutputSpec(_IOSpec):
    pass


class Input:
    """``Input[Dataset]`` annotation marker for input artifacts."""

    def __class_getitem__(cls, item: type) -> _InputSpec:
        return _InputSpec(item)


class Output:
    """``Output[Model]`` annotation marker for output artifacts."""

    def __class_getitem__(cls, item: type) -> _OutputSpec:
        return _OutputSpec(item)


# ---------------------------------------------------------------- references


class _Comparable:
    """Operator overloads building Condition expressions from references."""

    def _cmp(self, op: str, other: Any) -> "ConditionExpr":
        return ConditionExpr(op, self, other)

    def __eq__(self, other):  # type: ignore[override]
        return self._cmp("==", other)

    def __ne__(self, other):  # type: ignore[override]
        return self._cmp("!=", other)

    def __lt__(self, other):
        return self._cmp("<", other)

    def __le__(self, other):
        return self._cmp("<=", other)

    def __gt__(self, other):
        return self._cmp(">", other)

    def __ge__(self, other):
        return self._cmp(">=", other)

    def __hash__(self):
        return id(self)


@dataclass(eq=False)
class PipelineParam(_Comparable):
    """Reference to a pipeline input parameter."""

    name: str
    type: str = "String"


@dataclass(eq=False)
class LoopItem(_Comparable):
    """Placeholder for the current item of a ParallelFor (compile-time expanded)."""

    group_id: int

    def __getitem__(self, key: str) -> "LoopItemField":
        return LoopItemField(self.group_id, key)


@dataclass(eq=False)
class LoopItemField(_Comparable):
    group_id: int
    key: str


@dataclass(eq=False)
class TaskOutput(_Comparable):
    """Reference to another task's output parameter or artifact."""

    task: "Task"
    name: str
    is_artifact: bool
    type: str = "String"


@dataclass(eq=False)
class Collected(_Comparable):
    """Fan-in over a dynamic ParallelFor: ``dsl.Collected(task.output)``
    consumed OUTSIDE the loop resolves at runtime to the list of every
    iteration's output, in item order (upstream KFP v2 ``dsl.Collected``).
    Parameter outputs only — collect an artifact by returning its path/
    content as a parameter."""

    source: TaskOutput

    def __post_init__(self):
        if not isinstance(self.source, TaskOutput):
            raise TypeError("dsl.Collected takes a task output "
                            "(e.g. Collected(task.output))")
        if self.source.is_artifact:
            raise TypeError(
                "dsl.Collected collects parameter outputs; return the "
                "artifact's content (or URI) as a parameter to collect it")


class ConditionExpr:
    """A binary comparison over references/constants, evaluated by the driver."""

    def __init__(self, op: str, left: Any, right: Any):
        self.op = op
        self.left = left
        self.right = right

    def referenced_tasks(self) -> list["Task"]:
        return [x.task for x in (self.left, self.right) if isinstance(x, TaskOutput)]


# -------------------------------------------------------------------- groups


@dataclass
class _Group:
    kind: str  # "root" | "condition" | "loop" | "exit"
    group_id: int
    condition: Optional[ConditionExpr] = None
    items: Optional[Union[list, TaskOutput]] = None
    loop_item: Optional[LoopItem] = None
    tasks: list["Task"] = field(default_factory=list)
    exit_task: Optional["Task"] = None  # kind == "exit": the cleanup task
    # dynamic ParallelFor: fan out at RUNTIME over a producer task's list
    # output (upstream KFP v2 `dsl.ParallelFor(task.output)`); the compiler
    # emits an `iterator` marker instead of cloning, and the workflow
    # controller expands when the producer completes
    items_from: Optional[TaskOutput] = None


class Condition:
    """``with dsl.Condition(task.output > 0.5):`` — runtime-gated sub-DAG."""

    def __init__(self, expr: ConditionExpr, name: str = ""):
        if not isinstance(expr, ConditionExpr):
            raise TypeError("dsl.Condition takes a comparison over a task output or pipeline param")
        self.expr = expr
        self.name = name

    def __enter__(self):
        ctx = _require_context("dsl.Condition")
        ctx.push_group(_Group("condition", ctx.next_group_id(), condition=self.expr))
        return self

    def __exit__(self, *exc):
        _require_context("dsl.Condition").pop_group()
        return False


class ExitHandler:
    """``with dsl.ExitHandler(cleanup_task):`` — the cleanup task runs once
    every task in the block reaches ANY terminal state, success or failure
    (upstream ``[U:pipelines/sdk/python/kfp/dsl]`` ExitHandler semantics; the
    workflow stays Running until the cleanup finishes, then reports the
    block's real outcome).  ``cleanup_task`` must be created BEFORE the
    ``with`` block and take only regular inputs."""

    def __init__(self, exit_task: "Task"):
        if not isinstance(exit_task, Task):
            raise TypeError("dsl.ExitHandler takes the cleanup Task "
                            "(create it before the with block)")
        self.exit_task = exit_task

    def __enter__(self):
        ctx = _require_context("dsl.ExitHandler")
        ctx.push_group(_Group("exit", ctx.next_group_id(), exit_task=self.exit_task))
        return self

    def __exit__(self, *exc):
        _require_context("dsl.ExitHandler").pop_group()
        return False


class ParallelFor:
    """``with dsl.ParallelFor(items) as item:`` — fan-out per item.

    A static list expands at compile time (cloned tasks); a task output
    (``dsl.ParallelFor(t.output)``) expands at RUNTIME once the producer
    finishes — the output must be a JSON list."""

    def __init__(self, items: Union[list, tuple, TaskOutput]):
        self.items: Optional[list] = None
        self.items_from: Optional[TaskOutput] = None
        if isinstance(items, TaskOutput):
            if items.is_artifact:
                raise TypeError(
                    "dynamic ParallelFor iterates a parameter output (a JSON "
                    "list), not an artifact — return the list from the "
                    "component instead"
                )
            self.items_from = items
        else:
            self.items = list(items)

    def __enter__(self) -> LoopItem:
        ctx = _require_context("dsl.ParallelFor")
        gid = ctx.next_group_id()
        g = _Group("loop", gid, items=self.items, loop_item=LoopItem(gid),
                   items_from=self.items_from)
        ctx.push_group(g)
        return g.loop_item

    def __exit__(self, *exc):
        _require_context("dsl.ParallelFor").pop_group()
        return False


# ----------------------------------------------------------------- component


@dataclass
class ComponentSpec:
    name: str
    source: str
    function_name: str
    input_params: dict  # name -> {"type": str, "default": present?}
    input_artifacts: dict  # name -> schema_title
    output_params: dict  # name -> type
    output_artifacts: dict  # name -> schema_title
    defaults: dict


class Component:
    """A Python-function component; calling it inside a pipeline adds a Task."""

    def __init__(self, fn: Callable, name: Optional[str] = None):
        self.fn = fn
        self.name = name or fn.__name__.replace("_", "-")
        self.spec = self._introspect(fn)

    def _introspect(self, fn: Callable) -> ComponentSpec:
        # eval_str: modules with `from __future__ import annotations` deliver
        # annotations as strings; Input/Output markers must be real objects
        sig = inspect.signature(fn, eval_str=True)
        in_params: dict = {}
        in_artifacts: dict = {}
        out_params: dict = {}
        out_artifacts: dict = {}
        defaults: dict = {}
        for pname, p in sig.parameters.items():
            ann = p.annotation
            if isinstance(ann, _OutputSpec):
                out_artifacts[pname] = ann.artifact_type.schema_title
            elif isinstance(ann, _InputSpec):
                in_artifacts[pname] = ann.artifact_type.schema_title
            elif isinstance(ann, type) and issubclass(ann, Artifact):
                raise TypeError(
                    f"component {self.name!r} param {pname!r}: use Input[{ann.__name__}] "
                    f"or Output[{ann.__name__}], not the bare artifact type"
                )
            else:
                ptype = _PARAM_TYPES.get(ann, "String")
                in_params[pname] = {"type": ptype}
                if p.default is not inspect.Parameter.empty:
                    defaults[pname] = p.default
        ret = sig.return_annotation
        if ret is not inspect.Signature.empty and ret is not None:
            out_params["Output"] = _PARAM_TYPES.get(ret, "String")
        try:
            source = textwrap.dedent(inspect.getsource(fn))
        except (OSError, TypeError) as e:
            raise ValueError(
                f"component {self.name!r}: cannot extract source for the launcher ({e}); "
                "define components at module/function top level"
            ) from e
        # strip decorator lines so the source is a plain function definition
        lines = source.splitlines()
        start = next(i for i, ln in enumerate(lines) if ln.lstrip().startswith("def "))
        return ComponentSpec(
            name=self.name,
            source="\n".join(lines[start:]) + "\n",
            function_name=fn.__name__,
            input_params=in_params,
            input_artifacts=in_artifacts,
            output_params=out_params,
            output_artifacts=out_artifacts,
            defaults=defaults,
        )

    def __call__(self, **kwargs: Any) -> "Task":
        ctx = _current_context()
        if ctx is None:
            # outside a pipeline: run the function directly (unit-test ergonomics)
            return self.fn(**kwargs)
        return ctx.add_task(self, kwargs)


def component(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """Decorator: ``@dsl.component`` or ``@dsl.component(name=...)``."""
    if fn is None:
        return lambda f: Component(f, name=name)
    return Component(fn)


# ---------------------------------------------------------------------- task


class _TaskOutputs:
    def __init__(self, task: "Task"):
        self._task = task

    def __getitem__(self, name: str) -> TaskOutput:
        spec = self._task.component.spec
        if name in spec.output_artifacts:
            return TaskOutput(self._task, name, is_artifact=True, type=spec.output_artifacts[name])
        if name in spec.output_params:
            return TaskOutput(self._task, name, is_artifact=False, type=spec.output_params[name])
        raise KeyError(
            f"component {spec.name!r} has no output {name!r} "
            f"(params: {sorted(spec.output_params)}, artifacts: {sorted(spec.output_artifacts)})"
        )


class Task:
    """One node of the pipeline DAG."""

    def __init__(self, name: str, component_: Component, inputs: dict, group_path: tuple):
        self.name = name
        self.component = component_
        self.inputs = inputs  # pname -> constant | PipelineParam | TaskOutput | LoopItem(Field)
        self.group_path = group_path  # enclosing Condition/ParallelFor groups, outermost first
        self.dependencies: list[Task] = []
        self.display_name = name
        self.resources: dict = {}
        self.tpu: Optional[dict] = None
        self.enable_caching = True
        self.retries = 0
        self.outputs = _TaskOutputs(self)

    @property
    def output(self) -> TaskOutput:
        spec = self.component.spec
        if len(spec.output_params) == 1:
            return self.outputs[next(iter(spec.output_params))]
        if not spec.output_params and len(spec.output_artifacts) == 1:
            return self.outputs[next(iter(spec.output_artifacts))]
        raise AttributeError(
            f"task {self.name!r} has multiple outputs; use .outputs['name']"
        )

    # -------- fluent config (subset of upstream PipelineTask methods) --------

    def after(self, *tasks: "Task") -> "Task":
        self.dependencies.extend(tasks)
        return self

    def set_display_name(self, name: str) -> "Task":
        self.display_name = name
        return self

    def set_cpu_limit(self, cpu: str) -> "Task":
        self.resources["cpu"] = cpu
        return self

    def set_memory_limit(self, memory: str) -> "Task":
        self.resources["memory"] = memory
        return self

    def set_tpu(self, accelerator: str, chips: int = 0) -> "Task":
        """Request a TPU slice for this step, e.g. ``set_tpu("v5e-8")``.

        The compiled node asks the topology scheduler for a ``google.com/tpu``
        placement; chips defaults to the count encoded in the name (the
        ``-N`` suffix, or an ``AxB`` topology tail).  Validated here so a bad
        accelerator string fails at pipeline-definition time, not inside the
        workflow controller.
        """
        if not chips:
            tail = accelerator.rsplit("-", 1)[-1]
            try:
                if "x" in tail:
                    chips = 1
                    for part in tail.split("x"):
                        chips *= int(part)
                else:
                    chips = int(tail)
            except ValueError:
                raise ValueError(
                    f"set_tpu: cannot infer chip count from {accelerator!r}; "
                    "use e.g. 'v5e-8' / 'v5e-2x4' or pass chips= explicitly"
                ) from None
        self.tpu = {"accelerator": accelerator, "chips": int(chips)}
        return self

    def set_caching_options(self, enable: bool) -> "Task":
        self.enable_caching = enable
        return self

    def set_retry(self, num_retries: int) -> "Task":
        self.retries = int(num_retries)
        return self


# ------------------------------------------------------------------ pipeline


class _BuildContext:
    def __init__(self):
        self.root = _Group("root", 0)
        self._stack = [self.root]
        self.tasks: list[Task] = []
        self._names: dict[str, int] = {}
        self._gid = 0

    def next_group_id(self) -> int:
        self._gid += 1
        return self._gid

    def push_group(self, g: _Group) -> None:
        self._stack.append(g)

    def pop_group(self) -> None:
        self._stack.pop()

    def add_task(self, component_: Component, kwargs: dict) -> Task:
        spec = component_.spec
        known = set(spec.input_params) | set(spec.input_artifacts)
        unknown = set(kwargs) - known
        if unknown:
            raise TypeError(f"component {spec.name!r}: unknown inputs {sorted(unknown)}")
        missing = [
            p for p in spec.input_params
            if p not in kwargs and p not in spec.defaults
        ] + [a for a in spec.input_artifacts if a not in kwargs]
        if missing:
            raise TypeError(f"component {spec.name!r}: missing inputs {missing}")
        n = self._names.get(spec.name, 0) + 1
        self._names[spec.name] = n
        name = spec.name if n == 1 else f"{spec.name}-{n}"
        task = Task(name, component_, dict(kwargs), tuple(self._stack[1:]))
        self._stack[-1].tasks.append(task)
        self.tasks.append(task)
        return task


_ctx_stack: list[_BuildContext] = []


def _current_context() -> Optional[_BuildContext]:
    return _ctx_stack[-1] if _ctx_stack else None


def _require_context(what: str) -> _BuildContext:
    ctx = _current_context()
    if ctx is None:
        raise RuntimeError(f"{what} used outside a @dsl.pipeline function")
    return ctx


class Pipeline:
    """A traced pipeline definition (compile with compiler.Compiler)."""

    def __init__(self, fn: Callable, name: Optional[str] = None, description: str = ""):
        self.fn = fn
        self.name = name or fn.__name__.replace("_", "-")
        self.description = description
        sig = inspect.signature(fn)
        self.params: dict = {}
        self.defaults: dict = {}
        for pname, p in sig.parameters.items():
            self.params[pname] = _PARAM_TYPES.get(p.annotation, "String")
            if p.default is not inspect.Parameter.empty:
                self.defaults[pname] = p.default

    def trace(self) -> _BuildContext:
        ctx = _BuildContext()
        _ctx_stack.append(ctx)
        try:
            self.fn(**{p: PipelineParam(p, t) for p, t in self.params.items()})
        finally:
            _ctx_stack.pop()
        return ctx


def pipeline(fn: Optional[Callable] = None, *, name: Optional[str] = None, description: str = ""):
    """Decorator: ``@dsl.pipeline`` or ``@dsl.pipeline(name=..., description=...)``."""
    if fn is None:
        return lambda f: Pipeline(f, name=name, description=description)
    return Pipeline(fn)
