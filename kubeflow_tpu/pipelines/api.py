"""Pipelines CRDs + typed builders.

Upstream analogue (UNVERIFIED, SURVEY.md §2): Argo's ``Workflow`` CR (the KFP
execution substrate) and KFP's ``ScheduledWorkflow`` CR.  Pipeline/run/
experiment records live in the PipelineService (service.py) — upstream keeps
those in MySQL, not CRDs, and we mirror that split.
"""

from __future__ import annotations

from typing import Optional

from ..core.api import APIServer, CRD, Invalid, Obj

GROUP = "pipelines.kubeflow.org"
VERSION = "v1"

LABEL_RUN = f"{GROUP}/run"
LABEL_WORKFLOW = f"{GROUP}/workflow"
LABEL_NODE = f"{GROUP}/node"

# workflow / node phases
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"
SKIPPED = "Skipped"    # condition evaluated false
OMITTED = "Omitted"    # upstream dependency failed/skipped
NODE_TERMINAL = {SUCCEEDED, FAILED, SKIPPED, OMITTED}
WORKFLOW_TERMINAL = {SUCCEEDED, FAILED}


def _validate_workflow(obj: Obj) -> None:
    spec = obj.get("spec", {})
    ir = spec.get("pipelineSpec")
    if not isinstance(ir, dict) or "root" not in ir or "deploymentSpec" not in ir:
        raise Invalid("Workflow.spec.pipelineSpec must be a compiled pipeline IR")
    tasks = ir["root"].get("dag", {}).get("tasks", {})
    if not tasks:
        raise Invalid("Workflow pipelineSpec has no tasks")
    for name, node in tasks.items():
        for dep in node.get("dependentTasks", []):
            if dep not in tasks:
                raise Invalid(f"task {name!r} depends on unknown task {dep!r}")


def _validate_scheduled(obj: Obj) -> None:
    spec = obj.get("spec", {})
    trigger = spec.get("trigger", {})
    if ("intervalSeconds" in trigger) == ("cron" in trigger):
        raise Invalid("ScheduledWorkflow.spec.trigger needs exactly one of intervalSeconds | cron")
    if "pipelineSpec" not in spec:
        raise Invalid("ScheduledWorkflow.spec.pipelineSpec is required")


def register(api: APIServer) -> None:
    api.register_crd(
        CRD(group=GROUP, version=VERSION, kind="Workflow", plural="workflows", validator=_validate_workflow)
    )
    api.register_crd(
        CRD(
            group=GROUP,
            version=VERSION,
            kind="ScheduledWorkflow",
            plural="scheduledworkflows",
            validator=_validate_scheduled,
        )
    )


def workflow(
    name: str,
    pipeline_spec: dict,
    arguments: Optional[dict] = None,
    namespace: str = "default",
    labels: Optional[dict] = None,
) -> Obj:
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "Workflow",
        "metadata": {"name": name, "namespace": namespace, "labels": dict(labels or {})},
        "spec": {"pipelineSpec": pipeline_spec, "arguments": dict(arguments or {})},
    }


def scheduled_workflow(
    name: str,
    pipeline_spec: dict,
    interval_seconds: Optional[float] = None,
    cron: Optional[str] = None,
    arguments: Optional[dict] = None,
    max_concurrency: int = 1,
    namespace: str = "default",
) -> Obj:
    trigger: dict = {}
    if interval_seconds is not None:
        trigger["intervalSeconds"] = interval_seconds
    if cron is not None:
        trigger["cron"] = cron
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "ScheduledWorkflow",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "pipelineSpec": pipeline_spec,
            "arguments": dict(arguments or {}),
            "trigger": trigger,
            "maxConcurrency": max_concurrency,
            "enabled": True,
        },
    }
