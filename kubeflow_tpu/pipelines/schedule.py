"""ScheduledWorkflow controller: cron/interval-triggered pipeline runs.

Upstream analogue (UNVERIFIED, SURVEY.md §2): KFP's ScheduledWorkflow CRD +
controller (`[U:pipelines/backend/src/crd/controller/scheduledworkflow]`) —
recurring runs with max-concurrency gating.
"""

from __future__ import annotations

import time
from typing import Optional

from ..core.api import AlreadyExists, APIServer, Obj, owner_reference
from ..core.events import EventRecorder
from ..core.controller import Request, Result
from . import api as papi
from . import cron


class ScheduledWorkflowController:
    kind = "ScheduledWorkflow"

    def __init__(self, api: APIServer):
        self.api = api
        self.recorder = EventRecorder(api, "scheduledworkflow-controller")

    def _active(self, swf: Obj) -> int:
        wfs = self.api.list(
            "Workflow",
            namespace=swf["metadata"].get("namespace", "default"),
            label_selector={"scheduledworkflow": swf["metadata"]["name"]},
        )
        return sum(
            1
            for w in wfs
            if w.get("status", {}).get("phase") not in papi.WORKFLOW_TERMINAL
        )

    def _next_fire(self, swf: Obj, now: float) -> Optional[float]:
        trigger = swf["spec"]["trigger"]
        last = swf.get("status", {}).get("lastFiredAt")
        if "intervalSeconds" in trigger:
            base = last if last is not None else now - trigger["intervalSeconds"]
            return base + trigger["intervalSeconds"]
        return cron.next_fire(trigger["cron"], last if last is not None else now)

    def reconcile(self, req: Request) -> Optional[Result]:
        swf = self.api.try_get("ScheduledWorkflow", req.name, req.namespace)
        if swf is None:
            return None
        status = swf.setdefault("status", {})
        if not swf["spec"].get("enabled", True):
            return None
        now = time.time()
        fire_at = self._next_fire(swf, now)
        if fire_at is None:
            return None
        if fire_at > now:
            return Result(requeue_after=min(fire_at - now, 60.0))
        if self._active(swf) >= swf["spec"].get("maxConcurrency", 1):
            status["conditions"] = [{"type": "Throttled", "lastUpdate": now}]
            self.api.update_status(swf)
            return Result(requeue_after=1.0)
        n = status.get("fireCount", 0) + 1
        wf = papi.workflow(
            f"{req.name}-{n}",
            swf["spec"]["pipelineSpec"],
            arguments=swf["spec"].get("arguments"),
            namespace=req.namespace,
            labels={"scheduledworkflow": req.name},
        )
        wf["metadata"]["ownerReferences"] = [owner_reference(swf)]
        try:
            self.api.create(wf)
            self.recorder.normal(swf, "WorkflowTriggered", f"created workflow {req.name}-{n}")
        except AlreadyExists:
            pass
        status["fireCount"] = n
        status["lastFiredAt"] = now
        self.api.update_status(swf)
        nxt = self._next_fire(swf, now)
        return Result(requeue_after=max(0.05, min((nxt or now + 60) - now, 60.0)))
