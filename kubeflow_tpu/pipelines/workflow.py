"""Workflow DAG controller: the Argo-equivalent executor + embedded v2 driver.

Upstream analogue (UNVERIFIED, SURVEY.md §3.5): Argo's workflow-controller
schedules DAG nodes as pods; KFP v2 adds a per-node *driver* (input
resolution + cache check against MLMD) and a *launcher* (runs user code,
uploads artifacts).  Deviations by design of the deterministic simulator
(same pattern as katib/controllers.py):

  * the driver runs **in-process at reconcile time** instead of as a separate
    driver container — identical inputs-resolution/fingerprint contract;
  * the launcher pod reports results through its node workspace directory
    (``outputs.json``) rather than a sidecar API call, because pods here are
    plain OS processes with no apiserver endpoint;
  * the controller is the **single writer** to the metadata store (WAL is a
    one-writer format); the launcher only touches the object store.

Node lifecycle: Pending → (driver: skip | cache-hit | pod created) →
Running → Succeeded/Failed (with retries) ; condition false → Skipped ;
upstream dep failed/skipped → Omitted.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
from typing import Any, Optional

from ..core.api import APIServer, AlreadyExists, Obj, owner_reference
from ..core.events import EventRecorder
from ..core.controller import Request, Result
from ..scheduler.topology import TPU_RESOURCE, chips_in
from . import api as papi
from .artifacts import ObjectStore
from . import metadata as md


def _resolve_ref(ref: dict, args: dict, nodes: dict) -> Any:
    """Resolve one IR value reference against run args + completed nodes."""
    if "constant" in ref:
        return ref["constant"]
    if "componentInputParameter" in ref:
        return args[ref["componentInputParameter"]]
    if "taskOutputParameter" in ref:
        src = ref["taskOutputParameter"]
        node = nodes.get(src["producerTask"], {})
        outs = node.get("outputParameters", {})
        if src["outputParameterKey"] not in outs:
            raise KeyError(
                f"task {src['producerTask']!r} produced no output "
                f"parameter {src['outputParameterKey']!r}"
            )
        return outs[src["outputParameterKey"]]
    if "collectedOutput" in ref:
        # dsl.Collected fan-in: the per-iteration outputs of a dynamic
        # ParallelFor, in item order.  The consumer depends on the loop's
        # virtual node, so every child is terminal here; iterations a
        # Condition skipped contribute nothing (upstream semantics).
        src = ref["collectedOutput"]
        virtual = nodes.get(src["producerTask"], {})
        out = []
        for k in range(len(virtual.get("items", []))):
            child = nodes.get(f"{src['producerTask']}-it{k}", {})
            outs = child.get("outputParameters")
            if outs is None:
                continue  # skipped/omitted iteration
            if src["outputParameterKey"] not in outs:
                raise KeyError(
                    f"iteration {k} of {src['producerTask']!r} produced no "
                    f"output parameter {src['outputParameterKey']!r}")
            out.append(outs[src["outputParameterKey"]])
        return out
    raise ValueError(f"unresolvable reference: {ref!r}")


_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _eval_condition(expr: dict, args: dict, nodes: dict) -> bool:
    left = _resolve_ref(expr["left"], args, nodes)
    right = _resolve_ref(expr["right"], args, nodes)
    return bool(_OPS[expr["op"]](left, right))


def _refs_loop_item(v: Any, gid: int) -> bool:
    """True if ``v`` (a condition/operand tree) contains a ``loopItem``
    marker for iterator group ``gid`` — i.e. it can only be evaluated on an
    instantiated child, never on the virtual fan-out node."""
    if isinstance(v, dict):
        if v.get("loopItem", {}).get("groupId") == gid:
            return True
        return any(_refs_loop_item(x, gid) for x in v.values())
    if isinstance(v, list):
        return any(_refs_loop_item(x, gid) for x in v)
    return False


def _instantiate_iteration(tspec: dict, dag: dict, gid: int, k: int,
                           item: Any) -> dict:
    """One dynamic-ParallelFor child's concrete task spec: ``loopItem``
    markers become constants, and intra-group references retarget the
    same-index sibling (``dep`` → ``dep-itK``), mirroring the static
    expansion's clone_map semantics."""

    def same_group(name: str) -> bool:
        return dag.get(name, {}).get("iterator", {}).get("groupId") == gid

    def subst(v: Any) -> Any:
        if isinstance(v, dict):
            if "loopItem" in v and v["loopItem"].get("groupId") == gid:
                field = v["loopItem"].get("field")
                if field is None:
                    return {"constant": item}
                if not isinstance(item, dict) or field not in item:
                    raise ValueError(
                        f"ParallelFor item {item!r} has no field {field!r}")
                return {"constant": item[field]}
            out = {kk: subst(vv) for kk, vv in v.items()}
            for key in ("producerTask",):
                if key in out and isinstance(out[key], str) and same_group(out[key]):
                    out[key] = f"{out[key]}-it{k}"
            return out
        if isinstance(v, list):
            return [subst(x) for x in v]
        return v

    cspec = subst({kk: vv for kk, vv in tspec.items() if kk != "iterator"})
    cspec["dependentTasks"] = [
        f"{d}-it{k}" if same_group(d) else d
        for d in tspec.get("dependentTasks", [])]
    return cspec


class WorkflowController:
    kind = "Workflow"

    def __init__(
        self,
        api: APIServer,
        store: ObjectStore,
        metadata_store: md.MetadataStore,
        workdir: str,
    ):
        self.api = api
        self.store = store
        self.metadata = metadata_store
        self.workdir = workdir
        self.recorder = EventRecorder(api, "workflow-controller")

    # ------------------------------------------------------------- reconcile

    def reconcile(self, req: Request) -> Optional[Result]:
        wf = self.api.try_get("Workflow", req.name, req.namespace)
        if wf is None:
            return None
        status = wf.setdefault("status", {})
        if status.get("phase") in papi.WORKFLOW_TERMINAL:
            return None
        if "phase" not in status:
            status["phase"] = papi.RUNNING
            status["startedAt"] = time.time()
            status["nodes"] = {}
            ctx_id = self.metadata.put_context(
                "pipeline_run",
                f"{req.namespace}/{req.name}",
                {"pipeline": wf["spec"]["pipelineSpec"]["pipelineInfo"]["name"]},
            )
            status["contextId"] = ctx_id
            self.recorder.normal(wf, "WorkflowStarted", "DAG execution started")

        ir = wf["spec"]["pipelineSpec"]
        dag = ir["root"]["dag"]["tasks"]
        try:
            args = self._arguments(wf, ir)
        except ValueError as e:
            # user-supplied arguments are wrong: terminal, not retryable
            status["phase"] = papi.FAILED
            status["message"] = str(e)
            status["finishedAt"] = time.time()
            self.recorder.warning(wf, "InvalidArguments", str(e))
            self.api.update_status(wf)
            return None
        nodes = status["nodes"]
        progressed = False

        # iterate to fixpoint: phase changes (Failed/Skipped/Succeeded) must
        # propagate to dependents within one reconcile regardless of task
        # name ordering
        while True:
            pass_progressed = False
            for tname in sorted(dag):
                node = nodes.setdefault(tname, {"phase": papi.PENDING, "retries": 0})
                if node["phase"] in papi.NODE_TERMINAL:
                    continue
                if "iterator" in dag[tname]:
                    # dynamic ParallelFor: this entry is a VIRTUAL node that
                    # expands into children once its producer finishes
                    if self._drive_iterator(wf, tname, dag[tname], node, args, ir, dag):
                        pass_progressed = True
                    continue
                if node["phase"] == papi.RUNNING:
                    if self._check_pod(wf, tname, dag[tname], node, args):
                        pass_progressed = True
                    continue
                # Pending: gate on dependencies
                dep_phases = [nodes.get(d, {}).get("phase", papi.PENDING) for d in dag[tname].get("dependentTasks", [])]
                if dag[tname].get("isExitHandler"):
                    # ExitHandler cleanup: runs once every guarded task is
                    # terminal in ANY phase — failures must not omit it
                    if not all(p in papi.NODE_TERMINAL for p in dep_phases):
                        continue
                elif any(p in (papi.FAILED, papi.SKIPPED, papi.OMITTED) for p in dep_phases):
                    node["phase"] = papi.OMITTED
                    pass_progressed = True
                    continue
                elif not all(p == papi.SUCCEEDED for p in dep_phases):
                    continue
                if self._drive(wf, tname, dag[tname], node, args, ir):
                    pass_progressed = True
            if not pass_progressed:
                break
            progressed = True

        phase = self._aggregate(nodes, dag)
        if phase != status["phase"]:
            status["phase"] = phase
            if phase in papi.WORKFLOW_TERMINAL:
                status["finishedAt"] = time.time()
                self.recorder.normal(wf, f"Workflow{phase}", f"workflow {phase.lower()}")
            progressed = True
        if progressed:
            self.api.update_status(wf)
        return None

    def _arguments(self, wf: Obj, ir: dict) -> dict:
        defs = ir["root"]["inputDefinitions"]["parameters"]
        args = {}
        for pname, d in defs.items():
            if "defaultValue" in d:
                args[pname] = d["defaultValue"]
        args.update(wf["spec"].get("arguments", {}))
        unknown = set(wf["spec"].get("arguments", {})) - set(defs)
        if unknown:
            raise ValueError(f"unknown pipeline arguments: {sorted(unknown)}")
        missing = set(defs) - set(args)
        if missing:
            raise ValueError(f"missing pipeline arguments: {sorted(missing)}")
        return args

    def _aggregate(self, nodes: dict, dag: dict) -> str:
        phases = [nodes.get(t, {}).get("phase", papi.PENDING) for t in dag]
        # terminal only once EVERY node is terminal: a failure OMITs its
        # dependents within the same fixpoint, but ExitHandler cleanups still
        # have to run (and finish) before the workflow's phase settles
        if not all(p in papi.NODE_TERMINAL for p in phases):
            return papi.RUNNING
        if any(p == papi.FAILED for p in phases):
            return papi.FAILED
        return papi.SUCCEEDED

    # ------------------------------------------------- dynamic ParallelFor

    def _drive_iterator(self, wf: Obj, tname: str, tspec: dict, node: dict,
                        args: dict, ir: dict, dag: dict) -> bool:
        """Runtime fan-out (dsl.ParallelFor(task.output)): once the producer
        succeeds, read its JSON-list output and drive one child node per
        item through the normal driver (conditions, caching, retries all
        apply per child).  The virtual node's phase aggregates the children,
        so downstream dependents gate on it like any other task."""
        nodes = wf["status"]["nodes"]
        it = tspec["iterator"]
        if node["phase"] == papi.PENDING:
            dep_phases = [nodes.get(d, {}).get("phase", papi.PENDING)
                          for d in tspec.get("dependentTasks", [])]
            if any(p in (papi.FAILED, papi.SKIPPED, papi.OMITTED)
                   for p in dep_phases):
                node["phase"] = papi.OMITTED
                return True
            if not all(p == papi.SUCCEEDED for p in dep_phases):
                return False
            # the virtual node's own conditions gate expansion, mirroring
            # _drive: a dynamic ParallelFor nested in a false dsl.Condition
            # must SKIP (and OMIT its dependents) exactly like a static
            # loop.  Conditions that reference THIS group's loop item are
            # per-child — they evaluate after _instantiate_iteration
            # substitutes the item, not here
            for cond in tspec.get("conditions", []):
                if _refs_loop_item(cond, it["groupId"]):
                    continue
                if not _eval_condition(cond, args, nodes):
                    node["phase"] = papi.SKIPPED
                    return True
            raw = nodes.get(it["producerTask"], {}).get(
                "outputParameters", {}).get(it["outputParameterKey"])
            items = raw
            if isinstance(items, str):
                try:
                    items = json.loads(items)
                except ValueError:
                    items = None
            if not isinstance(items, list):
                node.update(phase=papi.FAILED,
                            message=f"ParallelFor source "
                                    f"{it['producerTask']}.{it['outputParameterKey']} "
                                    f"is not a JSON list: {raw!r}")
                self.recorder.warning(wf, "IteratorInvalid", node["message"])
                return True
            node["items"] = items
            node["phase"] = papi.SUCCEEDED if not items else papi.RUNNING
            return True
        if node["phase"] != papi.RUNNING:
            return False
        progressed = False
        child_phases = []
        for k, item in enumerate(node.get("items", [])):
            cname = f"{tname}-it{k}"
            child = nodes.setdefault(cname, {"phase": papi.PENDING, "retries": 0})
            if child["phase"] in papi.NODE_TERMINAL:
                child_phases.append(child["phase"])
                continue
            # instantiate ONCE and persist on the child: the substitution is
            # fully determined by (tspec, k, item), re-deriving it per
            # fixpoint pass would be pure per-tick overhead — and the
            # persisted spec survives a controller restart mid-run
            cspec = child.get("spec")
            if cspec is None:
                try:
                    cspec = _instantiate_iteration(tspec, dag, it["groupId"], k, item)
                except ValueError as e:  # e.g. item missing a referenced field
                    child.update(phase=papi.FAILED, message=str(e))
                    self.recorder.warning(wf, "IteratorItemInvalid", str(e))
                    child_phases.append(child["phase"])
                    progressed = True
                    continue
                child["spec"] = cspec
            if child["phase"] == papi.RUNNING:
                if self._check_pod(wf, cname, cspec, child, args):
                    progressed = True
            else:
                dep_phases = [nodes.get(d, {}).get("phase", papi.PENDING)
                              for d in cspec.get("dependentTasks", [])]
                if any(p in (papi.FAILED, papi.SKIPPED, papi.OMITTED)
                       for p in dep_phases):
                    child["phase"] = papi.OMITTED
                    progressed = True
                elif all(p == papi.SUCCEEDED for p in dep_phases):
                    if self._drive(wf, cname, cspec, child, args, ir):
                        progressed = True
            child_phases.append(child["phase"])
        if child_phases and all(p in papi.NODE_TERMINAL for p in child_phases):
            if any(p == papi.FAILED for p in child_phases):
                node["phase"] = papi.FAILED
            elif any(p in (papi.SKIPPED, papi.OMITTED) for p in child_phases):
                # static-loop parity: a static expansion attaches dependents
                # to EVERY clone, and one SKIPPED/OMITTED dep OMITs them —
                # so ANY skipped child must gate dependents of the virtual
                # node the same way (a Collected consumer of a partial
                # fan-out would otherwise read missing outputs)
                node["phase"] = papi.SKIPPED
            else:
                node["phase"] = papi.SUCCEEDED
            progressed = True
        return progressed

    # ---------------------------------------------------------------- driver

    def _drive(self, wf: Obj, tname: str, tspec: dict, node: dict, args: dict, ir: dict) -> bool:
        """KFP-v2-driver equivalent: conditions, input resolution, cache, pod."""
        nodes = wf["status"]["nodes"]
        for cond in tspec.get("conditions", []):
            if not _eval_condition(cond, args, nodes):
                node["phase"] = papi.SKIPPED
                return True

        params = {
            p: _resolve_ref(ref, args, nodes)
            for p, ref in tspec["inputs"]["parameters"].items()
        }
        in_artifacts = {}
        for aname, ref in tspec["inputs"]["artifacts"].items():
            src = ref["taskOutputArtifact"]
            prod = nodes.get(src["producerTask"], {})
            art = prod.get("outputArtifacts", {}).get(src["outputArtifactKey"])
            if art is None:
                raise KeyError(
                    f"task {src['producerTask']!r} produced no artifact {src['outputArtifactKey']!r}"
                )
            in_artifacts[aname] = art

        comp = ir["components"][tspec["componentRef"]]
        executor = ir["deploymentSpec"]["executors"][comp["executorLabel"]]
        out_param_defs = comp["outputDefinitions"]["parameters"]
        out_artifact_defs = comp["outputDefinitions"]["artifacts"]

        fp = _fingerprint(executor, params, in_artifacts, out_artifact_defs)
        node["fingerprint"] = fp
        if tspec.get("cachingOptions", {}).get("enableCache", True):
            cached = self.metadata.find_cached_execution(fp)
            if cached is not None:
                outs = cached.properties.get("outputs", {})
                node.update(
                    phase=papi.SUCCEEDED,
                    cached=True,
                    executionId=cached.id,
                    outputParameters=outs.get("parameters", {}),
                    outputArtifacts=outs.get("artifacts", {}),
                )
                self.recorder.normal(wf, "CacheHit", f"node {tname}: reused execution {cached.id}")
                return True

        # stage the node workspace + launcher pod
        run_uid = wf["metadata"]["uid"]
        workspace = os.path.join(self.workdir, run_uid, f"{tname}-r{node['retries']}")
        os.makedirs(workspace, exist_ok=True)
        out_artifacts = {
            aname: {
                "uri": self.store.uri("mlpipeline", f"{run_uid}/{tname}/{aname}"),
                "type": adef["schemaTitle"],
            }
            for aname, adef in out_artifact_defs.items()
        }
        task_doc = {
            "functionName": executor["python"]["functionName"],
            "source": executor["python"]["source"],
            "defaults": executor["python"].get("defaults", {}),
            "parameters": params,
            "inputArtifacts": in_artifacts,
            "outputArtifacts": out_artifacts,
            "outputParameters": sorted(out_param_defs),
            "storeRoot": self.store.root,
        }
        # tmp+os.replace: the launcher subprocess reads this back — a torn
        # write would crash the task with an unreadable doc (graftlint
        # atomic-write)
        task_path = os.path.join(workspace, "task.json")
        with open(task_path + ".tmp", "w") as f:
            json.dump(task_doc, f)
        os.replace(task_path + ".tmp", task_path)

        pod_name = f"{wf['metadata']['name']}-{tname}-r{node['retries']}"
        pod = self._pod(wf, tname, tspec, pod_name, workspace)
        try:
            self.api.create(pod)
        except AlreadyExists:
            pass
        node.update(phase=papi.RUNNING, podName=pod_name, workspace=workspace)
        node["inputParameters"] = params
        node["inputArtifacts"] = in_artifacts
        node["stagedOutputArtifacts"] = out_artifacts
        return True

    def _pod(self, wf: Obj, tname: str, tspec: dict, pod_name: str, workspace: str) -> Obj:
        resources: dict = dict(tspec.get("resources", {}))
        tpu = tspec.get("tpu")
        if tpu:
            # chips resolved at DSL time (Task.set_tpu); chips=0 covers IRs
            # compiled before that existed — infer from the accelerator name
            chips = int(tpu.get("chips") or 0)
            if not chips:
                tail = tpu["accelerator"].rsplit("-", 1)[-1]
                chips = chips_in(tail) if "x" in tail else int(tail)
            resources[TPU_RESOURCE] = chips
        container = {
            "name": "main",
            "command": [sys.executable, "-m", "kubeflow_tpu.pipelines.launcher_main", workspace],
            "env": [{"name": "PYTHONPATH", "value": _repo_root()}],
        }
        if resources:
            container["resources"] = {"limits": {k: v for k, v in resources.items()}}
        return {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "namespace": wf["metadata"].get("namespace", "default"),
                "labels": {
                    papi.LABEL_WORKFLOW: wf["metadata"]["name"],
                    papi.LABEL_NODE: tname,
                },
                "ownerReferences": [owner_reference(wf)],
            },
            "spec": {"restartPolicy": "Never", "containers": [container]},
        }

    # ------------------------------------------------------------ completion

    def _check_pod(self, wf: Obj, tname: str, tspec: dict, node: dict, args: dict) -> bool:
        pod = self.api.try_get("Pod", node["podName"], wf["metadata"].get("namespace", "default"))
        if pod is None:
            # pod vanished (evicted/deleted) — treat as a retryable failure
            return self._fail(wf, tname, tspec, node, "pod disappeared")
        phase = pod.get("status", {}).get("phase")
        if phase == "Succeeded":
            outputs_path = os.path.join(node["workspace"], "outputs.json")
            if not os.path.exists(outputs_path):
                return self._fail(wf, tname, tspec, node, "pod succeeded but wrote no outputs.json")
            with open(outputs_path) as f:
                outs = json.load(f)
            return self._complete(wf, tname, tspec, node, outs)
        if phase == "Failed":
            msg = pod.get("status", {}).get("message", "container exited nonzero")
            return self._fail(wf, tname, tspec, node, msg)
        return False

    def _complete(self, wf: Obj, tname: str, tspec: dict, node: dict, outs: dict) -> bool:
        ctx_id = wf["status"]["contextId"]
        artifacts: dict = {}
        for aname, spec in node["stagedOutputArtifacts"].items():
            meta = outs.get("artifactMetadata", {}).get(aname, {})
            aid = self.metadata.put_artifact(spec["type"], spec["uri"], md.LIVE, meta)
            self.metadata.put_attribution(ctx_id, aid)
            artifacts[aname] = {"id": aid, "uri": spec["uri"], "type": spec["type"], "metadata": meta}
        out_params = outs.get("outputParameters", {})
        exec_id = self.metadata.put_execution(
            f"component:{tspec['componentRef'].removeprefix('comp-')}",
            md.COMPLETE,
            fingerprint=node["fingerprint"],
            properties={
                "task": tname,
                "run": wf["metadata"]["name"],
                "outputs": {"parameters": out_params, "artifacts": artifacts},
            },
        )
        self.metadata.put_association(ctx_id, exec_id)
        for aname, art in artifacts.items():
            self.metadata.put_event(exec_id, art["id"], md.OUTPUT, aname)
        for aname, art in (node.get("inputArtifacts") or {}).items():
            if "id" in art:
                self.metadata.put_event(exec_id, art["id"], md.INPUT, aname)
        node.update(
            phase=papi.SUCCEEDED,
            executionId=exec_id,
            outputParameters=out_params,
            outputArtifacts=artifacts,
            cached=False,
        )
        return True

    def _fail(self, wf: Obj, tname: str, tspec: dict, node: dict, msg: str) -> bool:
        max_retries = tspec.get("retries", 0)
        if node["retries"] < max_retries:
            node["retries"] += 1
            node["phase"] = papi.PENDING
            node.pop("podName", None)
            self.recorder.warning(wf, "NodeRetry", f"node {tname}: {msg} (retry {node['retries']}/{max_retries})")
            return True
        node["phase"] = papi.FAILED
        node["message"] = msg
        self.recorder.warning(wf, "NodeFailed", f"node {tname}: {msg}")
        return True


def _fingerprint(executor: dict, params: dict, in_artifacts: dict, out_artifact_defs: dict) -> str:
    """KFP cache key: component spec + resolved inputs (+ output surface)."""
    doc = {
        "source": executor["python"]["source"],
        "functionName": executor["python"]["functionName"],
        "parameters": params,
        "inputArtifacts": {
            a: {"uri": art.get("uri"), "id": art.get("id")} for a, art in sorted(in_artifacts.items())
        },
        "outputs": sorted(out_artifact_defs),
    }
    return hashlib.sha256(json.dumps(doc, sort_keys=True).encode()).hexdigest()


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
