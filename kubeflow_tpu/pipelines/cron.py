"""Minimal 5-field cron matcher for ScheduledWorkflow triggers.

Upstream analogue (UNVERIFIED): KFP's ScheduledWorkflow controller supports
cron + interval triggers (`[U:pipelines/backend/src/crd/controller/
scheduledworkflow]`).  Supported syntax per field (minute hour dom month dow):
``*``, ``*/N``, ``A``, ``A-B``, and comma lists thereof.  dow: 0-6, 0=Sunday.
"""

from __future__ import annotations

import time
from typing import Optional

_RANGES = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 6)]


def _parse_field(field: str, lo: int, hi: int) -> set[int]:
    out: set[int] = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise ValueError(f"bad cron step in {field!r}")
        if part == "*":
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            lo2, hi2 = int(a), int(b)
        else:
            lo2 = hi2 = int(part)
        if not (lo <= lo2 <= hi2 <= hi):
            raise ValueError(f"cron field {field!r} out of range [{lo},{hi}]")
        out.update(range(lo2, hi2 + 1, step))
    return out


def parse(expr: str) -> list[set[int]]:
    fields = expr.split()
    if len(fields) != 5:
        raise ValueError(f"cron expression needs 5 fields, got {expr!r}")
    return [_parse_field(f, lo, hi) for f, (lo, hi) in zip(fields, _RANGES)]


def _matches_fields(fields: list[set[int]], ts: float) -> bool:
    minute, hour, dom, month, dow = fields
    t = time.localtime(ts)
    return (
        t.tm_min in minute
        and t.tm_hour in hour
        and t.tm_mday in dom
        and t.tm_mon in month
        and t.tm_wday in _to_cron_dow(dow)
    )


def matches(expr: str, ts: float) -> bool:
    return _matches_fields(parse(expr), ts)


def _to_cron_dow(dow: set[int]) -> set[int]:
    # struct_time: Monday=0..Sunday=6; cron: Sunday=0..Saturday=6
    return {(d - 1) % 7 for d in dow}


def next_fire(expr: str, after: float, horizon_days: int = 366) -> Optional[float]:
    """Next minute-aligned timestamp strictly after `after` matching the expr."""
    fields = parse(expr)  # parse once; the probe loop is minute-by-minute
    t = int(after // 60 + 1) * 60
    end = after + horizon_days * 86400
    while t <= end:
        if _matches_fields(fields, t):
            return float(t)
        t += 60
    return None
