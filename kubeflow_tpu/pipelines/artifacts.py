"""Artifact object store: the MinIO-equivalent, on the local filesystem.

Upstream analogue (UNVERIFIED, SURVEY.md §2/§3.5): KFP stores step artifacts
in MinIO under ``minio://mlpipeline/artifacts/...``; the launcher uploads
outputs and downloads inputs.  SURVEY.md §2b allows "SQLite + local FS
equivalents" for these external native deps, so this is a bucket/key object
store rooted at a directory, with the URI scheme ``mstore://bucket/key``.
"""

from __future__ import annotations

import os
import shutil

SCHEME = "mstore://"


class ObjectStore:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------------ uris

    def uri(self, bucket: str, key: str) -> str:
        return f"{SCHEME}{bucket}/{key}"

    def _path(self, uri: str) -> str:
        if not uri.startswith(SCHEME):
            raise ValueError(f"not an object-store uri: {uri!r}")
        rel = uri[len(SCHEME):]
        path = os.path.normpath(os.path.join(self.root, rel))
        # commonpath (not a prefix check) so "root-sibling" dirs can't pass
        if os.path.commonpath([path, self.root]) != self.root:
            raise ValueError(f"uri escapes the store root: {uri!r}")
        return path

    # ------------------------------------------------------------------- ops

    def put(self, uri: str, local_path: str) -> str:
        """Upload a file or directory to the store. Returns the uri."""
        dst = self._path(uri)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.isdir(local_path):
            if os.path.exists(dst):
                shutil.rmtree(dst)
            shutil.copytree(local_path, dst)
        else:
            shutil.copy2(local_path, dst)
        return uri

    def get(self, uri: str, local_path: str) -> str:
        """Download to a local path. Returns the local path."""
        src = self._path(uri)
        if not os.path.exists(src):
            raise FileNotFoundError(f"object not found: {uri}")
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        if os.path.isdir(src):
            if os.path.exists(local_path):
                shutil.rmtree(local_path)
            shutil.copytree(src, local_path)
        else:
            shutil.copy2(src, local_path)
        return local_path

    def put_bytes(self, uri: str, data: bytes) -> str:
        dst = self._path(uri)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        with open(dst, "wb") as f:
            f.write(data)
        return uri

    def get_bytes(self, uri: str) -> bytes:
        src = self._path(uri)
        if not os.path.exists(src):
            raise FileNotFoundError(f"object not found: {uri}")
        with open(src, "rb") as f:
            return f.read()

    def get_head(self, uri: str, n: int) -> tuple:
        """First ``n`` bytes + the object's total size — preview without
        pulling a multi-GB artifact into memory (webui run pages)."""
        src = self._path(uri)
        if not os.path.isfile(src):
            raise FileNotFoundError(f"object not found (or not a file): {uri}")
        size = os.path.getsize(src)
        with open(src, "rb") as f:
            return f.read(n), size

    def exists(self, uri: str) -> bool:
        return os.path.exists(self._path(uri))
