"""Step-pod launcher: run one component function inside its pod.

Upstream analogue (UNVERIFIED, SURVEY.md §3.5): KFP v2's launcher container
(`[U:pipelines/backend/src/v2/component/launcher_v2.go]`) — download input
artifacts, execute the user component, upload outputs.  Here the component is
an embedded Python function (lightweight-component style): the source from
the IR is exec'd with the dsl artifact types in scope, inputs are staged from
the object store, outputs are uploaded and reported via ``outputs.json`` in
the node workspace (the controller is the metadata-store writer, not us).

Usage (what the Workflow controller puts in the pod command):
    python -m kubeflow_tpu.pipelines.launcher_main <workspace-dir>
"""

from __future__ import annotations

import json
import os
import sys
import traceback


def run(workspace: str) -> int:
    from kubeflow_tpu.pipelines import dsl
    from kubeflow_tpu.pipelines.artifacts import ObjectStore

    with open(os.path.join(workspace, "task.json")) as f:
        task = json.load(f)
    store = ObjectStore(task["storeRoot"])

    kwargs: dict = dict(task["defaults"])
    kwargs.update(task["parameters"])

    staged_in = os.path.join(workspace, "inputs")
    staged_out = os.path.join(workspace, "outputs")
    os.makedirs(staged_in, exist_ok=True)
    os.makedirs(staged_out, exist_ok=True)

    for aname, art in task["inputArtifacts"].items():
        cls = dsl.ARTIFACT_TYPES.get(art.get("type", "system.Artifact"), dsl.Artifact)
        a = cls(name=aname, uri=art["uri"], metadata=art.get("metadata", {}))
        a.path = store.get(art["uri"], os.path.join(staged_in, aname))
        kwargs[aname] = a

    out_objs: dict = {}
    for aname, art in task["outputArtifacts"].items():
        cls = dsl.ARTIFACT_TYPES.get(art["type"], dsl.Artifact)
        a = cls(name=aname, uri=art["uri"])
        a.path = os.path.join(staged_out, aname)
        out_objs[aname] = a
        kwargs[aname] = a

    # exec the component source with the dsl names lightweight components use
    ns: dict = {
        "dsl": dsl,
        "Input": dsl.Input,
        "Output": dsl.Output,
        "Artifact": dsl.Artifact,
        "Dataset": dsl.Dataset,
        "Model": dsl.Model,
        "Metrics": dsl.Metrics,
    }
    exec(compile(task["source"], f"<component {task['functionName']}>", "exec"), ns)
    fn = ns[task["functionName"]]

    ret = fn(**kwargs)

    outputs: dict = {"outputParameters": {}, "artifactMetadata": {}}
    if "Output" in task["outputParameters"]:
        outputs["outputParameters"]["Output"] = ret
    for aname, a in out_objs.items():
        if os.path.exists(a.path):
            store.put(a.uri, a.path)
        outputs["artifactMetadata"][aname] = a.metadata

    # tmp+os.replace: the workflow controller polls for this file — it
    # must never observe a half-written doc (graftlint atomic-write)
    out_path = os.path.join(workspace, "outputs.json")
    with open(out_path + ".tmp", "w") as f:
        json.dump(outputs, f)
    os.replace(out_path + ".tmp", out_path)
    return 0


def main() -> int:
    if len(sys.argv) != 2:
        print("usage: launcher_main <workspace>", file=sys.stderr)
        return 2
    try:
        return run(sys.argv[1])
    except Exception:
        traceback.print_exc()
        return 1


if __name__ == "__main__":
    sys.exit(main())
