// MLMD-equivalent metadata store core.
//
// Role in the stack (SURVEY.md §2b): the reference's KFP v2 driver talks to
// ML Metadata, a C++ gRPC server backed by SQLite/MySQL.  This is the
// TPU-native rebuild's equivalent native core: a C++ storage engine holding
// artifacts / executions / contexts / events / associations with typed
// indexes and an append-only WAL for crash-safe persistence.  The Python
// client (metadata.py) binds via ctypes (no pybind11 in this image) and owns
// only JSON property (de)serialization — ids, indexing, lineage adjacency,
// durability and thread-safety all live here.
//
// Record wire format (core → Python), little-endian:
//   artifact:  i64 id | u32 state | lp(type) | lp(uri) | lp(props)
//   execution: i64 id | u32 state | lp(type) | lp(fingerprint) | lp(props)
//   context:   i64 id | u32 zero  | lp(type) | lp(name) | lp(props)
//   event:     i64 execution_id | i64 artifact_id | u32 type | lp(path)
// where lp(s) = u32 length + bytes.  The WAL stores one byte of op-tag plus
// the same serialization; replay rebuilds every index.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Artifact {
  int64_t id;
  uint32_t state;
  std::string type, uri, props;
};

struct Execution {
  int64_t id;
  uint32_t state;
  std::string type, fingerprint, props;
};

struct Context {
  int64_t id;
  std::string type, name, props;
};

struct Event {
  int64_t execution_id, artifact_id;
  uint32_t type;  // 0=INPUT 1=OUTPUT
  std::string path;
};

void put_u32(std::string* out, uint32_t v) { out->append(reinterpret_cast<char*>(&v), 4); }
void put_i64(std::string* out, int64_t v) { out->append(reinterpret_cast<char*>(&v), 8); }
void put_lp(std::string* out, const std::string& s) {
  put_u32(out, static_cast<uint32_t>(s.size()));
  out->append(s);
}

struct Reader {
  const char* p;
  const char* end;
  bool ok = true;
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v; std::memcpy(&v, p, 4); p += 4; return v;
  }
  int64_t i64() {
    if (p + 8 > end) { ok = false; return 0; }
    int64_t v; std::memcpy(&v, p, 8); p += 8; return v;
  }
  std::string lp() {
    uint32_t n = u32();
    if (!ok || p + n > end) { ok = false; return ""; }
    std::string s(p, n); p += n; return s;
  }
};

struct Store {
  std::mutex mu;
  std::string wal_path;  // empty → in-memory only
  FILE* wal = nullptr;
  int64_t next_id = 1;

  std::unordered_map<int64_t, Artifact> artifacts;
  std::unordered_map<int64_t, Execution> executions;
  std::unordered_map<int64_t, Context> contexts;
  std::vector<Event> events;

  std::unordered_map<std::string, std::vector<int64_t>> artifacts_by_type;
  std::unordered_map<std::string, std::vector<int64_t>> executions_by_type;
  std::unordered_map<std::string, std::vector<int64_t>> executions_by_fp;
  std::unordered_map<std::string, std::vector<int64_t>> contexts_by_type;
  std::unordered_map<std::string, int64_t> context_by_key;  // type + '\0' + name
  std::unordered_map<int64_t, std::vector<int64_t>> events_by_execution;  // -> event idx
  std::unordered_map<int64_t, std::vector<int64_t>> events_by_artifact;
  std::unordered_map<int64_t, std::vector<int64_t>> execs_by_context;
  std::unordered_map<int64_t, std::vector<int64_t>> artifacts_by_context;

  std::string scratch;  // last query result, drained by mds_read_buffer
};

enum Op : uint8_t {
  OP_ARTIFACT = 1,
  OP_EXECUTION = 2,
  OP_CONTEXT = 3,
  OP_EVENT = 4,
  OP_ASSOCIATION = 5,
  OP_ATTRIBUTION = 6,
};

std::string ser_artifact(const Artifact& a) {
  std::string s;
  put_i64(&s, a.id);
  put_u32(&s, a.state);
  put_lp(&s, a.type);
  put_lp(&s, a.uri);
  put_lp(&s, a.props);
  return s;
}

std::string ser_execution(const Execution& e) {
  std::string s;
  put_i64(&s, e.id);
  put_u32(&s, e.state);
  put_lp(&s, e.type);
  put_lp(&s, e.fingerprint);
  put_lp(&s, e.props);
  return s;
}

std::string ser_context(const Context& c) {
  std::string s;
  put_i64(&s, c.id);
  put_u32(&s, 0);
  put_lp(&s, c.type);
  put_lp(&s, c.name);
  put_lp(&s, c.props);
  return s;
}

std::string ser_event(const Event& e) {
  std::string s;
  put_i64(&s, e.execution_id);
  put_i64(&s, e.artifact_id);
  put_u32(&s, e.type);
  put_lp(&s, e.path);
  return s;
}

void erase_id(std::vector<int64_t>& v, int64_t id) {
  for (size_t i = 0; i < v.size(); ++i) {
    if (v[i] == id) { v.erase(v.begin() + i); return; }
  }
}

// Apply a deserialized op to the in-memory state (used by both the write path
// and WAL replay, so the two can never diverge).
void apply(Store* st, uint8_t op, const std::string& payload) {
  Reader r{payload.data(), payload.data() + payload.size()};
  switch (op) {
    case OP_ARTIFACT: {
      Artifact a;
      a.id = r.i64(); a.state = r.u32(); a.type = r.lp(); a.uri = r.lp(); a.props = r.lp();
      if (!r.ok) return;
      auto it = st->artifacts.find(a.id);
      if (it == st->artifacts.end()) {
        st->artifacts_by_type[a.type].push_back(a.id);
      } else if (it->second.type != a.type) {
        erase_id(st->artifacts_by_type[it->second.type], a.id);
        st->artifacts_by_type[a.type].push_back(a.id);
      }
      if (a.id >= st->next_id) st->next_id = a.id + 1;
      st->artifacts[a.id] = std::move(a);
      break;
    }
    case OP_EXECUTION: {
      Execution e;
      e.id = r.i64(); e.state = r.u32(); e.type = r.lp(); e.fingerprint = r.lp(); e.props = r.lp();
      if (!r.ok) return;
      auto it = st->executions.find(e.id);
      if (it == st->executions.end()) {
        st->executions_by_type[e.type].push_back(e.id);
        if (!e.fingerprint.empty()) st->executions_by_fp[e.fingerprint].push_back(e.id);
      } else {
        if (it->second.type != e.type) {
          erase_id(st->executions_by_type[it->second.type], e.id);
          st->executions_by_type[e.type].push_back(e.id);
        }
        if (it->second.fingerprint != e.fingerprint) {
          if (!it->second.fingerprint.empty())
            erase_id(st->executions_by_fp[it->second.fingerprint], e.id);
          if (!e.fingerprint.empty()) st->executions_by_fp[e.fingerprint].push_back(e.id);
        }
      }
      if (e.id >= st->next_id) st->next_id = e.id + 1;
      st->executions[e.id] = std::move(e);
      break;
    }
    case OP_CONTEXT: {
      Context c;
      c.id = r.i64(); r.u32(); c.type = r.lp(); c.name = r.lp(); c.props = r.lp();
      if (!r.ok) return;
      if (!st->contexts.count(c.id)) st->contexts_by_type[c.type].push_back(c.id);
      st->context_by_key[c.type + '\0' + c.name] = c.id;
      if (c.id >= st->next_id) st->next_id = c.id + 1;
      st->contexts[c.id] = std::move(c);
      break;
    }
    case OP_EVENT: {
      Event e;
      e.execution_id = r.i64(); e.artifact_id = r.i64(); e.type = r.u32(); e.path = r.lp();
      if (!r.ok) return;
      int64_t idx = static_cast<int64_t>(st->events.size());
      st->events_by_execution[e.execution_id].push_back(idx);
      st->events_by_artifact[e.artifact_id].push_back(idx);
      st->events.push_back(std::move(e));
      break;
    }
    case OP_ASSOCIATION: {
      int64_t ctx = r.i64(), exec = r.i64();
      if (!r.ok) return;
      auto& v = st->execs_by_context[ctx];
      bool dup = false;
      for (int64_t id : v) dup = dup || id == exec;
      if (!dup) v.push_back(exec);
      break;
    }
    case OP_ATTRIBUTION: {
      int64_t ctx = r.i64(), art = r.i64();
      if (!r.ok) return;
      auto& v = st->artifacts_by_context[ctx];
      bool dup = false;
      for (int64_t id : v) dup = dup || id == art;
      if (!dup) v.push_back(art);
      break;
    }
  }
}

// WAL record: u8 op | u32 payload_len | payload.  Truncated tails (crash mid
// write) are dropped at replay.
void wal_append(Store* st, uint8_t op, const std::string& payload) {
  if (!st->wal) return;
  uint32_t n = static_cast<uint32_t>(payload.size());
  fwrite(&op, 1, 1, st->wal);
  fwrite(&n, 4, 1, st->wal);
  fwrite(payload.data(), 1, n, st->wal);
  fflush(st->wal);
}

void replay(Store* st) {
  FILE* f = fopen(st->wal_path.c_str(), "rb");
  if (!f) return;
  std::string payload;
  for (;;) {
    uint8_t op;
    uint32_t n;
    if (fread(&op, 1, 1, f) != 1) break;
    if (fread(&n, 4, 1, f) != 1) break;
    payload.resize(n);
    if (n && fread(&payload[0], 1, n, f) != n) break;
    apply(st, op, payload);
  }
  fclose(f);
}

std::string cstr(const char* s) { return s ? std::string(s) : std::string(); }

void list_ids(Store* st, const std::vector<int64_t>* ids) {
  st->scratch.clear();
  if (ids) {
    for (int64_t id : *ids) put_i64(&st->scratch, id);
  }
}

}  // namespace

extern "C" {

void* mds_open(const char* path) {
  auto* st = new Store();
  st->wal_path = cstr(path);
  if (!st->wal_path.empty()) {
    replay(st);
    st->wal = fopen(st->wal_path.c_str(), "ab");
    if (!st->wal) { delete st; return nullptr; }
  }
  return st;
}

void mds_close(void* h) {
  auto* st = static_cast<Store*>(h);
  if (st->wal) fclose(st->wal);
  delete st;
}

int64_t mds_put_artifact(void* h, int64_t id, const char* type, const char* uri,
                         int32_t state, const char* props, int32_t props_len) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  Artifact a;
  a.id = id >= 0 ? id : st->next_id;
  a.state = static_cast<uint32_t>(state);
  a.type = cstr(type);
  a.uri = cstr(uri);
  a.props.assign(props ? props : "", static_cast<size_t>(props_len));
  if (id >= 0 && !st->artifacts.count(id)) return -1;  // update of unknown id
  std::string payload = ser_artifact(a);
  apply(st, OP_ARTIFACT, payload);
  wal_append(st, OP_ARTIFACT, payload);
  return a.id;
}

int64_t mds_put_execution(void* h, int64_t id, const char* type, int32_t state,
                          const char* fingerprint, const char* props, int32_t props_len) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  Execution e;
  e.id = id >= 0 ? id : st->next_id;
  e.state = static_cast<uint32_t>(state);
  e.type = cstr(type);
  e.fingerprint = cstr(fingerprint);
  e.props.assign(props ? props : "", static_cast<size_t>(props_len));
  if (id >= 0 && !st->executions.count(id)) return -1;
  std::string payload = ser_execution(e);
  apply(st, OP_EXECUTION, payload);
  wal_append(st, OP_EXECUTION, payload);
  return e.id;
}

int64_t mds_put_context(void* h, const char* type, const char* name,
                        const char* props, int32_t props_len) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  std::string key = cstr(type) + '\0' + cstr(name);
  Context c;
  auto it = st->context_by_key.find(key);
  c.id = it != st->context_by_key.end() ? it->second : st->next_id;
  c.type = cstr(type);
  c.name = cstr(name);
  c.props.assign(props ? props : "", static_cast<size_t>(props_len));
  std::string payload = ser_context(c);
  apply(st, OP_CONTEXT, payload);
  wal_append(st, OP_CONTEXT, payload);
  return c.id;
}

int32_t mds_put_event(void* h, int64_t execution_id, int64_t artifact_id,
                      int32_t type, const char* path) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  if (!st->executions.count(execution_id) || !st->artifacts.count(artifact_id)) return -1;
  Event e{execution_id, artifact_id, static_cast<uint32_t>(type), cstr(path)};
  std::string payload = ser_event(e);
  apply(st, OP_EVENT, payload);
  wal_append(st, OP_EVENT, payload);
  return 0;
}

int32_t mds_put_association(void* h, int64_t context_id, int64_t execution_id) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  if (!st->contexts.count(context_id) || !st->executions.count(execution_id)) return -1;
  std::string payload;
  put_i64(&payload, context_id);
  put_i64(&payload, execution_id);
  apply(st, OP_ASSOCIATION, payload);
  wal_append(st, OP_ASSOCIATION, payload);
  return 0;
}

int32_t mds_put_attribution(void* h, int64_t context_id, int64_t artifact_id) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  if (!st->contexts.count(context_id) || !st->artifacts.count(artifact_id)) return -1;
  std::string payload;
  put_i64(&payload, context_id);
  put_i64(&payload, artifact_id);
  apply(st, OP_ATTRIBUTION, payload);
  wal_append(st, OP_ATTRIBUTION, payload);
  return 0;
}

// ---- queries: each fills the scratch buffer and returns its length --------

int64_t mds_get_artifact(void* h, int64_t id) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->artifacts.find(id);
  st->scratch = it == st->artifacts.end() ? "" : ser_artifact(it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_get_execution(void* h, int64_t id) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->executions.find(id);
  st->scratch = it == st->executions.end() ? "" : ser_execution(it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_get_context(void* h, int64_t id) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->contexts.find(id);
  st->scratch = it == st->contexts.end() ? "" : ser_context(it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_context_id_by_name(void* h, const char* type, const char* name) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->context_by_key.find(cstr(type) + '\0' + cstr(name));
  return it == st->context_by_key.end() ? -1 : it->second;
}

int64_t mds_artifacts_by_type(void* h, const char* type) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->artifacts_by_type.find(cstr(type));
  list_ids(st, it == st->artifacts_by_type.end() ? nullptr : &it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_executions_by_type(void* h, const char* type) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->executions_by_type.find(cstr(type));
  list_ids(st, it == st->executions_by_type.end() ? nullptr : &it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_contexts_by_type(void* h, const char* type) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->contexts_by_type.find(cstr(type));
  list_ids(st, it == st->contexts_by_type.end() ? nullptr : &it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_executions_by_fingerprint(void* h, const char* fp) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->executions_by_fp.find(cstr(fp));
  list_ids(st, it == st->executions_by_fp.end() ? nullptr : &it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_executions_by_context(void* h, int64_t ctx) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->execs_by_context.find(ctx);
  list_ids(st, it == st->execs_by_context.end() ? nullptr : &it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_artifacts_by_context(void* h, int64_t ctx) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->artifacts_by_context.find(ctx);
  list_ids(st, it == st->artifacts_by_context.end() ? nullptr : &it->second);
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_events_by_execution(void* h, int64_t exec) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  st->scratch.clear();
  auto it = st->events_by_execution.find(exec);
  if (it != st->events_by_execution.end()) {
    for (int64_t idx : it->second) {
      std::string rec = ser_event(st->events[idx]);
      put_u32(&st->scratch, static_cast<uint32_t>(rec.size()));
      st->scratch.append(rec);
    }
  }
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_events_by_artifact(void* h, int64_t art) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  st->scratch.clear();
  auto it = st->events_by_artifact.find(art);
  if (it != st->events_by_artifact.end()) {
    for (int64_t idx : it->second) {
      std::string rec = ser_event(st->events[idx]);
      put_u32(&st->scratch, static_cast<uint32_t>(rec.size()));
      st->scratch.append(rec);
    }
  }
  return static_cast<int64_t>(st->scratch.size());
}

int64_t mds_read_buffer(void* h, char* out, int64_t cap) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  int64_t n = static_cast<int64_t>(st->scratch.size());
  if (n > cap) n = cap;
  std::memcpy(out, st->scratch.data(), static_cast<size_t>(n));
  return n;
}

int64_t mds_count(void* h, int32_t what) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  switch (what) {
    case 0: return static_cast<int64_t>(st->artifacts.size());
    case 1: return static_cast<int64_t>(st->executions.size());
    case 2: return static_cast<int64_t>(st->contexts.size());
    case 3: return static_cast<int64_t>(st->events.size());
  }
  return -1;
}

}  // extern "C"
