"""KFP persistence agent: a dedicated Workflow watcher reporting run state.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "KFP persistence agent" row):
``[U:pipelines/backend/src/agent/persistence/]`` — an informer on Argo
``Workflow`` CRs that calls the API server's ``ReportWorkflow`` RPC so the
run database reflects workflow state without the API server polling Argo.

Round 2 folded this into a ``sync_runs`` ticker inside the service (the
documented single-process deviation); this module restores the upstream
architecture: a separate watch-driven controller whose only job is
Workflow → ReportWorkflow.  Event-driven, not polled — the controller's
watch stream fires exactly when a Workflow's status changes.
"""

from __future__ import annotations

from typing import Optional

from ..core.api import APIServer
from ..core.controller import Request, Result


class PersistenceAgent:
    """Watches Workflow CRs; reports each change to the service's
    ``report_workflow`` (the ReportWorkflow RPC stand-in)."""

    kind = "Workflow"

    def __init__(self, api: APIServer, service):
        self.api = api
        self.service = service

    def reconcile(self, req: Request) -> Optional[Result]:
        wf = self.api.try_get("Workflow", req.name, req.namespace)
        if wf is None:
            return None
        self.service.report_workflow(wf)
        return None
