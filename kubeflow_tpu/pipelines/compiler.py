"""Pipeline compiler: traced DSL → deterministic IR JSON.

Upstream analogue (UNVERIFIED, SURVEY.md §2/§3.5): ``kfp.compiler.Compiler``
compiling the DSL to PipelineSpec IR proto / Argo YAML, golden-tested against
snapshots.  Here the IR is a plain JSON document (sorted keys, stable task
naming) executed by the Workflow controller (workflow.py) — goldens compare
byte-for-byte.

Compile steps:
  1. trace the pipeline function (dsl.Pipeline.trace);
  2. expand ``ParallelFor`` groups — clone the sub-DAG per item, substituting
     ``LoopItem`` references with constants and remapping intra-loop data
     dependencies (nested loops expand recursively, outermost first);
  3. attach runtime conditions (enclosing ``dsl.Condition`` expressions) and
     derive ``dependentTasks`` = explicit ``.after`` + data deps + tasks
     referenced by conditions;
  4. emit components (deduped per component) + executors (embedded function
     source) + the root DAG.
"""

from __future__ import annotations

import copy
import json
from typing import Any, Optional

from . import dsl
from .dsl import (
    Collected,
    ConditionExpr,
    LoopItem,
    LoopItemField,
    Pipeline,
    PipelineParam,
    Task,
    TaskOutput,
    _Group,
)

IR_SCHEMA = "kubeflow-tpu-pipelines/v1"


class CompileError(Exception):
    pass


# ------------------------------------------------------------ loop expansion


def _substitute(value: Any, gid: int, item: Any, clone_map: dict) -> Any:
    """Replace loop refs of group `gid` with `item`, remap cloned task refs."""
    if isinstance(value, LoopItem) and value.group_id == gid:
        return item
    if isinstance(value, LoopItemField) and value.group_id == gid:
        if not isinstance(item, dict) or value.key not in item:
            raise CompileError(f"ParallelFor item {item!r} has no field {value.key!r}")
        return item[value.key]
    if isinstance(value, TaskOutput) and id(value.task) in clone_map:
        return TaskOutput(clone_map[id(value.task)], value.name, value.is_artifact, value.type)
    if isinstance(value, ConditionExpr):
        return ConditionExpr(
            value.op,
            _substitute(value.left, gid, item, clone_map),
            _substitute(value.right, gid, item, clone_map),
        )
    return value


def _clone_task(t: Task, suffix: str, gid: int, item: Any, clone_map: dict) -> Task:
    c = Task(
        f"{t.name}{suffix}",
        t.component,
        {k: _substitute(v, gid, item, clone_map) for k, v in t.inputs.items()},
        tuple(
            _Group(g.kind, g.group_id, condition=_substitute(g.condition, gid, item, clone_map))
            if g.kind == "condition"
            else g
            for g in t.group_path
            if not (g.kind == "loop" and g.group_id == gid)
        ),
    )
    c.display_name = t.display_name if t.display_name != t.name else c.name
    c.resources = dict(t.resources)
    c.tpu = copy.deepcopy(t.tpu)
    c.enable_caching = t.enable_caching
    c.retries = t.retries
    c.dependencies = [
        clone_map.get(id(d), d) for d in t.dependencies
    ]
    return c


def _expand_loops(tasks: list[Task]) -> list[Task]:
    """Expand the first (outermost) STATIC loop group found; recurse until
    none left.  Dynamic groups (items_from) are left in place — the workflow
    controller expands them at runtime."""
    loop: Optional[_Group] = None
    for t in tasks:
        for g in t.group_path:
            if g.kind == "loop" and g.items_from is None:
                loop = g if loop is None or g.group_id < loop.group_id else loop
                break  # outermost in this task's path
    if loop is None:
        return tasks
    inside = [t for t in tasks if any(g is loop for g in t.group_path)]
    inside_ids = {id(t) for t in inside}
    out: list[Task] = []
    clones_by_orig: dict[int, list[Task]] = {id(t): [] for t in inside}
    for t in tasks:
        if id(t) not in inside_ids:
            out.append(t)
            continue
        for i, item in enumerate(loop.items or []):
            # map of already-cloned iteration-i tasks, for ref remapping
            clone_map = {
                oid: clones[i]
                for oid, clones in clones_by_orig.items()
                if len(clones) > i
            }
            c = _clone_task(t, f"-it{i}", loop.group_id, item, clone_map)
            clones_by_orig[id(t)].append(c)
            out.append(c)
    # references from OUTSIDE the loop to a task inside it are ambiguous —
    # catch data inputs, explicit .after() deps, and Condition references
    def _check_fanin(t: Task, ref_name: str) -> None:
        raise CompileError(
            f"task {t.name!r} references {ref_name!r} inside a ParallelFor "
            "from outside the loop; fan-in is not supported"
        )

    for t in out:
        for v in t.inputs.values():
            if isinstance(v, TaskOutput) and id(v.task) in inside_ids:
                _check_fanin(t, v.task.name)
        for d in t.dependencies:
            if id(d) in inside_ids:
                _check_fanin(t, d.name)
        for g in t.group_path:
            if g.kind == "condition" and g.condition is not None:
                for rt in g.condition.referenced_tasks():
                    if id(rt) in inside_ids:
                        _check_fanin(t, rt.name)
    return _expand_loops(out)


# -------------------------------------------------------------- IR emission


def _param_ref(value: Any, dynamic_gids: frozenset = frozenset()) -> dict:
    if isinstance(value, PipelineParam):
        return {"componentInputParameter": value.name}
    if isinstance(value, TaskOutput):
        if value.is_artifact:
            raise CompileError(f"artifact output {value.name!r} passed to a parameter input")
        return {
            "taskOutputParameter": {"producerTask": value.task.name, "outputParameterKey": value.name}
        }
    if isinstance(value, Collected):
        return {"collectedOutput": {
            "producerTask": value.source.task.name,
            "outputParameterKey": value.source.name,
        }}
    if isinstance(value, LoopItem):
        if value.group_id in dynamic_gids:
            return {"loopItem": {"groupId": value.group_id}}
        raise CompileError("loop item escaped expansion (used outside its ParallelFor?)")
    if isinstance(value, LoopItemField):
        if value.group_id in dynamic_gids:
            return {"loopItem": {"groupId": value.group_id, "field": value.key}}
        raise CompileError("loop item escaped expansion (used outside its ParallelFor?)")
    return {"constant": value}


def _expr_ir(e: Any, dynamic_gids: frozenset = frozenset()) -> Any:
    if isinstance(e, ConditionExpr):
        return {"op": e.op, "left": _expr_ir(e.left, dynamic_gids),
                "right": _expr_ir(e.right, dynamic_gids)}
    return _param_ref(e, dynamic_gids)


class Compiler:
    def compile(self, pipeline: Pipeline, output_path: Optional[str] = None) -> dict:
        if not isinstance(pipeline, Pipeline):
            raise TypeError("Compiler.compile takes a @dsl.pipeline-decorated function")
        ctx = pipeline.trace()
        tasks = _expand_loops(ctx.tasks)

        names = [t.name for t in tasks]
        if len(set(names)) != len(names):
            raise CompileError(f"duplicate task names after expansion: {sorted(names)}")

        # dynamic ParallelFor groups survive expansion: validate structure
        # and collect their gids for loopItem IR markers
        dyn_groups: dict[int, _Group] = {}
        for t in tasks:
            dyn_in_path = [g for g in t.group_path
                           if g.kind == "loop" and g.items_from is not None]
            for g in dyn_in_path:
                dyn_groups[g.group_id] = g
            if len(dyn_in_path) > 1:
                raise CompileError(
                    f"task {t.name!r}: dynamic ParallelFors cannot nest "
                    "inside each other (one runtime iterator per task)")
        import re as _re

        for g in dyn_groups.values():
            inside_ids = {id(t) for t in tasks
                          if any(x is g for x in t.group_path)}
            if id(g.items_from.task) in inside_ids:
                raise CompileError(
                    f"dynamic ParallelFor iterates the output of "
                    f"{g.items_from.task.name!r}, which is inside the loop")
            for g2 in dyn_groups.values():
                if g2 is not g and any(x is g2 for x in
                                       g.items_from.task.group_path):
                    raise CompileError(
                        f"dynamic ParallelFor iterates the output of "
                        f"{g.items_from.task.name!r}, which is inside another "
                        "dynamic ParallelFor; fan-in is not supported")
            if g.items_from.task.name not in names:
                # e.g. the producer sat inside an enclosing STATIC loop and
                # was cloned away — the runtime reference would dangle
                raise CompileError(
                    f"dynamic ParallelFor source {g.items_from.task.name!r} "
                    "does not survive loop expansion (was it defined inside "
                    "an enclosing ParallelFor?)")
            for t in tasks:
                if id(t) in inside_ids:
                    continue
                # DATA fan-in (outputs/conditions) is ambiguous — which
                # iteration? — and rejected, matching the static expansion;
                # dsl.Collected is the sanctioned fan-in and plain .after()
                # CONTROL deps gate on the loop's virtual node.
                refs = [v.task.name for v in t.inputs.values()
                        if isinstance(v, TaskOutput) and id(v.task) in inside_ids]
                for gp in t.group_path:
                    if gp.kind == "condition" and gp.condition is not None:
                        refs += [rt.name for rt in gp.condition.referenced_tasks()
                                 if id(rt) in inside_ids]
                if refs:
                    raise CompileError(
                        f"task {t.name!r} references {refs[0]!r} inside a "
                        "dynamic ParallelFor from outside the loop; fan-in "
                        "is not supported (dsl.Collected collects outputs)")
        for t in tasks:
            if not any(g.kind == "loop" and g.items_from is not None
                       for g in t.group_path):
                continue
            # runtime children are named {task}-it{K}: a REAL task with that
            # literal name would alias the child's status-node entry
            pat = _re.compile(_re.escape(t.name) + r"-it\d+$")
            clash = [n for n in names if n != t.name and pat.fullmatch(n)]
            if clash:
                raise CompileError(
                    f"task name {clash[0]!r} collides with runtime children "
                    f"of the dynamic ParallelFor task {t.name!r}")
        def _exprs_contain_collected(e) -> bool:
            if isinstance(e, Collected):
                return True
            if isinstance(e, ConditionExpr):
                return (_exprs_contain_collected(e.left)
                        or _exprs_contain_collected(e.right))
            return False

        for t in tasks:
            for g in t.group_path:
                if (g.kind == "condition" and g.condition is not None
                        and _exprs_contain_collected(g.condition)):
                    # referenced_tasks() doesn't see through Collected, so
                    # the condition would evaluate BEFORE the loop expands
                    # (against an empty list) — reject rather than misfire
                    raise CompileError(
                        f"task {t.name!r}: dsl.Collected cannot be used in a "
                        "dsl.Condition — collect into a task input and gate "
                        "on that task's output instead")
            for pname, value in t.inputs.items():
                if not isinstance(value, Collected):
                    continue
                src = value.source.task
                src_dyn = [g for g in src.group_path
                           if g.kind == "loop" and g.items_from is not None]
                if not src_dyn:
                    raise CompileError(
                        f"task {t.name!r} input {pname!r}: dsl.Collected "
                        f"source {src.name!r} is not inside a dynamic "
                        "ParallelFor — use the output directly")
                if any(g is src_dyn[-1] for g in t.group_path):
                    raise CompileError(
                        f"task {t.name!r} input {pname!r}: dsl.Collected "
                        "must be consumed OUTSIDE the loop it collects "
                        "(inside it, use the loop item / task output)")
                if src.name not in names:
                    # cloned away by an enclosing static loop: the emitted
                    # producerTask would dangle and the run would hang
                    raise CompileError(
                        f"task {t.name!r} input {pname!r}: dsl.Collected "
                        f"source {src.name!r} does not survive loop "
                        "expansion (is it inside an enclosing static "
                        "ParallelFor?)")

        # ExitHandler wiring: every task inside an exit group becomes a
        # dependency of that group's cleanup task, which is flagged so the
        # workflow runs it on ANY terminal dep phase (not just success)
        exit_deps: dict = {}  # exit Task -> set of guarded task names
        for t in tasks:
            for g in t.group_path:
                if g.kind == "exit":
                    if t is g.exit_task:
                        raise CompileError(
                            f"exit task {t.name!r} cannot be created inside its "
                            "own ExitHandler block")
                    exit_deps.setdefault(g.exit_task, set()).add(t.name)
        for et in exit_deps:
            if et not in tasks:
                raise CompileError(
                    f"exit task {et.name!r} is not part of this pipeline")
            # the cleanup runs even when producers FAILED, so a TaskOutput
            # input could be unresolvable at execution time — forbid them
            # (upstream likewise restricts exit-handler inputs)
            for pname, value in et.inputs.items():
                if isinstance(value, (TaskOutput, Collected)):
                    raise CompileError(
                        f"exit task {et.name!r} input {pname!r} references a task "
                        "output; exit handlers run after failures too, so they "
                        "may only take constants or pipeline parameters")
            # same hazard through an enclosing dsl.Condition: an expression
            # over a failed task's output would be unresolvable at cleanup
            for g in et.group_path:
                if g.kind == "condition" and g.condition is not None \
                        and g.condition.referenced_tasks():
                    raise CompileError(
                        f"exit task {et.name!r} sits inside a dsl.Condition that "
                        "references a task output; exit handlers run after "
                        "failures, so such a condition may be unresolvable — "
                        "gate on pipeline parameters only")

        components: dict = {}
        executors: dict = {}
        dag: dict = {}
        for t in tasks:
            spec = t.component.spec
            comp_key = f"comp-{spec.name}"
            if comp_key not in components:
                components[comp_key] = {
                    "executorLabel": f"exec-{spec.name}",
                    "inputDefinitions": {
                        "parameters": {
                            p: {"parameterType": d["type"]} for p, d in spec.input_params.items()
                        },
                        "artifacts": {
                            a: {"schemaTitle": s} for a, s in spec.input_artifacts.items()
                        },
                    },
                    "outputDefinitions": {
                        "parameters": {p: {"parameterType": ty} for p, ty in spec.output_params.items()},
                        "artifacts": {a: {"schemaTitle": s} for a, s in spec.output_artifacts.items()},
                    },
                }
                executors[f"exec-{spec.name}"] = {
                    "python": {
                        "functionName": spec.function_name,
                        "source": spec.source,
                        "defaults": dict(sorted(spec.defaults.items())),
                    }
                }
            deps = {d.name for d in t.dependencies}
            # loopItem markers are legal only for dynamic groups THIS task
            # sits in — an item that escaped its with-block must fail the
            # compile exactly like the static path does
            task_dyn_gids = frozenset(
                g.group_id for g in t.group_path
                if g.kind == "loop" and g.items_from is not None)
            params_ir: dict = {}
            artifacts_ir: dict = {}
            for pname, value in sorted(t.inputs.items()):
                if pname in spec.input_artifacts:
                    if not (isinstance(value, TaskOutput) and value.is_artifact):
                        raise CompileError(
                            f"task {t.name!r} input {pname!r} expects an artifact "
                            f"(another task's Output[...]), got {value!r}"
                        )
                    artifacts_ir[pname] = {
                        "taskOutputArtifact": {
                            "producerTask": value.task.name,
                            "outputArtifactKey": value.name,
                        }
                    }
                    deps.add(value.task.name)
                else:
                    params_ir[pname] = _param_ref(value, task_dyn_gids)
                    if isinstance(value, TaskOutput):
                        deps.add(value.task.name)
                    elif isinstance(value, Collected):
                        # gate on the loop's VIRTUAL node: all iterations
                        # terminal before the collection resolves
                        deps.add(value.source.task.name)
            conditions = []
            for g in t.group_path:
                if g.kind == "condition" and g.condition is not None:
                    conditions.append(_expr_ir(g.condition, task_dyn_gids))
                    for rt in g.condition.referenced_tasks():
                        deps.add(rt.name)
            iterator = None
            for g in t.group_path:
                if g.kind == "loop" and g.items_from is not None:
                    iterator = {"producerTask": g.items_from.task.name,
                                "outputParameterKey": g.items_from.name,
                                "groupId": g.group_id}
                    deps.add(g.items_from.task.name)
            if t in exit_deps:
                deps |= exit_deps[t]
            node: dict = {
                "componentRef": comp_key,
                "displayName": t.display_name,
                "dependentTasks": sorted(deps),
                "inputs": {"parameters": params_ir, "artifacts": artifacts_ir},
                "cachingOptions": {"enableCache": t.enable_caching},
            }
            if t in exit_deps:
                node["isExitHandler"] = True
            if conditions:
                node["conditions"] = conditions
            if iterator is not None:
                if t in exit_deps:
                    raise CompileError(
                        f"exit task {t.name!r} cannot sit inside a dynamic "
                        "ParallelFor (cleanup must run once, after the whole "
                        "fan-out — place the ExitHandler outside the loop)")
                node["iterator"] = iterator
            if t.retries:
                node["retries"] = t.retries
            if t.resources:
                node["resources"] = dict(sorted(t.resources.items()))
            if t.tpu:
                node["tpu"] = t.tpu
            dag[t.name] = node

        ir = {
            "schemaVersion": IR_SCHEMA,
            "pipelineInfo": {"name": pipeline.name, "description": pipeline.description},
            "root": {
                "inputDefinitions": {
                    "parameters": {
                        p: (
                            {"parameterType": ty, "defaultValue": pipeline.defaults[p]}
                            if p in pipeline.defaults
                            else {"parameterType": ty}
                        )
                        for p, ty in pipeline.params.items()
                    }
                },
                "dag": {"tasks": dag},
            },
            "components": components,
            "deploymentSpec": {"executors": executors},
        }
        if output_path:
            # tmp+os.replace: compiled IR is a durable artifact other
            # tooling loads (graftlint atomic-write)
            with open(output_path + ".tmp", "w") as f:
                json.dump(ir, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(output_path + ".tmp", output_path)
        return ir


def compile_to_json(pipeline: Pipeline) -> str:
    return json.dumps(Compiler().compile(pipeline), indent=2, sort_keys=True) + "\n"
