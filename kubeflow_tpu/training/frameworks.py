"""Per-framework job controllers: rendezvous env injection.

Upstream analogue (UNVERIFIED, SURVEY.md §2a/§3.1): each framework controller
overrides ``SetClusterSpec`` — TFJob renders ``TF_CONFIG``, PyTorchJob renders
``MASTER_ADDR``/``RANK``, etc.  The TPU-native centerpiece is
``TPUJobController``: it injects the ``jax.distributed`` coordinator plus
``MEGASCALE_*`` multislice env — the direct analogue of the reference's
``MASTER_ADDR``/``TF_CONFIG`` injection and "the single most important
mechanism to replicate" (SURVEY.md §2c).

In the simulator every host is 127.0.0.1; on a real cluster the same code
would emit the headless-Service DNS names created by the common controller.
"""

from __future__ import annotations

import json
import time

from ..core.api import AlreadyExists, APIServer, Obj, owner_reference
from ..core.controller import Result
from ..scheduler.topology import VARIANTS, chips_in
from .common import JobController


def _host(job: Obj, rtype: str, index: int) -> str:
    """Rendezvous hostname for one replica.

    Default (the simulator, where every pod is a localhost process) is
    127.0.0.1.  ``spec.network.hostMode: dns`` renders the headless-Service
    DNS names a real deployment uses — `{job}-{rtype}-{i}.{ns}.svc.{domain}`,
    matching the per-replica Services the common controller creates
    (common.py `_ensure_service`), so the Service objects are load-bearing
    API surface, not cosmetic parity.
    """
    net = job["spec"].get("network") or {}
    if net.get("hostMode") == "dns":
        ns = job["metadata"].get("namespace", "default")
        domain = net.get("clusterDomain", "cluster.local")
        return f"{job['metadata']['name']}-{rtype.lower()}-{index}.{ns}.svc.{domain}"
    return "127.0.0.1"


class TPUJobController(JobController):
    """TPUJob/JAXJob: jax.distributed over ICI, megascale over DCN."""

    kind = "TPUJob"
    gang_restart = True  # one chip down = whole-slice restart (SURVEY.md §5)

    def num_ports(self, total: int) -> int:
        return 2  # [jax coordinator, megascale coordinator]

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        n = replicas["Worker"]["replicas"]
        tpu = job["spec"].get("tpu") or {}
        num_slices = int(tpu.get("numSlices", 1))
        hosts_per_slice = max(1, n // num_slices)
        env = {
            "JAX_COORDINATOR_ADDRESS": f"{_host(job, rtype, 0)}:{ports[0]}",
            "JAX_NUM_PROCESSES": str(n),
            "JAX_PROCESS_ID": str(index),
            "TPU_WORKER_ID": str(index % hosts_per_slice),
            "TPU_WORKER_HOSTNAMES": ",".join(_host(job, rtype, i) for i in range(n)),
        }
        if tpu:
            variant = VARIANTS[tpu.get("accelerator", "v5e")]
            env["TPU_ACCELERATOR_TYPE"] = variant.name
            env["TPU_TOPOLOGY"] = tpu.get("topology", "2x2")
            env["TPU_CHIPS_PER_HOST"] = str(variant.chips_per_host)
        if num_slices > 1:
            # multislice: data parallel over DCN between slices (SURVEY.md §2c)
            env.update(
                {
                    "MEGASCALE_COORDINATOR_ADDRESS": f"{_host(job, rtype, 0)}:{ports[1]}",
                    "MEGASCALE_NUM_SLICES": str(num_slices),
                    "MEGASCALE_SLICE_ID": str(index // hosts_per_slice),
                }
            )
        profile = job["spec"].get("profile") or {}
        if profile.get("enabled"):
            # first-class XLA profiler surfacing (SURVEY.md §5): workloads
            # pick these up via parallel.profiling.maybe_trace
            env["TPU_PROFILE_DIR"] = profile.get("dir", "/tmp/tpu-profiles")
            env["TPU_PROFILE_STEPS"] = str(profile.get("steps", 5))
        ckpt = job["spec"].get("checkpoint") or {}
        if ckpt.get("dir"):
            # first-class checkpoint/auto-resume (SURVEY.md §5 checkpoint
            # row): runners restore_latest() on start when this is set, so a
            # gang restart resumes from step N instead of step 0
            env["CHECKPOINT_DIR"] = ckpt["dir"]
            env["CHECKPOINT_EVERY"] = str(ckpt.get("everySteps", 1000))
        preset = job["spec"].get("parallelism") or {}
        if preset.get("preset"):
            env["TPU_PARALLELISM_PRESET"] = preset["preset"]
            if preset.get("tensor"):
                env["TPU_TENSOR_PARALLEL"] = str(preset["tensor"])
        return env


class JAXJobController(TPUJobController):
    kind = "JAXJob"


class TFJobController(JobController):
    """TFJob: TF_CONFIG cluster-spec env (PS/Worker/Chief/Evaluator)."""

    kind = "TFJob"

    _ORDER = ("Chief", "Master", "PS", "Worker", "Evaluator")

    def num_ports(self, total: int) -> int:
        return total

    def _cluster(self, job: Obj, replicas: dict) -> dict[str, list[str]]:
        ports = self.ports_of(job)
        cluster: dict[str, list[str]] = {}
        p = 0
        for rtype in self._ORDER:
            if rtype not in replicas:
                continue
            addrs = []
            for i in range(replicas[rtype]["replicas"]):
                addrs.append(f"{_host(job, rtype, i)}:{ports[p]}")
                p += 1
            cluster[rtype.lower()] = addrs
        return cluster

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        tf_config = {
            "cluster": self._cluster(job, replicas),
            "task": {"type": rtype.lower(), "index": index},
        }
        return {"TF_CONFIG": json.dumps(tf_config)}


class PyTorchJobController(JobController):
    """PyTorchJob: MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK (DDP rendezvous).

    On the reference this fronts NCCL; here the same env boots
    ``torch.distributed`` with gloo on localhost, or torch-xla on TPU hosts.
    """

    kind = "PyTorchJob"

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        has_master = "Master" in replicas
        world = sum(r["replicas"] for r in replicas.values())
        if rtype == "Master":
            rank = 0
        else:
            rank = index + (1 if has_master else 0)
        # per-rank host list (rank order: Master then Workers) — the hostfile
        # analogue the C++ transport shim (kubeflow_tpu/transport/) uses to
        # dial its ring neighbor on multi-pod gangs
        hosts = []
        if has_master:
            hosts.append(_host(job, "Master", 0))
        for i in range(replicas.get("Worker", {}).get("replicas", 0)):
            hosts.append(_host(job, "Worker", i))
        env = {
            "MASTER_ADDR": _host(job, "Master" if has_master else "Worker", 0),
            "MASTER_PORT": str(ports[0]),
            "WORLD_SIZE": str(world),
            "RANK": str(rank),
            "LOCAL_RANK": "0",
            "TRANSPORT_HOSTS": ",".join(hosts),
        }
        elastic = job["spec"].get("elasticPolicy") or {}
        if elastic:
            # torchrun-style rendezvous bounds (upstream ElasticPolicy surface)
            env["PET_MIN_REPLICAS"] = str(elastic.get("minReplicas", 1))
            env["PET_MAX_REPLICAS"] = str(elastic.get("maxReplicas", world))
            env["PET_RDZV_ENDPOINT"] = f"{env['MASTER_ADDR']}:{env['MASTER_PORT']}"
        return env

    def absorb_failure(self, job: Obj, status: dict, rtype: str, index: int,
                       pod: Obj, rc) -> bool:
        """ElasticPolicy: a dead Worker shrinks the world instead of failing
        the job, down to minReplicas (upstream: torchrun re-rendezvous)."""
        elastic = job["spec"].get("elasticPolicy") or {}
        if not elastic or rtype != "Worker":
            return False
        current = self.effective_replicas(job)["Worker"]["replicas"]
        floor = max(1, int(elastic.get("minReplicas", 1)))
        if current - 1 < floor:
            return False
        status.setdefault("elasticReplicas", {})["Worker"] = current - 1
        status["lastElasticShrink"] = time.time()
        self.recorder.warning(
            job, "JobScaledDown",
            f"elastic: Worker[{index}] exit {rc}; world {current} -> {current - 1} (min {floor})",
        )
        return True

    def maybe_grow(self, job: Obj, status: dict):
        """Elastic scale-UP (SURVEY.md §5 failure row: ElasticPolicy + HPA):
        after a cooldown since the last shrink, re-expand one worker at a
        time back toward the spec count (capped by maxReplicas) — the
        simulator's stand-in for HPA-driven growth when capacity returns."""
        elastic = job["spec"].get("elasticPolicy") or {}
        shrunk = (status.get("elasticReplicas") or {}).get("Worker")
        # growth is opt-in (upstream: HPA attached to the elastic job)
        if not elastic.get("scaleUp") or shrunk is None:
            return None
        desired = job["spec"]["replicaSpecs"]["Worker"].get("replicas", 1)
        ceiling = min(desired, int(elastic.get("maxReplicas", desired)))
        if shrunk >= ceiling:
            return None
        cooldown = float(elastic.get("scaleUpCooldownSeconds", 1.0))
        since = time.time() - float(status.get("lastElasticShrink", 0))
        if since < cooldown:
            return Result(requeue_after=cooldown - since + 0.05)
        grown = shrunk + 1
        if grown >= ceiling and ceiling == desired:
            # fully recovered: drop the override entirely
            status.pop("elasticReplicas", None)
        else:
            # maxReplicas < spec count: the override must PERSIST at the
            # ceiling or effective_replicas would jump back to the spec count
            status["elasticReplicas"]["Worker"] = min(grown, ceiling)
        status["lastElasticShrink"] = time.time()  # pace successive grows
        self.recorder.normal(
            job, "JobScaledUp", f"elastic: world {shrunk} -> {grown} (ceiling {ceiling})"
        )
        return Result(requeue_after=0.05)


class MPIJobController(JobController):
    """MPIJob: launcher-runs-mpirun semantics.

    Upstream (SURVEY.md §2a MPIJob row): the controller renders a hostfile
    ConfigMap mounted into the Launcher pod; the launcher execs ``mpirun``
    against the Workers; job success is launcher success.  Here the hostfile
    ConfigMap is a real object the kubelet renders to a file under
    ``POD_VOLUME_ROOT`` (referenced via k8s ``$(VAR)`` env expansion), and
    the ip:port dial list for the simulator's transport shim rides MPI_HOSTS.
    """

    kind = "MPIJob"

    HOSTFILE_MOUNT = "/etc/mpi"

    def num_ports(self, total: int) -> int:
        return total

    def _hostfile_name(self, job: Obj) -> str:
        return f"{job['metadata']['name']}-hostfile"

    def prepare(self, job: Obj, replicas: dict) -> None:
        """Ensure the hostfile ConfigMap (upstream: one per MPIJob)."""
        name = job["metadata"]["name"]
        n_workers = replicas.get("Worker", {}).get("replicas", 0)
        slots = int((job["spec"].get("mpiImplementation") or {}).get("slotsPerWorker", 1)) \
            if isinstance(job["spec"].get("mpiImplementation"), dict) else \
            int(job["spec"].get("slotsPerWorker", 1))
        hostfile = "\n".join(
            f"{self.pod_name(job, 'Worker', i)} slots={slots}" for i in range(n_workers)
        )
        ns = job["metadata"].get("namespace", "default")
        existing = self.api.try_get("ConfigMap", self._hostfile_name(job), ns)
        if existing is None:
            try:
                self.api.create({
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {
                        "name": self._hostfile_name(job),
                        "namespace": ns,
                        "ownerReferences": [owner_reference(job)],
                    },
                    "data": {"hostfile": hostfile},
                })
            except AlreadyExists:
                pass
        elif existing.get("data", {}).get("hostfile") != hostfile:
            # worker count changed (scale): re-render, don't serve stale hosts
            existing["data"] = {"hostfile": hostfile}
            self.api.update(existing)

    def mutate_pod(self, pod: Obj, job: Obj, rtype: str, index: int) -> None:
        if rtype != "Launcher":
            return
        pod["spec"].setdefault("volumes", []).append(
            {"name": "mpi-hostfile", "configMap": {"name": self._hostfile_name(job)}}
        )
        c = pod["spec"]["containers"][0]
        c.setdefault("volumeMounts", []).append(
            {"name": "mpi-hostfile", "mountPath": self.HOSTFILE_MOUNT}
        )

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        n_workers = replicas.get("Worker", {}).get("replicas", 0)
        hosts = [f"{_host(job, 'Worker', i)}:{ports[i]}" for i in range(n_workers)]
        env = {
            "MPI_HOSTS": ",".join(hosts),
            "MPI_NUM_WORKERS": str(n_workers),
        }
        if rtype == "Launcher":
            # k8s dependent-env expansion: the kubelet substitutes $(...)
            hostfile = f"$(POD_VOLUME_ROOT){self.HOSTFILE_MOUNT}/hostfile"
            env["OMPI_MCA_orte_default_hostfile"] = hostfile
            env["MPI_HOSTFILE"] = hostfile
        if rtype == "Worker":
            env["MPI_WORKER_ID"] = str(index)
            env["MPI_WORKER_PORT"] = str(ports[index])
        return env


class MXJobController(JobController):
    """MXJob: DMLC parameter-server rendezvous (scheduler/server/worker)."""

    kind = "MXJob"

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        env = {
            "DMLC_PS_ROOT_URI": _host(job, "Scheduler", 0),
            "DMLC_PS_ROOT_PORT": str(ports[0]),
            "DMLC_NUM_SERVER": str(replicas.get("Server", {}).get("replicas", 0)),
            "DMLC_NUM_WORKER": str(replicas.get("Worker", {}).get("replicas", 0)),
            "DMLC_ROLE": rtype.lower(),
        }
        if rtype == "Worker":
            env["DMLC_WORKER_ID"] = str(index)
        return env


class PaddleJobController(JobController):
    """PaddleJob: collective-mode trainer endpoints rendezvous."""

    kind = "PaddleJob"

    def num_ports(self, total: int) -> int:
        return total

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        has_master = "Master" in replicas
        n_workers = replicas.get("Worker", {}).get("replicas", 0)
        endpoints = [f"{_host(job, 'Worker', i)}:{ports[i]}" for i in range(n_workers)]
        rank = 0 if rtype == "Master" else index
        env = {
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_TRAINERS_NUM": str(n_workers),
            "PADDLE_TRAINER_ID": str(rank),
            "TRAINING_ROLE": "TRAINER",
        }
        if rtype == "Worker":
            env["PADDLE_CURRENT_ENDPOINT"] = endpoints[index]
        if has_master:
            env["PADDLE_MASTER"] = f"{_host(job, 'Master', 0)}:{ports[n_workers] if len(ports) > n_workers else ports[0]}"
        return env


class XGBoostJobController(JobController):
    """XGBoostJob: rabit/dmlc tracker env."""

    kind = "XGBoostJob"

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        world = sum(r["replicas"] for r in replicas.values())
        rank = 0 if rtype == "Master" else index + (1 if "Master" in replicas else 0)
        return {
            "DMLC_TRACKER_URI": _host(job, "Master", 0),
            "DMLC_TRACKER_PORT": str(ports[0]),
            "DMLC_NUM_WORKER": str(world),
            "DMLC_TASK_ID": str(rank),
        }


ALL_CONTROLLERS = (
    TPUJobController,
    JAXJobController,
    TFJobController,
    PyTorchJobController,
    MPIJobController,
    MXJobController,
    PaddleJobController,
    XGBoostJobController,
)


def install(api: APIServer, manager) -> list[JobController]:
    """Register CRDs and attach all training controllers to a Manager."""
    from . import api as tapi

    tapi.register(api)
    out = []
    for cls in ALL_CONTROLLERS:
        ctrl = cls(api)
        manager.add(ctrl, owns=("Pod",))
        out.append(ctrl)
    return out
