"""Per-framework job controllers: rendezvous env injection.

Upstream analogue (UNVERIFIED, SURVEY.md §2a/§3.1): each framework controller
overrides ``SetClusterSpec`` — TFJob renders ``TF_CONFIG``, PyTorchJob renders
``MASTER_ADDR``/``RANK``, etc.  The TPU-native centerpiece is
``TPUJobController``: it injects the ``jax.distributed`` coordinator plus
``MEGASCALE_*`` multislice env — the direct analogue of the reference's
``MASTER_ADDR``/``TF_CONFIG`` injection and "the single most important
mechanism to replicate" (SURVEY.md §2c).

In the simulator every host is 127.0.0.1; on a real cluster the same code
would emit the headless-Service DNS names created by the common controller.
"""

from __future__ import annotations

import json

from ..core.api import APIServer, Obj
from ..scheduler.topology import VARIANTS, chips_in
from .common import JobController


def _host(job: Obj, rtype: str, index: int) -> str:
    # simulator address; real deployment: f"{job}-{rtype}-{i}.{ns}.svc"
    return "127.0.0.1"


class TPUJobController(JobController):
    """TPUJob/JAXJob: jax.distributed over ICI, megascale over DCN."""

    kind = "TPUJob"

    def num_ports(self, total: int) -> int:
        return 2  # [jax coordinator, megascale coordinator]

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        n = replicas["Worker"]["replicas"]
        tpu = job["spec"].get("tpu") or {}
        num_slices = int(tpu.get("numSlices", 1))
        hosts_per_slice = max(1, n // num_slices)
        env = {
            "JAX_COORDINATOR_ADDRESS": f"{_host(job, rtype, 0)}:{ports[0]}",
            "JAX_NUM_PROCESSES": str(n),
            "JAX_PROCESS_ID": str(index),
            "TPU_WORKER_ID": str(index % hosts_per_slice),
            "TPU_WORKER_HOSTNAMES": ",".join(_host(job, rtype, i) for i in range(n)),
        }
        if tpu:
            variant = VARIANTS[tpu.get("accelerator", "v5e")]
            env["TPU_ACCELERATOR_TYPE"] = variant.name
            env["TPU_TOPOLOGY"] = tpu.get("topology", "2x2")
            env["TPU_CHIPS_PER_HOST"] = str(variant.chips_per_host)
        if num_slices > 1:
            # multislice: data parallel over DCN between slices (SURVEY.md §2c)
            env.update(
                {
                    "MEGASCALE_COORDINATOR_ADDRESS": f"{_host(job, rtype, 0)}:{ports[1]}",
                    "MEGASCALE_NUM_SLICES": str(num_slices),
                    "MEGASCALE_SLICE_ID": str(index // hosts_per_slice),
                }
            )
        profile = job["spec"].get("profile") or {}
        if profile.get("enabled"):
            # first-class XLA profiler surfacing (SURVEY.md §5): workloads
            # pick these up via parallel.profiling.maybe_trace
            env["TPU_PROFILE_DIR"] = profile.get("dir", "/tmp/tpu-profiles")
            env["TPU_PROFILE_STEPS"] = str(profile.get("steps", 5))
        preset = job["spec"].get("parallelism") or {}
        if preset.get("preset"):
            env["TPU_PARALLELISM_PRESET"] = preset["preset"]
            if preset.get("tensor"):
                env["TPU_TENSOR_PARALLEL"] = str(preset["tensor"])
        return env


class JAXJobController(TPUJobController):
    kind = "JAXJob"


class TFJobController(JobController):
    """TFJob: TF_CONFIG cluster-spec env (PS/Worker/Chief/Evaluator)."""

    kind = "TFJob"

    _ORDER = ("Chief", "Master", "PS", "Worker", "Evaluator")

    def num_ports(self, total: int) -> int:
        return total

    def _cluster(self, job: Obj, replicas: dict) -> dict[str, list[str]]:
        ports = self.ports_of(job)
        cluster: dict[str, list[str]] = {}
        p = 0
        for rtype in self._ORDER:
            if rtype not in replicas:
                continue
            addrs = []
            for i in range(replicas[rtype]["replicas"]):
                addrs.append(f"{_host(job, rtype, i)}:{ports[p]}")
                p += 1
            cluster[rtype.lower()] = addrs
        return cluster

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        tf_config = {
            "cluster": self._cluster(job, replicas),
            "task": {"type": rtype.lower(), "index": index},
        }
        return {"TF_CONFIG": json.dumps(tf_config)}


class PyTorchJobController(JobController):
    """PyTorchJob: MASTER_ADDR/MASTER_PORT/WORLD_SIZE/RANK (DDP rendezvous).

    On the reference this fronts NCCL; here the same env boots
    ``torch.distributed`` with gloo on localhost, or torch-xla on TPU hosts.
    """

    kind = "PyTorchJob"

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        has_master = "Master" in replicas
        world = sum(r["replicas"] for r in replicas.values())
        if rtype == "Master":
            rank = 0
        else:
            rank = index + (1 if has_master else 0)
        # per-rank host list (rank order: Master then Workers) — the hostfile
        # analogue the C++ transport shim (kubeflow_tpu/transport/) uses to
        # dial its ring neighbor on multi-pod gangs
        hosts = []
        if has_master:
            hosts.append(_host(job, "Master", 0))
        for i in range(replicas.get("Worker", {}).get("replicas", 0)):
            hosts.append(_host(job, "Worker", i))
        env = {
            "MASTER_ADDR": _host(job, "Master" if has_master else "Worker", 0),
            "MASTER_PORT": str(ports[0]),
            "WORLD_SIZE": str(world),
            "RANK": str(rank),
            "LOCAL_RANK": "0",
            "TRANSPORT_HOSTS": ",".join(hosts),
        }
        elastic = job["spec"].get("elasticPolicy") or {}
        if elastic:
            # torchrun-style rendezvous bounds (upstream ElasticPolicy surface)
            env["PET_MIN_REPLICAS"] = str(elastic.get("minReplicas", 1))
            env["PET_MAX_REPLICAS"] = str(elastic.get("maxReplicas", world))
            env["PET_RDZV_ENDPOINT"] = f"{env['MASTER_ADDR']}:{env['MASTER_PORT']}"
        return env

    def absorb_failure(self, job: Obj, status: dict, rtype: str, index: int,
                       pod: Obj, rc) -> bool:
        """ElasticPolicy: a dead Worker shrinks the world instead of failing
        the job, down to minReplicas (upstream: torchrun re-rendezvous)."""
        elastic = job["spec"].get("elasticPolicy") or {}
        if not elastic or rtype != "Worker":
            return False
        current = self.effective_replicas(job)["Worker"]["replicas"]
        floor = max(1, int(elastic.get("minReplicas", 1)))
        if current - 1 < floor:
            return False
        status.setdefault("elasticReplicas", {})["Worker"] = current - 1
        self.recorder.warning(
            job, "JobScaledDown",
            f"elastic: Worker[{index}] exit {rc}; world {current} -> {current - 1} (min {floor})",
        )
        return True


class MPIJobController(JobController):
    """MPIJob: launcher + workers; hostfile-style env for the launcher."""

    kind = "MPIJob"

    def num_ports(self, total: int) -> int:
        return total

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        n_workers = replicas.get("Worker", {}).get("replicas", 0)
        hosts = [f"{_host(job, 'Worker', i)}:{ports[i]}" for i in range(n_workers)]
        env = {
            "OMPI_MCA_orte_default_hostfile_contents": "\n".join(hosts),
            "MPI_HOSTS": ",".join(hosts),
            "MPI_NUM_WORKERS": str(n_workers),
        }
        if rtype == "Worker":
            env["MPI_WORKER_ID"] = str(index)
            env["MPI_WORKER_PORT"] = str(ports[index])
        return env


class XGBoostJobController(JobController):
    """XGBoostJob: rabit/dmlc tracker env."""

    kind = "XGBoostJob"

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        ports = self.ports_of(job)
        world = sum(r["replicas"] for r in replicas.values())
        rank = 0 if rtype == "Master" else index + (1 if "Master" in replicas else 0)
        return {
            "DMLC_TRACKER_URI": _host(job, "Master", 0),
            "DMLC_TRACKER_PORT": str(ports[0]),
            "DMLC_NUM_WORKER": str(world),
            "DMLC_TASK_ID": str(rank),
        }


ALL_CONTROLLERS = (
    TPUJobController,
    JAXJobController,
    TFJobController,
    PyTorchJobController,
    MPIJobController,
    XGBoostJobController,
)


def install(api: APIServer, manager) -> list[JobController]:
    """Register CRDs and attach all training controllers to a Manager."""
    from . import api as tapi

    tapi.register(api)
    out = []
    for cls in ALL_CONTROLLERS:
        ctrl = cls(api)
        manager.add(ctrl, owns=("Pod",))
        out.append(ctrl)
    return out
