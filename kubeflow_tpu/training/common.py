"""Shared JobController: the reconcile engine behind every training job kind.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Training operator: common
JobController"): ``JobController.ReconcileJobs/ReconcilePods/
ReconcileServices`` in training-operator's ``pkg/controller.v1/common``.

Reconcile contract (framework subclasses override the hooks at the bottom):
  1. terminal jobs: apply cleanPodPolicy, honor ttlSecondsAfterFinished;
  2. allocate rendezvous ports once (persisted as an annotation so the
     reconcile is idempotent);
  3. ensure the gang PodGroup (minMember = total replicas, all-or-nothing);
  4. create missing pods with framework rendezvous env injected
     (``set_cluster_spec`` — the TF_CONFIG / MASTER_ADDR / jax.distributed
     analogue, SURVEY.md §3.1) + a headless Service per replica;
  5. restart policy: ExitCode treats exit codes >= 128 (signal/preemption)
     as retryable (pod recreated, Restarting condition) and 1–127 as
     permanent; Never fails the job; Always/OnFailure restart in place via
     the kubelet.  ``backoffLimit`` caps total controller-driven recreations;
  6. aggregate replicaStatuses + Created/Running/Restarting/Succeeded/Failed
     conditions (success policy is a framework hook).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from ..core.api import APIServer, AlreadyExists, NotFound, Obj, owner_reference
from ..core.conditions import has_condition, set_condition
from ..core.controller import Request, Result
from ..core.events import EventRecorder
from ..scheduler.topology import (
    ACCELERATOR_LABEL,
    POD_GROUP_LABEL,
    TOPOLOGY_LABEL,
    TPU_RESOURCE,
    VARIANTS,
    chips_in,
)
from ..core.metrics import JOBS_CREATED, JOBS_FAILED, JOBS_RESTARTED, JOBS_SUCCESSFUL
from ..utils.net import find_free_ports
from . import api as tapi

PORTS_ANNOTATION = "training.kubeflow.org/rendezvous-ports"

RETRYABLE_EXIT_MIN = 128  # signal-terminated / preempted → retryable


class JobController:
    kind: str = "TPUJob"
    # slice-level failure domain (SURVEY.md §5): retryable failure of ANY
    # replica restarts the whole gang (one backoff count). True for the
    # jax.distributed kinds — survivors of a partial failure are wedged in
    # collectives and rendezvous needs every process to rejoin. Framework
    # kinds with per-rank recovery semantics (TF PS, torch elastic) keep
    # per-pod restarts.
    gang_restart: bool = False

    def __init__(self, api: APIServer):
        self.api = api
        self.recorder = EventRecorder(api, f"{self.kind.lower()}-controller")

    # ------------------------------------------------------------- reconcile

    def reconcile(self, req: Request) -> Optional[Result]:
        job = self.api.try_get(self.kind, req.name, req.namespace)
        if job is None:
            return None
        status = job.setdefault("status", {})

        if has_condition(status, tapi.SUCCEEDED) or has_condition(status, tapi.FAILED):
            return self._reconcile_terminal(job)

        if not has_condition(status, tapi.CREATED):
            set_condition(status, tapi.CREATED, "True", f"{self.kind}Created", "job accepted")
            self.recorder.normal(job, "JobCreated", f"{self.kind} {req.name} created")
            JOBS_CREATED.inc(kind=self.kind)
            job = self.api.update_status(job)
            status = job["status"]

        replicas = self.effective_replicas(job)
        total = sum(r["replicas"] for r in replicas.values())

        job = self._ensure_ports(job, replicas)
        status = job["status"]  # rebind: _ensure_ports returns a fresh copy
        self._ensure_pod_group(job, total)
        self.prepare(job, replicas)

        pods_by_type: dict[str, list[Optional[Obj]]] = {}
        for rtype, rspec in replicas.items():
            pods_by_type[rtype] = [
                self.api.try_get("Pod", self.pod_name(job, rtype, i), req.namespace)
                for i in range(rspec["replicas"])
            ]

        # --- restart / failure policy before creating anything.
        # Two passes: detect any PERMANENT failure first so we never delete
        # sibling pods (and their logs) of a job that is about to fail.
        backoff_limit = job["spec"].get("runPolicy", {}).get("backoffLimit", 3)
        failure_msg = None
        retryable_failures: list[tuple[str, int, Obj, Optional[int]]] = []
        for rtype, rspec in replicas.items():
            policy = rspec.get("restartPolicy", "Never")
            for i, pod in enumerate(pods_by_type[rtype]):
                if pod is None or pod.get("status", {}).get("phase") != "Failed":
                    continue
                rc = _exit_code(pod)
                retryable = policy in ("Always", "OnFailure") or (
                    policy == "ExitCode" and rc is not None and rc >= RETRYABLE_EXIT_MIN
                )
                if not retryable:
                    if self.absorb_failure(job, status, rtype, i, pod, rc):
                        # elastic shrink: the framework accepted the loss;
                        # drop the pod, requeue to re-render the world
                        self.api.try_delete("Pod", pod["metadata"]["name"], req.namespace)
                        self.api.update_status(job)
                        return Result(requeue_after=0.05)
                    failure_msg = f"{rtype}[{i}] failed with exit code {rc} (permanent)"
                    break
                # gang mode: the current cycle's failures all collapse into ONE
                # restart that hasn't been charged yet, so only PAST restarts
                # count against the budget (backoffLimit=N allows N restarts,
                # matching the per-pod accounting)
                pending = 0 if self.gang_restart else len(retryable_failures)
                if self._restarts(status) + pending >= backoff_limit:
                    failure_msg = f"{rtype}[{i}] exceeded backoffLimit ({backoff_limit})"
                    break
                retryable_failures.append((rtype, i, pod, rc))
            if failure_msg:
                break

        restarted = False
        if failure_msg is None and retryable_failures:
            if self.gang_restart:
                # slice-level failure domain (SURVEY.md §5): one worker down
                # restarts the WHOLE gang — survivors are blocked in XLA
                # collectives and a fresh jax.distributed rendezvous needs
                # every process to rejoin; workers resume from the newest
                # checkpoint (spec.checkpoint), so this costs steps-since-
                # save, not the run. One gang restart = one backoff count.
                rtype0, i0, _, rc0 = retryable_failures[0]
                for rtype, rspec in replicas.items():
                    for i, pod in enumerate(pods_by_type[rtype]):
                        if pod is not None:
                            self.api.try_delete("Pod", pod["metadata"]["name"], req.namespace)
                            pods_by_type[rtype][i] = None
                status["restartCount"] = self._restarts(status) + 1
                restarted = True
                JOBS_RESTARTED.inc(kind=self.kind)
                self.recorder.warning(
                    job, "SliceRestarting",
                    f"{rtype0}[{i0}] exit {rc0}: retryable, restarting the whole gang"
                )
            else:
                for rtype, i, pod, rc in retryable_failures:
                    self.api.try_delete("Pod", pod["metadata"]["name"], req.namespace)
                    pods_by_type[rtype][i] = None
                    status["restartCount"] = self._restarts(status) + 1
                    restarted = True
                    JOBS_RESTARTED.inc(kind=self.kind)
                    self.recorder.warning(
                        job, "JobRestarting", f"{rtype}[{i}] exit {rc}: retryable, recreating"
                    )

        if failure_msg:
            set_condition(status, tapi.FAILED, "True", "JobFailed", failure_msg)
            set_condition(status, tapi.RUNNING, "False", "JobFailed", failure_msg)
            status["completionTime"] = time.time()
            self.recorder.warning(job, "JobFailed", failure_msg)
            JOBS_FAILED.inc(kind=self.kind)
            self.api.update_status(job)
            return self._reconcile_terminal(job)

        if restarted:
            set_condition(status, tapi.RESTARTING, "True", "JobRestarting", "recreating failed pods")
            self.api.update_status(job)
            return Result(requeue_after=0.05)

        # --- create missing pods + services; delete pods beyond the desired
        # count (elastic scale-down / spec.replicas shrink)
        for rtype, rspec in replicas.items():
            for i, pod in enumerate(pods_by_type[rtype]):
                if pod is None:
                    created = self._create_pod(job, rtype, i, rspec, replicas)
                    pods_by_type[rtype][i] = created
                    self._ensure_service(job, created)
            i = rspec["replicas"]
            while self.api.try_delete("Pod", self.pod_name(job, rtype, i), req.namespace):
                self.recorder.normal(job, "JobScaledDown", f"removed {rtype}[{i}]")
                i += 1

        # --- aggregate status
        replica_statuses = {}
        any_active = False
        for rtype, pods in pods_by_type.items():
            phases = [((p or {}).get("status") or {}).get("phase", "Pending") for p in pods]
            replica_statuses[rtype] = {
                "active": sum(ph in ("Pending", "Running") for ph in phases),
                "succeeded": sum(ph == "Succeeded" for ph in phases),
                "failed": sum(ph == "Failed" for ph in phases),
            }
            any_active = any_active or any(ph == "Running" for ph in phases)
        status["replicaStatuses"] = replica_statuses

        if self.is_succeeded(job, pods_by_type):
            set_condition(status, tapi.SUCCEEDED, "True", "JobSucceeded", "job completed")
            set_condition(status, tapi.RUNNING, "False", "JobSucceeded", "job completed")
            status["completionTime"] = time.time()
            self.recorder.normal(job, "JobSucceeded", f"{self.kind} {req.name} succeeded")
            JOBS_SUCCESSFUL.inc(kind=self.kind)
            self.api.update_status(job)
            return self._reconcile_terminal(self.api.get(self.kind, req.name, req.namespace))

        if any_active and not has_condition(status, tapi.RUNNING):
            set_condition(status, tapi.RUNNING, "True", f"{self.kind}Running", "pods running")
            self.recorder.normal(job, "JobRunning", "all pods scheduled")
        grow = self.maybe_grow(job, status)
        self.api.update_status(job)
        return grow

    # ------------------------------------------------------------- terminal

    def _reconcile_terminal(self, job: Obj) -> Optional[Result]:
        ns = job["metadata"].get("namespace", "default")
        policy = job["spec"].get("runPolicy", {}).get("cleanPodPolicy", "None")
        if policy != "None":
            for pod in self._job_pods(job):
                phase = pod.get("status", {}).get("phase", "Pending")
                if policy == "All" or (policy == "Running" and phase in ("Pending", "Running")):
                    self.api.try_delete("Pod", pod["metadata"]["name"], ns)
        ttl = job["spec"].get("runPolicy", {}).get("ttlSecondsAfterFinished")
        if ttl is not None:
            done_at = job.get("status", {}).get("completionTime") or time.time()
            remaining = done_at + ttl - time.time()
            if remaining <= 0:
                self.api.try_delete(self.kind, job["metadata"]["name"], ns)
                return None
            return Result(requeue_after=remaining)
        return None

    # --------------------------------------------------------------- helpers

    def _restarts(self, status: dict) -> int:
        return int(status.get("restartCount", 0))

    def _job_pods(self, job: Obj) -> list[Obj]:
        return self.api.list(
            "Pod",
            namespace=job["metadata"].get("namespace", "default"),
            label_selector={tapi.LABEL_JOB_NAME: job["metadata"]["name"]},
        )

    def pod_name(self, job: Obj, rtype: str, index: int) -> str:
        return f"{job['metadata']['name']}-{rtype.lower()}-{index}"

    def _ensure_ports(self, job: Obj, replicas: dict) -> Obj:
        if PORTS_ANNOTATION in job["metadata"].get("annotations", {}):
            return job
        total = sum(r["replicas"] for r in replicas.values())
        ports = find_free_ports(self.num_ports(total))
        job["metadata"].setdefault("annotations", {})[PORTS_ANNOTATION] = json.dumps(ports)
        return self.api.update(job)

    def ports_of(self, job: Obj) -> list[int]:
        return json.loads(job["metadata"]["annotations"][PORTS_ANNOTATION])

    def _ensure_pod_group(self, job: Obj, total: int) -> None:
        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        min_member = total
        sched = job["spec"].get("runPolicy", {}).get("schedulingPolicy") or {}
        elastic = job["spec"].get("elasticPolicy") or {}
        if "minAvailable" in sched:
            min_member = sched["minAvailable"]
        elif "minReplicas" in elastic:
            # elastic jobs gang only on the floor: the job is viable at min
            min_member = min(total, elastic["minReplicas"])
        try:
            self.api.create(
                {
                    "apiVersion": "scheduling.kubeflow.org/v1",
                    "kind": "PodGroup",
                    "metadata": {
                        "name": name,
                        "namespace": ns,
                        "ownerReferences": [owner_reference(job)],
                    },
                    "spec": {"minMember": min_member, "queue": sched.get("queue", "default")},
                }
            )
        except AlreadyExists:
            pass

    def _create_pod(self, job: Obj, rtype: str, index: int, rspec: dict, replicas: dict) -> Obj:
        import copy

        name = job["metadata"]["name"]
        ns = job["metadata"].get("namespace", "default")
        template = copy.deepcopy(rspec["template"])
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": self.pod_name(job, rtype, index),
                "namespace": ns,
                "labels": {
                    tapi.LABEL_JOB_NAME: name,
                    tapi.LABEL_REPLICA_TYPE: rtype.lower(),
                    tapi.LABEL_REPLICA_INDEX: str(index),
                    POD_GROUP_LABEL: name,
                    **template.get("metadata", {}).get("labels", {}),
                },
                "ownerReferences": [owner_reference(job)],
            },
            "spec": copy.deepcopy(template["spec"]),
        }
        spec = pod["spec"]
        spec.setdefault("restartPolicy", self._pod_restart_policy(rspec))
        if spec.get("nodeSelector") is None:
            spec.pop("nodeSelector", None)

        tpu = job["spec"].get("tpu")
        if tpu:
            self._apply_tpu_placement(spec, tpu)
            num_slices = int(tpu.get("numSlices", 1))
            if num_slices > 1:
                variant = VARIANTS[tpu.get("accelerator", "v5e")]
                hosts_per_slice = max(1, chips_in(tpu.get("topology", "2x2")) // variant.chips_per_host)
                from ..scheduler.topology import SLICE_GROUP_LABEL

                pod["metadata"]["labels"][SLICE_GROUP_LABEL] = f"{name}-s{index // hosts_per_slice}"

        # rendezvous env goes into EVERY container (sidecars need it too);
        # template entries win on name collision, valueFrom entries pass through
        cluster_env = self.set_cluster_spec(job, rtype, index, replicas)
        for c in spec["containers"]:
            existing = c.get("env", [])
            names = {e["name"] for e in existing}
            c["env"] = existing + [
                {"name": k, "value": str(v)} for k, v in cluster_env.items() if k not in names
            ]
        self.mutate_pod(pod, job, rtype, index)
        return self.api.create(pod)

    def _pod_restart_policy(self, rspec: dict) -> str:
        policy = rspec.get("restartPolicy", "Never")
        # ExitCode is controller-driven recreation; at pod level it is Never.
        return "Never" if policy == "ExitCode" else policy

    def _apply_tpu_placement(self, spec: dict, tpu: dict) -> None:
        variant = VARIANTS[tpu.get("accelerator", "v5e")]
        sel = spec.setdefault("nodeSelector", {})
        sel.setdefault(ACCELERATOR_LABEL, variant.name)
        sel.setdefault(TOPOLOGY_LABEL, tpu.get("topology", "2x2"))
        res = spec["containers"][0].setdefault("resources", {})
        req = res.setdefault("requests", {})
        req.setdefault(TPU_RESOURCE, min(variant.chips_per_host, chips_in(tpu.get("topology", "2x2"))))

    def _ensure_service(self, job: Obj, pod: Obj) -> None:
        """Headless Service per replica — upstream gives each replica stable
        DNS; in the simulator every address is 127.0.0.1 but the objects keep
        API parity for tests and UIs."""
        try:
            self.api.create(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": {
                        "name": pod["metadata"]["name"],
                        "namespace": pod["metadata"].get("namespace", "default"),
                        "ownerReferences": [owner_reference(job)],
                    },
                    "spec": {
                        "clusterIP": "None",
                        "selector": {
                            tapi.LABEL_JOB_NAME: job["metadata"]["name"],
                            tapi.LABEL_REPLICA_INDEX: pod["metadata"]["labels"][tapi.LABEL_REPLICA_INDEX],
                            tapi.LABEL_REPLICA_TYPE: pod["metadata"]["labels"][tapi.LABEL_REPLICA_TYPE],
                        },
                    },
                }
            )
        except AlreadyExists:
            pass

    # ------------------------------------------------------ framework hooks

    def effective_replicas(self, job: Obj) -> dict[str, dict]:
        """Expanded replicaSpecs. TPU jobs with spec.tpu get replicas derived
        from slice topology: one worker pod per TPU host per slice."""
        replicas = {k: dict(v) for k, v in (job["spec"].get("replicaSpecs") or {}).items()}
        tpu = job["spec"].get("tpu")
        if tpu and "Worker" in replicas:
            variant = VARIANTS[tpu.get("accelerator", "v5e")]
            hosts = max(1, chips_in(tpu.get("topology", "2x2")) // variant.chips_per_host)
            replicas["Worker"]["replicas"] = hosts * tpu.get("numSlices", 1)
        # elastic shrink recorded by absorb_failure overrides the spec count
        for rtype, n in ((job.get("status") or {}).get("elasticReplicas") or {}).items():
            if rtype in replicas:
                replicas[rtype]["replicas"] = n
        return replicas

    def num_ports(self, total_replicas: int) -> int:
        return 1  # coordinator only; frameworks with per-task ports override

    def prepare(self, job: Obj, replicas: dict) -> None:
        """Hook: ensure framework-owned side objects (e.g. the MPIJob
        hostfile ConfigMap) before any pod is created."""

    def mutate_pod(self, pod: Obj, job: Obj, rtype: str, index: int) -> None:
        """Hook: framework-specific pod surgery (volumes, mounts) before
        the pod is POSTed."""

    def maybe_grow(self, job: Obj, status: dict) -> Optional[Result]:
        """Hook: elastic scale-UP decision, called at the end of a healthy
        reconcile.  Return a Result to requeue for future growth."""
        return None

    def set_cluster_spec(self, job: Obj, rtype: str, index: int, replicas: dict) -> dict[str, str]:
        """Rendezvous env for one replica. Framework-specific."""
        return {}

    def absorb_failure(self, job: Obj, status: dict, rtype: str, index: int,
                       pod: Obj, rc: Optional[int]) -> bool:
        """Hook: return True to absorb a permanent pod failure instead of
        failing the job (elastic frameworks shrink the replica set here)."""
        return False

    def is_succeeded(self, job: Obj, pods_by_type: dict[str, list[Optional[Obj]]]) -> bool:
        """Default success policy: the chief replica type fully succeeded;
        if absent, all pods succeeded."""
        chief = tapi.JOB_KINDS[self.kind]["chief"]
        target = pods_by_type.get(chief)
        if not target:
            target = [p for pods in pods_by_type.values() for p in pods]
        return bool(target) and all(
            p is not None and p.get("status", {}).get("phase") == "Succeeded" for p in target
        )


def _exit_code(pod: Obj) -> Optional[int]:
    for cs in pod.get("status", {}).get("containerStatuses", []):
        term = cs.get("state", {}).get("terminated")
        if term is not None and "exitCode" in term:
            return int(term["exitCode"])
    return None
