"""Training job CRD types: TPUJob/JAXJob, TFJob, PyTorchJob, MPIJob, XGBoostJob.

Upstream analogue (UNVERIFIED, SURVEY.md §2a): training-operator's
``kubeflow.org/v1`` API — ``ReplicaSpec{replicas, template, restartPolicy}``,
``RunPolicy{cleanPodPolicy, ttlSecondsAfterFinished, backoffLimit,
schedulingPolicy}``, ``JobCondition{Created,Running,Restarting,Succeeded,
Failed}``.  The TPU-first addition is ``spec.tpu`` on every job kind:
``{accelerator, topology, numSlices}`` drives topology-aware gang scheduling
and rendezvous env injection (the reference's NCCL/TF_CONFIG wiring mapped to
ICI/DCN, SURVEY.md §2c).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core.api import APIServer, CRD, Invalid, Obj

GROUP = "kubeflow.org"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"

# job kinds and their replica-type conventions
JOB_KINDS = {
    "TPUJob": {"types": ("Worker",), "chief": "Worker"},
    "JAXJob": {"types": ("Worker",), "chief": "Worker"},
    "TFJob": {"types": ("Chief", "Master", "PS", "Worker", "Evaluator"), "chief": "Chief"},
    "PyTorchJob": {"types": ("Master", "Worker"), "chief": "Master"},
    "MPIJob": {"types": ("Launcher", "Worker"), "chief": "Launcher"},
    "MXJob": {"types": ("Scheduler", "Server", "Worker"), "chief": "Worker"},
    "PaddleJob": {"types": ("Master", "Worker"), "chief": "Worker"},
    "XGBoostJob": {"types": ("Master", "Worker"), "chief": "Master"},
}

# condition types (upstream JobCondition)
CREATED = "Created"
RUNNING = "Running"
RESTARTING = "Restarting"
SUCCEEDED = "Succeeded"
FAILED = "Failed"

# labels (group/domain mirrors upstream's training.kubeflow.org labels)
LABEL_JOB_NAME = "training.kubeflow.org/job-name"
LABEL_REPLICA_TYPE = "training.kubeflow.org/replica-type"
LABEL_REPLICA_INDEX = "training.kubeflow.org/replica-index"

RESTART_POLICIES = ("Always", "OnFailure", "Never", "ExitCode")
CLEAN_POD_POLICIES = ("Running", "All", "None")


def _validate_job(obj: Obj) -> None:
    kind = obj["kind"]
    spec = obj.get("spec", {})
    replica_specs = spec.get("replicaSpecs") or {}
    if not replica_specs:
        raise Invalid(f"{kind}: spec.replicaSpecs required (spec.tpu only sizes them)")
    allowed = JOB_KINDS[kind]["types"]
    for rtype, rspec in replica_specs.items():
        if rtype not in allowed:
            raise Invalid(f"{kind}: unknown replica type {rtype!r}; allowed {allowed}")
        rp = rspec.get("restartPolicy", "Never")
        if rp not in RESTART_POLICIES:
            raise Invalid(f"{kind}: bad restartPolicy {rp!r}")
        if "template" not in rspec:
            raise Invalid(f"{kind}: replicaSpecs[{rtype}].template required")
        # single-coordinator replica types (upstream enforces one master)
        if rtype in ("Master", "Chief", "Launcher", "Scheduler") and rspec.get("replicas", 1) > 1:
            raise Invalid(f"{kind}: replicaSpecs[{rtype}].replicas must be 1")
    run = spec.get("runPolicy", {})
    cpp = run.get("cleanPodPolicy", "None")
    if cpp not in CLEAN_POD_POLICIES:
        raise Invalid(f"{kind}: bad cleanPodPolicy {cpp!r}")


def _default_job(obj: Obj) -> None:
    spec = obj.setdefault("spec", {})
    run = spec.setdefault("runPolicy", {})
    run.setdefault("cleanPodPolicy", "None")
    run.setdefault("backoffLimit", 3)
    for rspec in (spec.get("replicaSpecs") or {}).values():
        rspec.setdefault("replicas", 1)
        rspec.setdefault("restartPolicy", "Never")


def register(api: APIServer) -> None:
    for kind in JOB_KINDS:
        api.register_crd(
            CRD(
                group=GROUP,
                version=VERSION,
                kind=kind,
                plural=kind.lower() + "s",
                validator=_validate_job,
                defaulter=_default_job,
            )
        )


# ------------------------------------------------------------ typed builders

@dataclass
class TPUSpec:
    """TPU-first extension: request a slice by shape, not by pod arithmetic."""

    accelerator: str = "v5e"
    topology: str = "2x2"
    num_slices: int = 1

    def to_obj(self) -> dict:
        return {
            "accelerator": self.accelerator,
            "topology": self.topology,
            "numSlices": self.num_slices,
        }


@dataclass
class ReplicaSpec:
    replicas: int = 1
    restart_policy: str = "Never"
    command: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    image: str = "local"
    resources: dict = field(default_factory=dict)
    node_selector: dict = field(default_factory=dict)

    def to_obj(self) -> dict:
        return {
            "replicas": self.replicas,
            "restartPolicy": self.restart_policy,
            "template": {
                "spec": {
                    "nodeSelector": dict(self.node_selector) or None,
                    "containers": [
                        {
                            "name": "main",
                            "image": self.image,
                            "command": list(self.command),
                            "env": [{"name": k, "value": v} for k, v in self.env.items()],
                            "resources": dict(self.resources),
                        }
                    ],
                }
            },
        }


def job(
    kind: str,
    name: str,
    replica_specs: dict[str, ReplicaSpec],
    namespace: str = "default",
    tpu: Optional[TPUSpec] = None,
    run_policy: Optional[dict] = None,
) -> Obj:
    return {
        "apiVersion": API_VERSION,
        "kind": kind,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "replicaSpecs": {t: r.to_obj() for t, r in replica_specs.items()},
            **({"tpu": tpu.to_obj()} if tpu else {}),
            **({"runPolicy": run_policy} if run_policy else {}),
        },
    }
