"""Training Python SDK.

Upstream analogue (UNVERIFIED, SURVEY.md §2a): ``kubeflow.training.
TrainingClient`` — create/get/wait/logs/delete for every job kind.  Katib
trials and Pipelines steps drive jobs through this client, exactly as
upstream's do (SURVEY.md §3.3).
"""

from __future__ import annotations

from typing import Optional

from ..core.api import Obj
from ..core.cluster import Cluster
from ..core.conditions import has_condition
from . import api as tapi


class TrainingClient:
    def __init__(self, cluster: Cluster, namespace: str = "default"):
        self.cluster = cluster
        self.namespace = namespace

    def create_job(self, job: Obj) -> Obj:
        job.setdefault("metadata", {}).setdefault("namespace", self.namespace)
        return self.cluster.api.create(job)

    def get_job(self, kind: str, name: str) -> Optional[Obj]:
        return self.cluster.api.try_get(kind, name, self.namespace)

    def job_condition(self, kind: str, name: str) -> Optional[str]:
        job = self.get_job(kind, name)
        if job is None:
            return None
        status = job.get("status", {})
        for cond in (tapi.SUCCEEDED, tapi.FAILED, tapi.RUNNING, tapi.CREATED):
            if has_condition(status, cond):
                return cond
        return None

    def wait_for_job(self, kind: str, name: str, timeout: float = 300.0) -> str:
        """Block (driving the cluster) until the job is terminal."""
        def done() -> bool:
            return self.job_condition(kind, name) in (tapi.SUCCEEDED, tapi.FAILED)

        self.cluster.wait_for(done, timeout=timeout)
        cond = self.job_condition(kind, name)
        if cond not in (tapi.SUCCEEDED, tapi.FAILED):
            raise TimeoutError(f"{kind} {name} not terminal after {timeout}s (at {cond})")
        return cond

    def get_job_logs(self, kind: str, name: str) -> dict[str, str]:
        pods = self.cluster.api.list(
            "Pod", namespace=self.namespace, label_selector={tapi.LABEL_JOB_NAME: name}
        )
        return {
            p["metadata"]["name"]: self.cluster.logs(p["metadata"]["name"], self.namespace)
            for p in pods
        }

    def delete_job(self, kind: str, name: str) -> None:
        self.cluster.api.try_delete(kind, name, self.namespace)

    def scale_job(self, kind: str, name: str, replicas: int, rtype: str = "Worker") -> Obj:
        """Elastic scale (upstream: HPA on ElasticPolicy): clamp to the
        job's [minReplicas, maxReplicas] and update the spec; the controller
        converges pods to the new world size."""
        job = self.cluster.api.get(kind, name, self.namespace)
        elastic = job["spec"].get("elasticPolicy") or {}
        lo = int(elastic.get("minReplicas", 1))
        hi = int(elastic.get("maxReplicas", replicas))
        replicas = max(lo, min(int(replicas), hi))
        job["spec"]["replicaSpecs"][rtype]["replicas"] = replicas
        # an explicit scale supersedes any elastic shrink recorded in status
        if (job.get("status") or {}).get("elasticReplicas", {}).get(rtype) is not None:
            job["status"]["elasticReplicas"].pop(rtype)
        return self.cluster.api.update(job)
