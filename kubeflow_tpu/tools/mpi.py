"""Build-on-first-use for the vendored minimal ``mpirun`` (mpirun.cc).

The image ships no MPI runtime, so the MPIJob launcher-exec contract could
never run against a real binary (the test skipped through r4).  This
builds the vendored local mpirun into ``<pkg>/tools/bin/mpirun`` (hash-
gated like the other native cores) so tests — and users without OpenMPI —
can put that directory on PATH.
"""

from __future__ import annotations

import hashlib
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "mpirun.cc")
_BIN_DIR = os.path.join(_DIR, "bin")


def ensure_mpirun() -> str:
    """Compile mpirun.cc if its source changed; return the bin dir to put
    on PATH.  Concurrent builders race safely via atomic rename."""
    with open(_SRC, "rb") as f:
        tag = hashlib.md5(f.read()).hexdigest()[:10]
    exe = os.path.join(_BIN_DIR, "mpirun")
    stamp = os.path.join(_BIN_DIR, f".mpirun.{tag}")
    if not (os.path.exists(exe) and os.path.exists(stamp)):
        os.makedirs(_BIN_DIR, exist_ok=True)
        tmp = exe + f".tmp{os.getpid()}"
        subprocess.run(["g++", "-O2", "-std=c++17", "-Wall", _SRC, "-o", tmp],
                       check=True, capture_output=True)
        os.replace(tmp, exe)
        with open(stamp, "w"):
            pass
    return _BIN_DIR
