"""Vendored native developer/contract tools (built on first use)."""
