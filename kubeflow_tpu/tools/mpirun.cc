// Minimal local `mpirun`: the OpenMPI launcher CLI contract, single-host.
//
// Upstream analogue (UNVERIFIED, SURVEY.md §2a MPIJob row): the `mpirun`
// binary the MPIJob Launcher pod execs.  This image ships no MPI runtime
// (the real test skipped through r4 — VERDICT r4 "What's missing" #5), so
// this vendored tool implements the subset of the CLI the MPIJob
// controller's generated command line and hostfile actually exercise:
//
//   mpirun [--allow-run-as-root] [--oversubscribe] [-np N]
//          [--host h:s[,h:s...]] [--hostfile|-hostfile PATH]
//          [-x ENV[=VAL]] CMD ARGS...
//
// Semantics: every rank is forked LOCALLY (this box cannot ssh to pod
// "hosts"; slots are summed from --host/--hostfile, -np wins when given),
// with the env OpenMPI programs read: OMPI_COMM_WORLD_RANK / _SIZE /
// _LOCAL_RANK / _LOCAL_SIZE plus PMI_RANK / PMI_SIZE.  Exit status is the
// first non-zero child status.  It is a CONTRACT-TEST tool: it proves the
// controller's launcher command line, hostfile rendering, and env plumbing
// drive a real mpirun-shaped executable — it performs no MPI communication
// itself (ranks use their own transport, e.g. jax.distributed or the
// transport shim, exactly as TPU-native MPI-style jobs should).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>
#include <fstream>
#include <sstream>
#include <sys/wait.h>
#include <unistd.h>

static int slots_of(const std::string& spec) {
  // "host" or "host:slots"
  auto c = spec.find(':');
  if (c == std::string::npos) return 1;
  int s = atoi(spec.c_str() + c + 1);
  return s > 0 ? s : 1;
}

int main(int argc, char** argv) {
  int np = -1;
  int hosted_slots = 0;
  std::vector<char*> cmd;
  std::vector<std::string> exports;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--allow-run-as-root" || a == "--oversubscribe" ||
        a == "--bind-to" || a == "--map-by") {
      if ((a == "--bind-to" || a == "--map-by") && i + 1 < argc) i++;
      continue;  // accepted, no-op locally
    } else if ((a == "-np" || a == "--np" || a == "-n") && i + 1 < argc) {
      np = atoi(argv[++i]);
    } else if ((a == "--host" || a == "-H") && i + 1 < argc) {
      std::stringstream ss(argv[++i]);
      std::string h;
      while (std::getline(ss, h, ',')) hosted_slots += slots_of(h);
    } else if ((a == "--hostfile" || a == "-hostfile" || a == "--machinefile") &&
               i + 1 < argc) {
      std::ifstream f(argv[++i]);
      if (!f) { fprintf(stderr, "mpirun: cannot read hostfile %s\n", argv[i]); return 1; }
      std::string line;
      while (std::getline(f, line)) {
        // "host slots=N" (OpenMPI hostfile format) or bare "host"
        if (line.empty() || line[0] == '#') continue;
        auto sl = line.find("slots=");
        hosted_slots += sl == std::string::npos ? 1 : std::max(1, atoi(line.c_str() + sl + 6));
      }
    } else if (a == "-x" && i + 1 < argc) {
      exports.push_back(argv[++i]);  // ENV or ENV=VAL
    } else if (a == "--help") {
      printf("minimal local mpirun (kubeflow_tpu vendored contract tool)\n");
      return 0;
    } else {
      for (int j = i; j < argc; j++) cmd.push_back(argv[j]);
      break;
    }
  }
  if (cmd.empty()) { fprintf(stderr, "mpirun: no command given\n"); return 1; }
  cmd.push_back(nullptr);
  int size = np > 0 ? np : (hosted_slots > 0 ? hosted_slots : 1);

  for (const auto& e : exports) {
    auto eq = e.find('=');
    if (eq != std::string::npos)
      setenv(e.substr(0, eq).c_str(), e.c_str() + eq + 1, 1);
    // bare "-x NAME" re-exports the launcher's value: already inherited
  }

  std::vector<pid_t> kids;
  for (int r = 0; r < size; r++) {
    pid_t pid = fork();
    if (pid < 0) { perror("mpirun: fork"); return 1; }
    if (pid == 0) {
      char buf[32];
      snprintf(buf, sizeof buf, "%d", r);
      setenv("OMPI_COMM_WORLD_RANK", buf, 1);
      setenv("OMPI_COMM_WORLD_LOCAL_RANK", buf, 1);
      setenv("PMI_RANK", buf, 1);
      snprintf(buf, sizeof buf, "%d", size);
      setenv("OMPI_COMM_WORLD_SIZE", buf, 1);
      setenv("OMPI_COMM_WORLD_LOCAL_SIZE", buf, 1);
      setenv("PMI_SIZE", buf, 1);
      execvp(cmd[0], cmd.data());
      fprintf(stderr, "mpirun: exec %s: %s\n", cmd[0], strerror(errno));
      _exit(127);
    }
    kids.push_back(pid);
  }
  int rc = 0;
  for (pid_t pid : kids) {
    int st = 0;
    waitpid(pid, &st, 0);
    int code = WIFEXITED(st) ? WEXITSTATUS(st) : 128 + WTERMSIG(st);
    if (code != 0 && rc == 0) rc = code;
  }
  return rc;
}
