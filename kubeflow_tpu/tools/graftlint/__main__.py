"""CLI: ``python -m kubeflow_tpu.tools.graftlint [paths...]``.

Exit status 0 = clean (or everything suppressed/baselined), 1 =
unsuppressed findings, 2 = a target failed to parse.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import analyze, default_baseline_path, default_root, \
    write_baseline
from .rules import ALL_RULES, rule_table


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="repo-native static analysis for serving invariants")
    ap.add_argument("paths", nargs="*",
                    help="files to analyze (default: all of kubeflow_tpu/)")
    ap.add_argument("--root", default=None,
                    help="package root to discover under")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {default_baseline_path()})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show grandfathered findings)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current unsuppressed findings as the new "
                         "baseline and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, invariant, history in rule_table():
            print(f"{name}\n  invariant: {invariant}\n  history: {history}")
        return 0

    rules = None
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",")}
        unknown = wanted - {r.name for r in ALL_RULES}
        if unknown:
            print(f"unknown rules: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [cls() for cls in ALL_RULES if cls.name in wanted]

    report = analyze(
        paths=args.paths or None,
        root=args.root or default_root(),
        rules=rules,
        baseline_path=args.baseline,
        use_baseline=not (args.no_baseline or args.write_baseline))

    if args.write_baseline:
        path = args.baseline or default_baseline_path()
        write_baseline(path, report.unsuppressed)
        print(f"baseline: {len(report.unsuppressed)} entries -> {path}")
        return 0

    if args.as_json:
        json.dump(report.to_dict(), sys.stdout, indent=1)
        print()
    else:
        for f in report.unsuppressed:
            print(f.render())
        for rel, msg in report.parse_errors:
            print(f"{rel}: PARSE ERROR: {msg}")
        counts = report.to_dict()["counts"]
        print(f"graftlint: {report.files_analyzed} files, "
              f"{counts['unsuppressed']} findings "
              f"({counts['suppressed']} suppressed, "
              f"{counts['baselined']} baselined) "
              f"in {report.elapsed_s:.2f}s")
    if report.parse_errors:
        return 2
    return 1 if report.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
