"""lock-discipline and release-guarantee: the concurrency rules.

Both are annotation-driven (@GuardedBy-style): the code declares its
discipline inline and the checker enforces the declaration everywhere in
the module — including call sites written three PRs later by someone who
never read the declaring class.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import (Context, Finding, Rule, SourceFile, _ACQ_RE, _GUARDED_RE,
                    _HOLDS_RE, _REL_RE, expr_text)


class LockDisciplineRule(Rule):
    name = "lock-discipline"
    invariant = ("attributes declared '# guarded-by: <lock>' are only "
                 "touched inside 'with <owner>.<lock>:' (or in functions "
                 "annotated '# graftlint: holds-lock=<lock>')")
    history = ("PR 13 second pass: the ingress evidence snapshot iterated "
               "shared proxy state without state.lock and raced pod-churn "
               "mutation exactly when churn was the story")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        # 1. collect guarded declarations: self.<attr> = ...  # guarded-by: L
        guarded: dict[str, str] = {}
        decl_fn: dict[str, ast.AST] = {}  # attr -> declaring function
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    lock = sf.directive_near(node.lineno, _GUARDED_RE)
                    if lock:
                        guarded[t.attr] = lock
                        decl_fn[t.attr] = sf.enclosing_function(node)
        if not guarded:
            return
        # imported module names are not instances — 'json.loads' must not
        # match a guarded attr that happens to be called 'loads'
        imported: set = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imported.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    imported.add(a.asname or a.name)
        # 2. every access to a guarded attr must be lock-covered
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Attribute):
                continue
            lock = guarded.get(node.attr)
            if lock is None:
                continue
            recv = expr_text(node.value)
            if recv is None or recv.split(".")[0] in imported:
                continue
            fn = sf.enclosing_function(node)
            # the declaring function (the constructor) initializes before
            # the object is shared — exempt
            if fn is not None and fn is decl_fn.get(node.attr):
                continue
            if self._covered(sf, node, recv, lock):
                continue
            yield Finding(
                self.name, sf.rel, node.lineno,
                f"'{recv}.{node.attr}' is guarded-by '{lock}' but accessed "
                f"outside 'with {recv}.{lock}:' (annotate the enclosing "
                f"function '# graftlint: holds-lock={lock}' if every "
                f"caller holds it)")

    @staticmethod
    def _covered(sf: SourceFile, node, recv: str, lock: str) -> bool:
        want = f"{recv}.{lock}"
        for a in sf.ancestors(node):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    t = expr_text(item.context_expr)
                    if t == want or t == lock:
                        return True
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held = sf.directive_near(a.lineno, _HOLDS_RE)
                if held == lock:
                    return True
                # a decorated def: the directive may sit on the first
                # decorator line instead of the def line
                for dec in a.decorator_list:
                    if sf.directive_near(dec.lineno, _HOLDS_RE) == lock:
                        return True
        return False


class ReleaseGuaranteeRule(Rule):
    name = "release-guarantee"
    invariant = ("a statement annotated '# graftlint: acquires=<token>' "
                 "has a matching '# graftlint: releases=<token>' inside a "
                 "'finally:' block of the same function")
    history = ("PR 14 review: an exception in the pre-relay span leaked "
               "the admitted inflight slot forever — leaked slots ratchet "
               "the AIMD count until the service sheds 100%")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        acquires: list[tuple[int, str]] = []
        releases: list[tuple[int, str]] = []
        for ln, c in sf.comments.items():
            target = ln if ln in sf.code_lines else ln + 1
            m = _ACQ_RE.search(c)
            if m:
                acquires.append((target, m.group(1)))
            m = _REL_RE.search(c)
            if m:
                releases.append((target, m.group(1)))
        if not acquires and not releases:
            return
        # index releases by (function chain, token); require finally
        # context.  A release in a closure DEFINED in the acquiring
        # function counts (the background-thread handoff shape), so the
        # whole enclosing-function chain is credited.
        safe: set[tuple[int, str]] = set()
        unsafe_fn: dict[tuple[int, str], int] = {}
        for ln, token in releases:
            node = self._node_at(sf, ln)
            if node is None:
                continue
            chain = [0]
            cur = sf.enclosing_function(node)
            while cur is not None:
                chain.append(id(cur))
                cur = sf.enclosing_function(cur)
            for fid in chain:
                if self._in_finally(sf, node):
                    safe.add((fid, token))
                else:
                    unsafe_fn[(fid, token)] = ln
        for ln, token in acquires:
            node = self._node_at(sf, ln)
            fn = sf.enclosing_function(node) if node is not None else None
            fid = id(fn) if fn is not None else 0
            if (fid, token) in safe:
                continue
            if (fid, token) in unsafe_fn:
                yield Finding(
                    self.name, sf.rel, ln,
                    f"'{token}' is released at line "
                    f"{unsafe_fn[(fid, token)]} but not from a 'finally:' "
                    f"block — an exception between acquire and release "
                    f"leaks it")
            else:
                yield Finding(
                    self.name, sf.rel, ln,
                    f"acquire of '{token}' has no "
                    f"'# graftlint: releases={token}' in a 'finally:' "
                    f"block of the same function")

    @staticmethod
    def _node_at(sf: SourceFile, line: int):
        best = None
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.stmt) and node.lineno <= line \
                    and (node.end_lineno or node.lineno) >= line:
                if best is None or node.lineno >= best.lineno:
                    best = node
        return best

    @staticmethod
    def _in_finally(sf: SourceFile, node) -> bool:
        # parents are immediate, so at each Try ancestor the previous hop
        # is one of its direct body/handler/finalbody statements
        child = node
        for a in sf.ancestors(node):
            if isinstance(a, ast.Try) and any(child is s
                                              for s in a.finalbody):
                return True
            child = a
        return False
