"""atomic-write: durable JSON/state writes use tmp + os.replace.

A write is "durable JSON" when the function both opens a path in a
write mode and serializes JSON into it (json.dump / f.write(json.dumps)),
or the path literal names a .json file.  The sanctioned discipline is
the kvstore one: write to a sibling tmp path, fsync-free os.replace.
Writes whose path expression already mentions tmp are the first half of
that discipline and pass.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Context, Finding, Rule, SourceFile, expr_text


class AtomicWriteRule(Rule):
    name = "atomic-write"
    invariant = ("durable JSON/state writes go through tmp + os.replace, "
                 "never bare open(path, 'w')")
    history = ("PR 7: torn-write chaos against the tiered KV store — "
               "every durable artifact since (page files, incident "
               "bundles, checkpoints) uses the tmp+os.replace discipline "
               "so a crash mid-write leaves the old file, not half a new "
               "one")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        funcs = [n for n in ast.walk(sf.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        # module body as a pseudo-function for script-style files
        for scope in funcs + [sf.tree]:
            yield from self._check_scope(sf, scope)

    def _check_scope(self, sf: SourceFile, scope) -> Iterable[Finding]:
        own_nodes = list(self._own_walk(scope))
        opens = []
        json_write = False
        replaced_srcs: list = []  # os.replace(<src>, <dst>) first args
        for node in own_nodes:
            if not isinstance(node, ast.Call):
                continue
            t = expr_text(node.func)
            if t == "open" and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str) \
                    and node.args[1].value.startswith("w"):
                opens.append(node)
            elif t in ("json.dump", "json.dumps"):
                json_write = True
            elif t == "os.replace" and node.args:
                replaced_srcs.append(
                    ast.get_source_segment(sf.text, node.args[0]) or "")
        if not opens:
            return
        for node in opens:
            path_src = ast.get_source_segment(sf.text, node.args[0]) or ""
            durable = json_write or ".json" in path_src
            if not durable:
                continue
            if "tmp" in path_src.lower():
                continue  # writing the tmp half of the discipline
            # exemption is PER OPEN: this open's exact path must be what
            # an os.replace in the scope moves — one correctly-staged
            # write must not grandfather a second bare one next to it
            if any(path_src == r for r in replaced_srcs):
                continue
            yield Finding(
                self.name, sf.rel, node.lineno,
                f"bare open({path_src}, 'w') with a JSON payload and no "
                f"os.replace of that path in scope — a crash mid-write "
                f"leaves a torn file; write to '<path>.tmp' then "
                f"os.replace")

    @staticmethod
    def _own_walk(scope):
        """Walk scope WITHOUT descending into nested function defs (their
        writes are judged in their own scope)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not scope:
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
