"""event-loop-blocking: functions on the ingress readiness loop never
block.

Opt-in via '# graftlint: event-loop' on (or directly above) the def
line — the marker the ingress data plane (serving/ingress_core.py)
puts on every function the selector loop thread runs.  One blocked
call there stalls EVERY connection the proxy is carrying, so the rule
bans the calls that block by construction (time.sleep, a synchronous
urlopen, reading a whole response) and the ones that block by default
(socket recv/accept on a socket that was never switched to
non-blocking mode).

The socket check is structural, not nominal: a recv()/accept()/
recvfrom() is accepted only when the call sits under a ``try`` whose
handlers catch BlockingIOError — the unavoidable signature of
non-blocking socket code (a non-blocking socket RAISES
BlockingIOError instead of waiting; code that never catches it either
blocks or was never tested).  Referencing the loop's selector is not
enough: registering a socket with a selector does not make its recv
non-blocking.

json.loads/json.load are banned outright: the loop only FRAMES
requests (split head, count Content-Length bytes); parsing a multi-KB
body is worker-pool work, and on the loop it is a per-request stall
multiplied by every other connection.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Context, Finding, Rule, SourceFile, _EVLOOP_RE, expr_text

BANNED_CALLS = {
    "time.sleep": "a sleeping loop thread stalls every connection — "
                  "use the selector timeout for pacing",
    "urllib.request.urlopen": "a synchronous dial+read on the loop "
                              "blocks all connections — backend I/O "
                              "belongs on the worker pool (see "
                              "serving/transport.py)",
    "urlopen": "a synchronous dial+read on the loop blocks all "
               "connections — backend I/O belongs on the worker pool "
               "(see serving/transport.py)",
    "json.loads": "body parsing is worker-pool work — the loop only "
                  "frames bytes (head split + Content-Length count)",
    "json.load": "body parsing is worker-pool work — the loop only "
                 "frames bytes (head split + Content-Length count)",
}

# socket methods that block unless the socket is non-blocking
_BLOCKING_SOCK_METHODS = ("recv", "accept", "recvfrom")


class EventLoopRule(Rule):
    name = "event-loop-blocking"
    invariant = ("functions marked '# graftlint: event-loop' never call "
                 "time.sleep/urlopen/json.loads, and every socket "
                 "recv/accept sits under a try that catches "
                 "BlockingIOError (the non-blocking discipline proof)")
    history = ("ISSUE 20: the ingress moved from thread-per-connection "
               "to one readiness loop — a single blocked call there now "
               "stalls every in-flight request, not one; the rule makes "
               "the loop's non-blocking discipline machine-checked "
               "instead of reviewed")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            marked = sf.directive_near(node.lineno, _EVLOOP_RE) or any(
                sf.directive_near(d.lineno, _EVLOOP_RE)
                for d in node.decorator_list)
            if not marked:
                continue
            yield from self._check_body(sf, node)

    def _check_body(self, sf: SourceFile, fn) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            t = expr_text(node.func)
            if t in BANNED_CALLS:
                yield Finding(
                    self.name, sf.rel, node.lineno,
                    f"event-loop function '{fn.name}' calls {t}() — "
                    f"{BANNED_CALLS[t]}")
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _BLOCKING_SOCK_METHODS \
                    and not self._under_blockingio_guard(sf, node, fn):
                yield Finding(
                    self.name, sf.rel, node.lineno,
                    f"event-loop function '{fn.name}' calls "
                    f".{node.func.attr}() outside a try that catches "
                    f"BlockingIOError — a default (blocking) socket "
                    f"here stalls every connection on the loop; set the "
                    f"socket non-blocking and handle BlockingIOError")

    @staticmethod
    def _under_blockingio_guard(sf: SourceFile, node, fn) -> bool:
        """True when an ancestor ``try`` (inside fn) has a handler whose
        exception list names BlockingIOError."""
        for a in sf.ancestors(node):
            if a is fn:
                return False
            if not isinstance(a, ast.Try):
                continue
            for handler in a.handlers:
                for exc in _exc_names(handler.type):
                    if exc.endswith("BlockingIOError"):
                        return True
        return False


def _exc_names(t) -> list:
    """Dotted names inside an except clause type (name or tuple)."""
    if t is None:
        return []
    if isinstance(t, ast.Tuple):
        out = []
        for e in t.elts:
            out.extend(_exc_names(e))
        return out
    name = expr_text(t)
    return [name] if name else []
