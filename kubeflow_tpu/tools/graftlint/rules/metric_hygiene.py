"""metric-hygiene: metric objects resolve to a registration; labeled
series keyed by unbounded identity have a removal path.

Registrations are ``<registry>.counter|gauge|histogram("name", ...)``
calls anywhere in the tree (telemetry.py owns the engine scope, the
router/disagg modules own the ingress scope).  Usage sites are
``ALL_CAPS.inc/observe/set`` on module-level constants — the convention
every metric in the repo follows.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from ..core import (Context, Finding, Rule, SourceFile, is_package,
                    module_name, resolve_import_base)

REG_METHODS = {"counter", "gauge", "histogram"}
USE_METHODS = {"inc", "observe", "set"}
ALLCAPS_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
# label keys whose value space grows with traffic, not with config
IDENTITY_LABELS = {"tenant", "pod", "session", "rid", "handle"}


class MetricHygieneRule(Rule):
    name = "metric-hygiene"
    invariant = ("every ALL_CAPS metric constant used via .inc/.observe/"
                 ".set resolves to a registry registration, and any metric "
                 "labeled by unbounded identity (tenant/pod/session/rid/"
                 "handle) has a .remove() path somewhere in the tree")
    history = ("PR 14 second pass: ingress_tenant_tokens leaked one "
               "registry series per tenant forever under a unique-tenant "
               "storm while the controller's own dicts were bounded — the "
               "gauge needed drain_pruned_tenants wired to .remove()")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        # pass 1: registrations and the module-level constants bound to them
        reg_names: dict[str, str] = {}          # metric name -> kind
        aliases: dict[str, dict[str, str]] = {}  # module -> const -> metric
        for sf in ctx.files:
            mod = module_name(sf.rel)
            amap: dict[str, str] = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Assign):
                    m = self._registration(node.value)
                    if m is not None:
                        name, kind = m
                        reg_names[name] = kind
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                amap[t.id] = name
                elif isinstance(node, ast.Call):
                    m = self._registration(node)
                    if m is not None:
                        reg_names[m[0]] = m[1]
            aliases[mod] = amap
        # imported aliases: from X import CONST / import X as x
        imports: dict[str, dict[str, str]] = {}  # module -> local -> module
        modules = set(ctx.by_module)
        for sf in ctx.files:
            mod = module_name(sf.rel)
            imap: dict[str, str] = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ImportFrom):
                    base = resolve_import_base(mod, is_package(sf.rel),
                                               node)
                    if base is None:
                        continue
                    for a in node.names:
                        # 'from . import disagg' binds the SUBMODULE —
                        # resolve to it when it exists, else the base
                        # (symbol import)
                        sub = f"{base}.{a.name}"
                        imap[a.asname or a.name] = (sub if sub in modules
                                                    else base)
                elif isinstance(node, ast.Import):
                    for a in node.names:
                        imap[a.asname or a.name.split(".")[0]] = a.name
            imports[mod] = imap
        # pass 2: usages
        label_use: dict[str, set] = {}   # metric name -> label keys seen
        removed: set = set()             # metric names with a .remove path
        use_sites: dict[str, list] = {}  # metric name -> [(rel, line)]
        for sf in ctx.files:
            mod = module_name(sf.rel)
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                method = node.func.attr
                if method not in USE_METHODS and method != "remove":
                    continue
                metric = self._resolve(node.func.value, mod, aliases,
                                       imports)
                if metric is None:
                    if method in USE_METHODS \
                            and self._looks_like_metric(node.func.value):
                        yield Finding(
                            self.name, sf.rel, node.lineno,
                            f"'{ast.get_source_segment(sf.text, node.func) or method}' "
                            f"does not resolve to a registry "
                            f"counter/gauge/histogram registration")
                    continue
                if method == "remove":
                    removed.add(metric)
                    continue
                keys = {kw.arg for kw in node.keywords if kw.arg}
                label_use.setdefault(metric, set()).update(keys)
                use_sites.setdefault(metric, []).append((sf.rel,
                                                         node.lineno))
        # pass 3: identity-labeled series need a removal path
        for metric in sorted(label_use):
            idents = label_use[metric] & IDENTITY_LABELS
            if not idents or metric in removed:
                continue
            rel, line = use_sites[metric][0]
            yield Finding(
                self.name, rel, line,
                f"metric '{metric}' is labeled by unbounded identity "
                f"({', '.join(sorted(idents))}) but no .remove() call "
                f"exists anywhere — each new identity leaks a series "
                f"forever")

    # ------------------------------------------------------------- helpers

    @staticmethod
    def _registration(node) -> Optional[tuple]:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in REG_METHODS \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value, node.func.attr
        return None

    @staticmethod
    def _looks_like_metric(recv) -> bool:
        """Only ALL_CAPS constants are held to the registration rule —
        lowercase receivers (self.ttft, histogram locals) register at
        their own assignment site."""
        if isinstance(recv, ast.Name):
            return bool(ALLCAPS_RE.match(recv.id))
        if isinstance(recv, ast.Attribute):
            return bool(ALLCAPS_RE.match(recv.attr))
        return False

    def _resolve(self, recv, mod: str, aliases: dict,
                 imports: dict) -> Optional[str]:
        """Metric name for a usage receiver: NAME in this module, or
        mod_alias.NAME through the import map."""
        if isinstance(recv, ast.Name):
            local = aliases.get(mod, {}).get(recv.id)
            if local:
                return local
            src = imports.get(mod, {}).get(recv.id)
            if src:  # from X import CONST
                return aliases.get(src, {}).get(recv.id)
            return None
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name):
            src = imports.get(mod, {}).get(recv.value.id)
            if src:
                return aliases.get(src, {}).get(recv.attr)
        return None
