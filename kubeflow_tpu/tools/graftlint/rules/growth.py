"""bounded-growth: dicts keyed by unbounded identity need a prune path.

Heuristic: an instance (or module-level) dict whose name mentions a
request/tenant/pod-shaped identity must have SOME shrink operation —
pop/popitem/clear/del/reassignment — reachable in the same class (or
module).  Existence, not call-graph reachability: the historical bugs
were dicts with NO removal code at all.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from ..core import Context, Finding, Rule, SourceFile

IDENT_RE = re.compile(
    r"tenant|session|pod\b|pods|rid|request|replica|backend|handle|"
    r"bucket|trace", re.IGNORECASE)
DICT_FACTORIES = {"dict", "defaultdict", "OrderedDict", "Counter"}


def _is_dict_value(v) -> bool:
    if isinstance(v, ast.Dict):
        return True
    if isinstance(v, ast.Call):
        f = v.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        return name in DICT_FACTORIES
    return False


class BoundedGrowthRule(Rule):
    name = "bounded-growth"
    invariant = ("a dict keyed by request/tenant/pod/session identity has "
                 "a prune/eviction operation in its owning class or module")
    history = ("PR 14 review: a unique-X-Tenant-Id-per-request storm grew "
               "four per-tenant dicts and the per-admission share sum "
               "without bound until the amortized adjust pass learned to "
               "prune them")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        for cls in ast.walk(sf.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(sf, cls)
        yield from self._check_module_level(sf)

    def _check_class(self, sf: SourceFile, cls) -> Iterable[Finding]:
        init = next((n for n in cls.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return
        candidates: dict[str, int] = {}
        for node in ast.walk(init):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if value is None or not _is_dict_value(value):
                continue
            for t in targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                        and IDENT_RE.search(t.attr)):
                    candidates[t.attr] = node.lineno
        if not candidates:
            return
        shrunk = _module_shrunk_names(sf)
        shrunk |= self._reassigned_attrs(cls, init)
        for attr, line in sorted(candidates.items(), key=lambda kv: kv[1]):
            if attr in shrunk:
                continue
            yield Finding(
                self.name, sf.rel, line,
                f"'self.{attr}' in class {cls.name} looks keyed by "
                f"unbounded identity but nothing in the module pops/"
                f"clears/deletes from it — a churn workload grows it "
                f"forever")

    @staticmethod
    def _reassigned_attrs(cls, init) -> set:
        """self.X reassigned wholesale in a method outside __init__."""
        out: set = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) \
                    and not (init.lineno <= node.lineno
                             <= (init.end_lineno or init.lineno)):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        out.add(t.attr)
        return out

    def _check_module_level(self, sf: SourceFile) -> Iterable[Finding]:
        candidates: dict[str, int] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and _is_dict_value(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name) and IDENT_RE.search(t.id):
                        candidates[t.id] = node.lineno
        if not candidates:
            return
        shrunk = _module_shrunk_names(sf)
        for name, line in sorted(candidates.items(), key=lambda kv: kv[1]):
            if name not in shrunk:
                yield Finding(
                    self.name, sf.rel, line,
                    f"module-level dict '{name}' looks keyed by unbounded "
                    f"identity but nothing in the module pops/clears/"
                    f"deletes from it")


def _module_shrunk_names(sf: SourceFile) -> set:
    """Names (attribute or bare) with a shrink op anywhere in the module.

    Receiver-agnostic on purpose: proxy state dicts are pruned by the
    OWNING component (``state.sessions.pop`` in ServiceProxy), not by
    methods of the declaring dataclass.  Also recognizes the alias-loop
    fold shape ``for d in (self.a, self.b): ... d.pop(...)``."""
    shrunk: set = set()
    aliased: dict[str, list] = {}  # loop-var -> attr names it aliases
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.For, ast.AsyncFor)) \
                and isinstance(node.target, ast.Name) \
                and isinstance(node.iter, (ast.Tuple, ast.List)):
            attrs = [e.attr for e in node.iter.elts
                     if isinstance(e, ast.Attribute)]
            if attrs:
                aliased.setdefault(node.target.id, []).extend(attrs)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("pop", "popitem", "clear"):
            recv = node.func.value
            if isinstance(recv, ast.Attribute):
                shrunk.add(recv.attr)
            elif isinstance(recv, ast.Name):
                shrunk.add(recv.id)
                shrunk.update(aliased.get(recv.id, ()))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    if isinstance(t.value, ast.Attribute):
                        shrunk.add(t.value.attr)
                    elif isinstance(t.value, ast.Name):
                        shrunk.add(t.value.id)
                        shrunk.update(aliased.get(t.value.id, ()))
    return shrunk
