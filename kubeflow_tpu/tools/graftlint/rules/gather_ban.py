"""gather-ban: hot-path functions never gather a mesh-sharded KV pool to
host.

Opt-in via '# graftlint: hot-path' on (or directly above) the def line —
the same marker hot-path reads.  Flags ``jax.device_get(...)`` of
anything, and ``np.asarray(...)`` / ``numpy.asarray(...)`` whose argument
expression names a pool (contains "pool", e.g. ``self.k_pool``,
``pool[:, pages]``) — the exact shape of the pre-ISSUE-16 snapshot
regression, where one ``np.asarray(leaf[:, pages])`` over a
tensor-parallel pool implied an all-gather of pool-sized KV through host
RAM.  The shard-native path (sharding.snapshot_shards) reads
``shard.data`` instead, which this rule deliberately does not match.
Heuristic by design: per-shard helpers name their locals ``block`` /
``shard``; anything called "pool" inside a hot-path function is the
engine's device pool.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Context, Finding, Rule, SourceFile, _HOT_RE, expr_text

# full gathers of any argument — device_get IS the gather primitive
_GATHER_CALLS = ("jax.device_get",)
# host-copy calls that gather when aimed at a pool
_ASARRAY_CALLS = ("np.asarray", "numpy.asarray")


def _names_pool(node) -> bool:
    """True when any name inside the argument expression names a pool —
    walks the whole subtree so subscripted forms (``self.k_pool[:, p]``)
    match, not just bare dotted chains."""
    for sub in ast.walk(node):
        t = expr_text(sub)
        if t and "pool" in t.lower():
            return True
    return False


class GatherBanRule(Rule):
    name = "gather-ban"
    invariant = ("functions marked '# graftlint: hot-path' never call "
                 "jax.device_get, and never np.asarray a mesh-sharded "
                 "pool — snapshot per shard (sharding.snapshot_shards) "
                 "so host copies move one shard's bytes, not the pool's")
    history = ("ISSUE 16: every KV snapshot path (swap park, session pin, "
               "handoff export, fabric publish) gathered the full pool to "
               "host via np.asarray(leaf[:, pages]) — at TP=N that is an "
               "all-gather of N chips' KV through one host buffer; the "
               "sharded data plane moves per-shard addressable bytes only")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            marked = sf.directive_near(node.lineno, _HOT_RE) or any(
                sf.directive_near(d.lineno, _HOT_RE)
                for d in node.decorator_list)
            if not marked:
                continue
            yield from self._check_body(sf, node)

    def _check_body(self, sf: SourceFile, fn) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            t = expr_text(node.func)
            if t in _GATHER_CALLS:
                yield Finding(
                    self.name, sf.rel, node.lineno,
                    f"hot-path function '{fn.name}' calls {t}() — a full "
                    f"device->host gather; snapshot per shard instead")
            elif t in _ASARRAY_CALLS and node.args \
                    and _names_pool(node.args[0]):
                yield Finding(
                    self.name, sf.rel, node.lineno,
                    f"hot-path function '{fn.name}' calls {t}() on a "
                    f"pool — on a mesh-sharded pool this gathers every "
                    f"chip's KV through host RAM; use "
                    f"sharding.snapshot_shards to move one shard's bytes")
