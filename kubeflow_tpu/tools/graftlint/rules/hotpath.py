"""hot-path: functions on the per-request/per-tick critical path stay
allocation-light and O(1).

Opt-in via '# graftlint: hot-path' on (or directly above) the def line.
Bans the known offenders from the repo's review history — JSON parsing,
sorting, deep copies — and flags O(n) iteration under a lock.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Context, Finding, Rule, SourceFile, _HOT_RE, expr_text

BANNED_CALLS = {
    "json.loads": "parse once at the boundary, pass the object",
    "json.dumps": "serialize off the hot path",
    "json.dump": "serialize off the hot path",
    "copy.deepcopy": "deep copies are O(object graph)",
    "sorted": "sorting is O(n log n) — keep a cache or a heap",
    "time.time": "wall clock skews under NTP steps — hot-path timing "
                 "uses time.perf_counter",
    # grammar/regex compilation is admission-time work (README
    # "Structured output"): the per-tick mask path walks PRE-compiled
    # automata; a compile here would stall every slot in the batch
    "re.compile": "pattern compilation is O(pattern) with a global lock "
                  "on the cache — compile at module scope",
    "compile_grammar": "grammar compilation belongs at admission — the "
                       "tick path only walks compiled automata",
    "compile_json_schema": "schema compilation belongs at admission — "
                           "the tick path only walks compiled automata",
    "compile_spec": "spec compilation belongs at admission — the tick "
                    "path only walks compiled automata",
    "constrain.compile_grammar": "grammar compilation belongs at "
                                 "admission — the tick path only walks "
                                 "compiled automata",
    "constrain.compile_spec": "spec compilation belongs at admission — "
                              "the tick path only walks compiled "
                              "automata",
}


class HotPathRule(Rule):
    name = "hot-path"
    invariant = ("functions marked '# graftlint: hot-path' never call "
                 "json.loads/json.dumps/copy.deepcopy/sorted/time.time "
                 "and never iterate a collection under a lock")
    history = ("PR 14 review: the deadline gate sorted the rolling latency "
               "window per admission under the controller lock — the "
               "module's stated O(1) discipline, made true by a p50 cache "
               "refreshed once per adjust pass")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            marked = sf.directive_near(node.lineno, _HOT_RE) or any(
                sf.directive_near(d.lineno, _HOT_RE)
                for d in node.decorator_list)
            if not marked:
                continue
            yield from self._check_body(sf, node)

    def _check_body(self, sf: SourceFile, fn) -> Iterable[Finding]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                t = expr_text(node.func)
                if t in BANNED_CALLS:
                    yield Finding(
                        self.name, sf.rel, node.lineno,
                        f"hot-path function '{fn.name}' calls {t}() — "
                        f"{BANNED_CALLS[t]}")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                lock = self._lock_above(sf, node, fn)
                if lock and self._iterates_collection(node.iter):
                    yield Finding(
                        self.name, sf.rel, node.lineno,
                        f"hot-path function '{fn.name}' iterates a "
                        f"collection inside 'with {lock}:' — O(n) work "
                        f"under a lock serializes every other holder")

    @staticmethod
    def _lock_above(sf: SourceFile, node, fn) -> str:
        """Name of a lock-ish context manager between node and fn."""
        for a in sf.ancestors(node):
            if a is fn:
                return ""
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    t = expr_text(item.context_expr)
                    if t and "lock" in t.lower():
                        return t
        return ""

    @staticmethod
    def _iterates_collection(it) -> bool:
        """True for 'for x in <attr>' / '<attr>.items()/values()/keys()'
        — the unbounded-collection shapes; range()/literals are fine."""
        if isinstance(it, ast.Attribute):
            return True
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) \
                and it.func.attr in ("items", "values", "keys"):
            return True
        return False
