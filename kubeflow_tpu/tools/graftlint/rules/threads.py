"""thread-lifecycle: every threading.Thread is daemonized or joined.

A non-daemon thread with no join owner outlives its creator: it pins
interpreter shutdown, leaks across test cases, and — the production
shape — keeps polling a dead engine's state forever.  Accepted
ownership shapes:

  * ``daemon=True`` at construction (or ``.daemon = True`` before start)
  * ``self._t = Thread(...)`` with a ``self._t.join(...)`` anywhere in
    the owning class (a ``stop()``/``close()`` join path)
  * a local/listcomp thread with a ``.join(`` later in the same function
    (the router's scatter-gather fan-outs)
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Context, Finding, Rule, SourceFile, expr_text


class ThreadLifecycleRule(Rule):
    name = "thread-lifecycle"
    invariant = ("every threading.Thread is constructed daemon=True, "
                 "joined by its owning class, or joined in its creating "
                 "function")
    history = ("PR 10: a second engine start() spawned a SECOND loop "
               "thread racing every dispatch's buffer-donation contract; "
               "owned lifecycle (idempotent start, joined stop) is the "
               "fix pattern")

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            t = expr_text(node.func)
            if t not in ("threading.Thread", "Thread"):
                continue
            if self._daemon_kwarg(node):
                continue
            if self._owned(sf, node):
                continue
            yield Finding(
                self.name, sf.rel, node.lineno,
                "threading.Thread without daemon=True and without a "
                "join owner — daemonize it or join it from the owner's "
                "stop()/close()")

    @staticmethod
    def _daemon_kwarg(node) -> bool:
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return True
        return False

    def _owned(self, sf: SourceFile, node) -> bool:
        # find the assignment this call feeds (directly or via listcomp)
        assign = None
        for a in sf.ancestors(node):
            if isinstance(a, (ast.Assign, ast.AnnAssign)):
                assign = a
                break
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                break
        fn = sf.enclosing_function(node)
        if assign is not None:
            targets = assign.targets if isinstance(assign, ast.Assign) \
                else [assign.target]
            for t in targets:
                # self.<attr> = Thread(...): join or daemon anywhere in class
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    cls = sf.enclosing_class(node)
                    if cls is not None and self._class_owns(cls, t.attr):
                        return True
                # local = Thread(...) (or a listcomp of them)
                if isinstance(t, ast.Name) and fn is not None \
                        and self._joined_later(fn, node.lineno):
                    return True
        # bare Thread(...).start() or constructor arg: only daemon saves it
        return False

    def _class_owns(self, cls, attr: str) -> bool:
        for node in ast.walk(cls):
            # self.<attr>.join(...)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and expr_text(node.func.value) == f"self.{attr}":
                return True
            # t = self.<attr>; ... t.join() — the incidents stop()
            # pattern; the local MUST actually be joined in the same
            # method (a mere is_alive() read alias is not ownership)
            if isinstance(node, ast.Assign) \
                    and expr_text(node.value) == f"self.{attr}":
                for t in node.targets:
                    if isinstance(t, ast.Name) and self._local_joined(
                            cls, node, t.id):
                        return True
            # self.<attr>.daemon = True before start
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                            and expr_text(t.value) == f"self.{attr}":
                        return True
        return False

    @staticmethod
    def _local_joined(cls, assign, name: str) -> bool:
        """True when the method containing ``assign`` also calls
        ``<name>.join(...)``."""
        for fn in ast.walk(cls):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not (fn.lineno <= assign.lineno
                    <= (fn.end_lineno or fn.lineno)):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "join" \
                        and expr_text(node.func.value) == name:
                    return True
        return False

    @staticmethod
    def _joined_later(fn, after_line: int) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and node.lineno >= after_line:
                return True
        return False
