"""import-weight: the router's POD import chain stays light.

Walks the REAL top-level import graph from the ingress roots
(serving/router.py, serving/overload.py) and flags any module in the
closure that imports numpy, jax, or the serving engine at module scope.
Function-scope (lazy) imports are the sanctioned pattern and are not
edges.
"""

from __future__ import annotations

import ast
import collections
from typing import Iterable, Optional

from ..core import (Context, Finding, Rule, is_package, module_name,
                    resolve_import_base)

ROOTS = ("kubeflow_tpu.serving.router", "kubeflow_tpu.serving.overload")
# heavy leaf packages that must never ride the ingress import chain; the
# engine subtree transitively pulls numpy AND jax
BANNED_EXTERNAL = ("numpy", "jax")
BANNED_INTERNAL_PREFIX = "kubeflow_tpu.serving.engine"


def _top_level_imports(tree: ast.Module) -> Iterable[ast.stmt]:
    """Module-scope import statements, descending into module-level
    If/Try bodies but skipping 'if TYPE_CHECKING:' guards."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            yield node
        elif isinstance(node, ast.If):
            t = node.test
            name = t.id if isinstance(t, ast.Name) else (
                t.attr if isinstance(t, ast.Attribute) else "")
            if name != "TYPE_CHECKING":
                stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, ast.Try):
            stack.extend(node.body)
            for h in node.handlers:
                stack.extend(h.body)
            stack.extend(node.orelse)
            stack.extend(node.finalbody)


class ImportWeightRule(Rule):
    name = "import-weight"
    invariant = ("no module reachable from serving/router.py or "
                 "serving/overload.py via top-level imports may import "
                 "numpy, jax, or kubeflow_tpu.serving.engine at module "
                 "scope")
    history = ("PR 14: a top-level numpy/scheduler import on the serving "
               "package chain took the POD subprocess import from 0.28s "
               "to 1.26s — enough to blow the 1.5s scale-from-zero "
               "activation grace and re-zero the deployment")

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        # adjacency: module -> [(target_module, line)]
        edges: dict[str, list] = {}
        banned_at: dict[str, list] = {}  # module -> [(line, what)]
        for sf in ctx.files:
            mod = module_name(sf.rel)
            out: list = []
            bans: list = []
            for node in _top_level_imports(sf.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        root = a.name.split(".")[0]
                        if root in BANNED_EXTERNAL:
                            bans.append((node.lineno, a.name))
                        if a.name.startswith("kubeflow_tpu"):
                            out.append((a.name, node.lineno))
                else:
                    base = resolve_import_base(mod, is_package(sf.rel),
                                               node)
                    if base is None:
                        continue
                    if base.split(".")[0] in BANNED_EXTERNAL:
                        bans.append((node.lineno, base))
                    if base.startswith("kubeflow_tpu"):
                        for a in node.names:
                            sub = f"{base}.{a.name}"
                            # 'from .x import y': y may be a submodule or
                            # a symbol — edge to the submodule when it
                            # exists, else to the base module
                            out.append((sub if sub in ctx.by_module
                                        else base, node.lineno))
            # importing any module executes its ancestor packages too
            withself = set()
            for tgt, ln in out:
                parts = tgt.split(".")
                for i in range(1, len(parts) + 1):
                    anc = ".".join(parts[:i])
                    if anc in ctx.by_module and anc != mod:
                        withself.add((anc, ln))
            edges[mod] = sorted(withself)
            if bans:
                banned_at[mod] = bans
        # BFS the closure from the roots, keeping one witness chain
        parent: dict[str, Optional[str]] = {}
        q = collections.deque()
        for r in ROOTS:
            if r in ctx.by_module and r not in parent:
                parent[r] = None
                q.append(r)
        while q:
            cur = q.popleft()
            for tgt, _ in edges.get(cur, ()):
                if tgt not in parent and tgt in ctx.by_module:
                    parent[tgt] = cur
                    q.append(tgt)
        for mod in sorted(parent):
            sf = ctx.by_module[mod]
            # banned internal targets: an edge INTO the engine subtree
            for tgt, ln in edges.get(mod, ()):
                if tgt.startswith(BANNED_INTERNAL_PREFIX):
                    yield Finding(
                        self.name, sf.rel, ln,
                        f"{mod} (on the ingress import chain: "
                        f"{self._chain(parent, mod)}) imports {tgt} at "
                        f"module scope — move it into the function that "
                        f"needs it")
            for ln, what in banned_at.get(mod, ()):
                yield Finding(
                    self.name, sf.rel, ln,
                    f"{mod} (on the ingress import chain: "
                    f"{self._chain(parent, mod)}) imports {what} at "
                    f"module scope — move it into the function that "
                    f"needs it")

    @staticmethod
    def _chain(parent: dict, mod: str) -> str:
        hops = [mod]
        while parent.get(hops[-1]) is not None:
            hops.append(parent[hops[-1]])
        return " <- ".join(hops)
