"""Rule registry: the ten invariants distilled from the repo's own
review history (see each rule's ``history`` for the bug it encodes)."""

from .atomic import AtomicWriteRule
from .eventloop import EventLoopRule
from .gather_ban import GatherBanRule
from .growth import BoundedGrowthRule
from .hotpath import HotPathRule
from .imports import ImportWeightRule
from .locks import LockDisciplineRule, ReleaseGuaranteeRule
from .metric_hygiene import MetricHygieneRule
from .threads import ThreadLifecycleRule

ALL_RULES = [
    LockDisciplineRule,
    ReleaseGuaranteeRule,
    ImportWeightRule,
    HotPathRule,
    EventLoopRule,
    GatherBanRule,
    BoundedGrowthRule,
    AtomicWriteRule,
    MetricHygieneRule,
    ThreadLifecycleRule,
]


def rule_table() -> list:
    """(name, invariant, history) rows — the README table's source."""
    return [(r.name, r.invariant, r.history) for r in ALL_RULES]
