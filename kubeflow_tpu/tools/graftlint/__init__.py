"""graftlint — repo-native static analysis for the serving stack's
load-bearing invariants (ISSUE 15).

Fourteen PRs of review fixes kept rediscovering the same defect classes by
hand: shared proxy state read outside ``state.lock``, inflight slots
incremented without a ``finally`` release, per-tenant dicts and metric
label series that grow without a prune path, heavy imports leaking onto
the router's POD import chain (the 0.28s -> 1.26s cold-start regression),
non-atomic durable writes, and O(n) work on paths the modules document as
O(1).  JetStream's "orchestration off the critical path" discipline and
NanoFlow's host-side-bottleneck finding (PAPERS.md) both say these
invariants are load-bearing for serving throughput — so they are enforced
here by an AST checker, not by reviewer memory.

Usage::

    python -m kubeflow_tpu.tools.graftlint            # human output
    python -m kubeflow_tpu.tools.graftlint --json     # machine-readable
    python -m kubeflow_tpu.tools.graftlint --write-baseline

Suppression syntax (reason REQUIRED — a reasonless suppression is itself
a finding)::

    x = self._table[k]  # graftlint: disable=lock-discipline -- single-writer loop thread

A suppression comment on its own line covers the next statement; on a
``def``/``class``/``with``/``for`` header it covers the whole block.

Annotation conventions the rules consume::

    self.sessions = {}        # guarded-by: lock        (lock-discipline)
    def _eject(...):          # graftlint: holds-lock=lock
    decision = admit(...)     # graftlint: acquires=inflight
    ov.release(decision)      # graftlint: releases=inflight
    def feed(...):            # graftlint: hot-path

The tier-1 gate (tests/test_graftlint.py) runs the analyzer over all of
``kubeflow_tpu/`` and requires zero unsuppressed findings.
"""

from .core import (Finding, Report, SourceFile, analyze, default_baseline_path,
                   default_root, load_baseline, write_baseline)
from .rules import ALL_RULES, rule_table

__all__ = [
    "ALL_RULES", "Finding", "Report", "SourceFile", "analyze",
    "default_baseline_path", "default_root", "load_baseline",
    "rule_table", "write_baseline",
]
