"""Analyzer framework: parsed sources, suppressions, baseline, driver.

The framework is deliberately small: each rule sees a ``SourceFile``
(AST + per-line comments + directive index) per file and a shared
``Context`` for cross-module facts (the import graph, the metric
registry).  Findings carry a content fingerprint — rule + path +
normalized source line + occurrence index — so the committed baseline
survives line drift without grandfathering NEW instances of an old bug.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import time
import tokenize
from typing import Iterable, Optional

# ---------------------------------------------------------------- directives

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable=([\w,\-]+)(?:\s*--\s*(\S.*))?")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([\w]+)")
_HOLDS_RE = re.compile(r"#\s*graftlint:\s*holds-lock=([\w]+)")
_HOT_RE = re.compile(r"#\s*graftlint:\s*hot-path\b")
_EVLOOP_RE = re.compile(r"#\s*graftlint:\s*event-loop\b")
_ACQ_RE = re.compile(r"#\s*graftlint:\s*acquires=([\w\-]+)")
_REL_RE = re.compile(r"#\s*graftlint:\s*releases=([\w\-]+)")

# block statements a standalone/header suppression extends over
_BLOCK_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.With, ast.AsyncWith, ast.For, ast.AsyncFor, ast.While,
                ast.If, ast.Try)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, e.g. kubeflow_tpu/serving/router.py
    line: int
    message: str
    suppressed: bool = False
    baselined: bool = False
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint,
                "suppressed": self.suppressed, "baselined": self.baselined}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def expr_text(node) -> Optional[str]:
    """Dotted-name text of a Name/Attribute chain (None for anything
    else) — the receiver-matching currency of the lock rules."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_text(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


class SourceFile:
    """One parsed module: AST, parent links, comments and directives."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.parents: dict[int, ast.AST] = {}
        self._stmt_at: dict[int, ast.stmt] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
            if isinstance(node, ast.stmt):
                prev = self._stmt_at.get(node.lineno)
                # outermost statement starting on a line wins (its extent
                # is what a header suppression should cover)
                if prev is None or ((node.end_lineno or node.lineno)
                                    > (prev.end_lineno or prev.lineno)):
                    self._stmt_at[node.lineno] = node
        self.comments: dict[int, str] = {}
        self.code_lines: set[int] = set()
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
                elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                                      tokenize.INDENT, tokenize.DEDENT,
                                      tokenize.ENCODING, tokenize.ENDMARKER):
                    for ln in range(tok.start[0], tok.end[0] + 1):
                        self.code_lines.add(ln)
        except tokenize.TokenError:  # pragma: no cover - ast.parse passed
            pass
        # suppression ranges: (lo, hi, {rules}) — built after comments
        self.suppressions: list[tuple[int, int, set]] = []
        self.bad_suppressions: list[int] = []  # lines missing a reason
        self._build_suppressions()

    # ------------------------------------------------------------ comments

    def comment(self, line: int) -> str:
        return self.comments.get(line, "")

    def directive_near(self, line: int, regex: re.Pattern) -> Optional[str]:
        """Match a directive on ``line`` or on a standalone comment line
        directly above it; returns the first capture group (or the match
        text for group-less patterns)."""
        for ln in (line, line - 1):
            c = self.comments.get(ln)
            if not c:
                continue
            if ln != line and ln in self.code_lines:
                continue  # the line above holds code — its comment is its own
            m = regex.search(c)
            if m:
                return m.group(1) if m.groups() else m.group(0)
        return None

    # --------------------------------------------------------- suppressions

    def _build_suppressions(self) -> None:
        for ln, c in self.comments.items():
            m = _DISABLE_RE.search(c)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad_suppressions.append(ln)
                continue
            target = ln
            if ln not in self.code_lines:  # standalone: covers next stmt
                target = ln + 1
                while (target <= len(self.lines)
                       and target not in self.code_lines):
                    target += 1
            stmt = self._stmt_at.get(target)
            hi = target
            if stmt is not None and isinstance(stmt, _BLOCK_STMTS):
                hi = stmt.end_lineno or target
            self.suppressions.append((min(ln, target), hi, rules))

    def suppressed(self, rule: str, line: int) -> bool:
        for lo, hi, rules in self.suppressions:
            if lo <= line <= hi and (rule in rules or "all" in rules):
                return True
        return False

    # -------------------------------------------------------------- queries

    def parent(self, node) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def ancestors(self, node) -> Iterable[ast.AST]:
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def enclosing_function(self, node):
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a
        return None

    def enclosing_class(self, node):
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None

class Context:
    """Shared cross-module state handed to every rule."""

    def __init__(self, root: str, package_root: str,
                 files: list[SourceFile]):
        self.root = root
        self.package_root = package_root
        self.files = files
        self.by_rel = {f.rel: f for f in files}
        self.by_module = {module_name(f.rel): f for f in files}
        self.shared: dict[str, object] = {}  # per-rule scratch


def module_name(rel: str) -> str:
    """kubeflow_tpu/serving/router.py -> kubeflow_tpu.serving.router."""
    parts = rel.replace(os.sep, "/").split("/")
    if parts[-1] == "__init__.py":
        parts = parts[:-1]
    else:
        parts[-1] = parts[-1][:-3]
    return ".".join(parts)


def is_package(rel: str) -> bool:
    return rel.replace(os.sep, "/").endswith("/__init__.py")


def resolve_import_base(mod: str, is_pkg: bool, node) -> Optional[str]:
    """Absolute dotted base of a (possibly relative) ImportFrom, given the
    importing module's dotted name.  A PACKAGE (__init__) is its own
    level-1 anchor — ``from . import x`` inside kubeflow_tpu/serving/
    __init__.py means kubeflow_tpu.serving.x, so packages strip one
    level fewer than plain modules."""
    if node.level == 0:
        return node.module
    strip = node.level - 1 if is_pkg else node.level
    parts = mod.split(".")
    base = parts[:len(parts) - strip] if strip <= len(parts) else []
    if node.module:
        base = base + node.module.split(".")
    return ".".join(base) if base else None


class Rule:
    """Base rule: per-file ``check`` plus cross-module ``finalize``."""

    name = "abstract"
    invariant = ""
    history = ""  # the historical bug this rule encodes

    def check(self, sf: SourceFile, ctx: Context) -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        return ()


# ------------------------------------------------------------------ baseline

def default_root() -> str:
    """The kubeflow_tpu package directory (three levels up)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> set:
    if not path or not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return {e["fingerprint"] for e in data.get("entries", ())}


def write_baseline(path: str, findings: list) -> None:
    entries = [{"rule": f.rule, "path": f.path, "line": f.line,
                "fingerprint": f.fingerprint, "message": f.message}
               for f in findings]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# -------------------------------------------------------------------- driver

@dataclasses.dataclass
class Report:
    root: str
    files_analyzed: int
    elapsed_s: float
    findings: list          # every finding, flags set
    parse_errors: list      # (rel, message)

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def counts(self) -> dict:
        by_rule: dict[str, int] = {}
        for f in self.unsuppressed:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return by_rule

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "root": self.root,
            "files_analyzed": self.files_analyzed,
            "elapsed_s": round(self.elapsed_s, 4),
            "findings": [f.to_dict() for f in self.unsuppressed],
            "counts": {
                "unsuppressed": len(self.unsuppressed),
                "suppressed": sum(1 for f in self.findings if f.suppressed),
                "baselined": sum(1 for f in self.findings if f.baselined),
                "by_rule": self.counts(),
            },
            "parse_errors": [{"path": p, "message": m}
                             for p, m in self.parse_errors],
        }


def discover(root: str) -> list:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def analyze(paths: Optional[list] = None, root: Optional[str] = None,
            rules: Optional[list] = None,
            baseline_path: Optional[str] = None,
            use_baseline: bool = True) -> Report:
    """Run the rule set; ``paths`` overrides discovery (fixture tests)."""
    from .rules import ALL_RULES  # late: rules import core
    t0 = time.perf_counter()
    root = root or default_root()
    package_root = os.path.dirname(root)
    targets = paths if paths is not None else discover(root)
    files: list[SourceFile] = []
    parse_errors: list[tuple[str, str]] = []
    for p in targets:
        p = os.path.abspath(p)
        rel = os.path.relpath(p, package_root)
        try:
            with open(p, encoding="utf-8") as f:
                text = f.read()
            files.append(SourceFile(p, rel, text))
        except (SyntaxError, ValueError, OSError) as e:
            parse_errors.append((rel, str(e)))
    ctx = Context(root, package_root, files)
    active = rules if rules is not None else [cls() for cls in ALL_RULES]
    findings: list[Finding] = []
    for rule in active:
        for sf in files:
            findings.extend(rule.check(sf, ctx))
        findings.extend(rule.finalize(ctx))
    # reasonless suppressions are findings themselves (never suppressible)
    for sf in files:
        for ln in sf.bad_suppressions:
            findings.append(Finding(
                "suppression-syntax", sf.rel, ln,
                "graftlint suppression without a reason: use "
                "'# graftlint: disable=<rule> -- <why this is safe>'"))
    # mark suppressions, assign fingerprints, apply baseline
    seq: dict[tuple, int] = {}
    baseline = (load_baseline(baseline_path or default_baseline_path())
                if use_baseline else set())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    for f in findings:
        sf = ctx.by_rel.get(f.path)
        src = (sf.lines[f.line - 1].strip()
               if sf and 0 < f.line <= len(sf.lines) else "")
        key = (f.rule, f.path, src)
        k = seq.get(key, 0)
        seq[key] = k + 1
        f.fingerprint = hashlib.sha1(
            f"{f.rule}|{f.path}|{src}|{k}".encode()).hexdigest()[:16]
        if sf is not None and f.rule != "suppression-syntax" \
                and sf.suppressed(f.rule, f.line):
            f.suppressed = True
        elif f.fingerprint in baseline:
            f.baselined = True
    return Report(root=root, files_analyzed=len(files),
                  elapsed_s=time.perf_counter() - t0,
                  findings=findings, parse_errors=parse_errors)
