"""Workload-side XLA profiler surfacing (SURVEY.md §5).

The TPUJob controller injects ``TPU_PROFILE_DIR``/``TPU_PROFILE_STEPS`` when
``spec.profile.enabled``; a training loop wraps its hot loop with
``maybe_trace`` and gets a ``jax.profiler`` trace (viewable in
TensorBoard/XProf) for the configured number of steps — no workload code
changes needed to turn profiling on or off.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional


def profile_dir(environ=None) -> Optional[str]:
    env = environ if environ is not None else os.environ
    return env.get("TPU_PROFILE_DIR") or None


def profile_steps(environ=None) -> int:
    env = environ if environ is not None else os.environ
    return int(env.get("TPU_PROFILE_STEPS", "5"))


@contextlib.contextmanager
def maybe_trace(step: int, environ=None) -> Iterator[bool]:
    """Trace this step iff profiling is enabled and step < TPU_PROFILE_STEPS.

    Yields whether the step is being traced.  Steps after the window are
    zero-overhead (no context at all beyond the env check).
    """
    d = profile_dir(environ)
    if d is None or step >= profile_steps(environ):
        yield False
        return
    import jax

    os.makedirs(d, exist_ok=True)
    with jax.profiler.StepTraceAnnotation("train", step_num=step):
        if step == 0:
            jax.profiler.start_trace(d)
        yield True
        if step == profile_steps(environ) - 1:
            jax.profiler.stop_trace()
