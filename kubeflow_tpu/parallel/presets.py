"""Named parallelism presets: the JAXJob-facing surface of §2c.

A preset maps a strategy name (what a TPUJob/JAXJob spec or the
``TPU_PARALLELISM_PRESET`` env var carries) to a concrete MeshConfig + the
attention implementation that rides it.  This is how the platform exposes
DP/FSDP/TP/SP/CP/EP without the workload hand-rolling mesh math — the
reference has no equivalent (parallelism is user-code there, SURVEY.md §2c).

    preset = get_preset("ring-cp", n_devices=16)
    mesh = build_mesh(preset.mesh, jax.devices())
    out = preset.attention(q, k, v, mesh, causal=True)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional

from ..ops.attention import multihead_attention
from ..ops.flash_attention import flash_attention
from ..ops.ring_attention import ring_attention
from ..ops.ulysses import ulysses_attention
from .mesh import MeshConfig

ENV_PRESET = "TPU_PARALLELISM_PRESET"


@dataclasses.dataclass(frozen=True)
class Preset:
    name: str
    mesh: MeshConfig
    #: attention(q, k, v, mesh, causal=...) for sharded presets;
    #: attention(q, k, v, causal=...) for single-axis presets (mesh unused)
    attention: Callable
    description: str = ""


def _dense(q, k, v, mesh=None, causal=True):
    return multihead_attention(q, k, v, causal=causal)


def _flash(q, k, v, mesh=None, causal=True):
    return flash_attention(q, k, v, causal=causal)


def get_preset(name: str, n_devices: int, tensor: int = 1, stages: int = 1) -> Preset:
    """Resolve a strategy name to a preset sized for n_devices."""
    if name in ("dp", "data"):
        return Preset(name, MeshConfig(data=n_devices, fsdp=1), _flash,
                      "pure data parallel (gradients psum over `data`)")
    if name == "fsdp":
        return Preset(name, MeshConfig(fsdp=n_devices), _flash,
                      "ZeRO-3-style sharded data parallel over ICI")
    if name in ("tp", "tensor"):
        t = tensor if tensor > 1 else 2  # tp means tensor>1; default 2
        if tensor == 1 and n_devices % 2:
            raise ValueError(f"tp preset needs an even device count, got {n_devices}")
        if n_devices % t:
            raise ValueError(f"tensor={t} does not divide {n_devices} devices")
        return Preset(name, MeshConfig(fsdp=n_devices // t, tensor=t),
                      _flash, "Megatron-style tensor parallel innermost, fsdp outer")
    if name in ("ring-cp", "ring", "cp"):
        return Preset(
            name, MeshConfig(fsdp=1, seq=n_devices),
            lambda q, k, v, mesh, causal=True: ring_attention(q, k, v, mesh, causal=causal),
            "ring attention: KV rotates the ICI ring; S scales with devices",
        )
    if name in ("ulysses", "sp"):
        return Preset(
            name, MeshConfig(fsdp=1, seq=n_devices),
            lambda q, k, v, mesh, causal=True: ulysses_attention(q, k, v, mesh, causal=causal),
            "Ulysses: head all-to-all, full-length attention per device",
        )
    if name in ("moe-ep", "ep", "expert"):
        return Preset(name, MeshConfig(fsdp=1, expert=n_devices), _flash,
                      "expert parallel: MoE FFN dispatched over `expert`")
    if name in ("pp", "pipeline"):
        s = stages if stages > 1 else 2
        if n_devices % s:
            raise ValueError(f"stages={s} does not divide {n_devices} devices")
        return Preset(name, MeshConfig(stages=s, fsdp=n_devices // s), _dense,
                      "GPipe pipeline over `stages` (parallel/pipeline.py), fsdp within")
    raise ValueError(
        f"unknown parallelism preset {name!r}; "
        "available: dp, fsdp, tp, pp, ring-cp, ulysses, moe-ep"
    )


ENV_TENSOR = "TPU_TENSOR_PARALLEL"


def preset_from_env(n_devices: int, default: str = "fsdp") -> Preset:
    """What a JAXJob worker calls: the controller sets TPU_PARALLELISM_PRESET
    (and optionally TPU_TENSOR_PARALLEL for the tp preset's axis size)."""
    return get_preset(
        os.environ.get(ENV_PRESET, default),
        n_devices,
        tensor=int(os.environ.get(ENV_TENSOR, "1")),
    )
