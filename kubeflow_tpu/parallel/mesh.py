"""Device mesh construction: the TPU-first scaling substrate.

The reference platform never looks inside the model (SURVEY.md §2c: TP/PP/SP
are user-code there).  Here parallelism is a first-class framework layer:
one ``Mesh`` with named axes, models annotated with logical shardings, XLA
inserts the collectives (scaling-book recipe: pick a mesh, annotate, let XLA
insert collectives over ICI/DCN).

Axis convention (MaxText-style):
  data   — pure data parallel, laid across DCN (between slices)
  fsdp   — ZeRO-3-style sharded data parallel, within a slice over ICI
  tensor — tensor/model parallel (Megatron-style), innermost over ICI
  seq    — sequence/context parallel (ring attention rides this axis)
  expert — MoE expert parallel
  stages — pipeline stages (sub-meshes per slice block)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "stages", "fsdp", "seq", "expert", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Sizes per axis; -1 on at most one axis means "absorb remaining devices"."""

    data: int = 1
    stages: int = 1
    fsdp: int = -1
    seq: int = 1
    expert: int = 1
    tensor: int = 1

    def sizes(self, n_devices: int) -> dict[str, int]:
        vals = {a: getattr(self, a) for a in AXES}
        fills = [a for a, v in vals.items() if v == -1]
        if len(fills) > 1:
            raise ValueError(f"at most one -1 axis, got {fills}")
        fixed = math.prod(v for v in vals.values() if v != -1)
        if fills:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            vals[fills[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {vals} needs {fixed} devices, have {n_devices}")
        return vals


def build_mesh(config: Optional[MeshConfig] = None, devices=None) -> Mesh:
    """Build the global mesh.

    Axis order puts ``data`` outermost (slowest-varying → DCN-friendly) and
    ``tensor`` innermost (fastest-varying → adjacent chips on the ICI torus),
    matching how ``jax.devices()`` orders a slice.
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.sizes(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXES)


def single_device_mesh(device=None) -> Mesh:
    d = device if device is not None else jax.devices()[0]
    return Mesh(np.array([d]).reshape((1,) * len(AXES)), AXES)
