"""Pipeline parallelism: GPipe microbatch schedule over the ``stages`` axis.

Role (SURVEY.md §2c PP row: "jax stage-sharded scan / GSPMD ``stages`` axis
across pod-slice sub-meshes").  TPU-first design — the schedule is pure GSPMD,
no shard_map:

  * layer stacks [L, ...] are regrouped into [S, L/S, ...] with the leading
    stage dim sharded over ``stages`` (each device block holds its stage's
    layers only — model memory scales 1/S);
  * activations live in a shift register [S, mb, ...] whose stage dim is
    sharded over ``stages``; each tick applies ``vmap``-ed stage compute (XLA
    partitions the vmap spatially — every stage computes simultaneously) and
    ``jnp.roll``s the register one stage forward, which XLA lowers to a
    collective-permute over the ICI ring;
  * because everything is jit-level GSPMD, PP composes freely with
    data/fsdp/tensor/seq/expert shardings in the same step, and autodiff
    derives the reverse schedule (grads ride the same ring backwards).

Bubble accounting is the GPipe classic: (S-1)/(M+S-1) of ticks are warmup/
drain — pick microbatches M >= 4·S to keep it under ~20%.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def num_stages(stage_params: Any) -> int:
    return jax.tree.leaves(stage_params)[0].shape[0]


def stack_stages(layer_params: Any, stages: int) -> Any:
    """Regroup layer-stacked params [L, ...] → stage-stacked [S, L/S, ...].

    The leading stage dim is what the model's PP sharding rules pin to the
    ``stages`` mesh axis.
    """

    def regroup(leaf):
        l = leaf.shape[0]
        if l % stages:
            raise ValueError(f"{l} layers not divisible into {stages} stages")
        return leaf.reshape(stages, l // stages, *leaf.shape[1:])

    return jax.tree.map(regroup, layer_params)


def unstack_stages(stage_params: Any) -> Any:
    """Inverse of stack_stages: [S, L/S, ...] → [L, ...]."""
    return jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), stage_params)


def gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    num_microbatches: int,
    mb_spec: Optional[P] = None,
    remat: bool = True,
    remat_policy: Optional[Callable] = None,
) -> jax.Array:
    """Run x [B, ...] through S pipeline stages, microbatched.

    ``stage_fn(params_slice, x_mb) -> x_mb`` applies ONE stage (its params
    slice has leading dim L/S); it must be shape-preserving on x and contain
    only jit-level ops (sharding constraints fine, shard_map not — the
    schedule vmaps it over the stage dim).

    ``mb_spec``: PartitionSpec of one microbatch activation [mb, ...]
    (defaults to batch over (data, fsdp)); the shift register is constrained
    to P("stages", *mb_spec).
    """
    S = num_stages(stage_params)
    M = num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible into {M} microbatches")
    mb = b // M
    if mb_spec is None:
        mb_spec = P(("data", "fsdp"))
    reg_spec = P("stages", *mb_spec)

    if remat:
        stage_fn = jax.checkpoint(
            stage_fn,
            policy=remat_policy or jax.checkpoint_policies.nothing_saveable,
        )
    vstage = jax.vmap(stage_fn)

    xs = x.reshape(M, mb, *x.shape[1:])
    state = jnp.zeros((S, mb) + x.shape[1:], x.dtype)
    outs = jnp.zeros_like(xs)

    def tick(carry, t):
        state, outs = carry
        # feed slot 0 (bubble ticks t >= M refeed the last microbatch; their
        # output falls off the end of the schedule and is never read)
        feed = jax.lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        state = jax.lax.dynamic_update_index_in_dim(state, feed.astype(state.dtype), 0, 0)
        state = jax.lax.with_sharding_constraint(state, reg_spec)
        state = vstage(stage_params, state)
        state = jax.lax.with_sharding_constraint(state, reg_spec)
        # collect the last stage's output for microbatch t-(S-1); warmup ticks
        # write garbage to slot 0, overwritten when the real t=S-1 tick lands
        out_t = jax.lax.index_in_dim(state, S - 1, 0, keepdims=False)
        j = jnp.clip(t - (S - 1), 0, M - 1)
        outs = jax.lax.dynamic_update_index_in_dim(outs, out_t, j, 0)
        # shift register: stage s's output becomes stage s+1's next input
        # (lowered to a collective-permute over the stages ring)
        state = jnp.roll(state, 1, axis=0)
        return (state, outs), None

    (_, outs), _ = jax.lax.scan(tick, (state, outs), jnp.arange(M + S - 1))
    return outs.reshape(b, *x.shape[1:])
