"""Path-rule sharding: map parameter pytree paths to PartitionSpecs.

GSPMD style: models ship a list of ``(path-regex, PartitionSpec)`` rules;
``shard_params`` resolves every leaf to a ``NamedSharding`` on the mesh.  XLA
then inserts all-gathers/reduce-scatters for fsdp, all-reduces for tensor —
no hand-written collectives in model code (SURVEY.md §2c TP/SP rows).
"""

from __future__ import annotations

import re
from typing import Any, Iterable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Iterable[tuple[str, P]]


def path_of(keypath) -> str:
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_for(path: str, rules: Rules, ndim: int) -> P:
    for pattern, spec in rules:
        if re.search(pattern, path):
            tup = tuple(spec)
            if len(tup) < ndim:  # pad leading dims (e.g. scan-stacked layers)
                tup = (None,) * (ndim - len(tup)) + tup
            return P(*tup)
    return P()


def tree_specs(tree: Any, rules: Rules) -> Any:
    """PartitionSpec pytree matching ``tree``'s structure."""
    return jax.tree_util.tree_map_with_path(
        lambda kp, leaf: spec_for(path_of(kp), rules, getattr(leaf, "ndim", 0)), tree
    )


def sanitize_spec(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Drop axes from dims they don't divide (e.g. vocab 30522 on tensor=4) —
    the MaxText-style alternative is padding; replication is the safe default."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    for d, entry in enumerate(tuple(spec)):
        if entry is None or d >= len(shape):
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        total = 1
        for a in axes:
            total *= sizes.get(a, 1)
        out.append(entry if shape[d] % total == 0 else None)
    return P(*out)


def tree_shardings(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    specs = tree_specs(tree, rules)
    return jax.tree.map(
        lambda leaf, s: NamedSharding(mesh, sanitize_spec(s, getattr(leaf, "shape", ()), mesh)),
        tree,
        specs,
    )


def shard_params(params: Any, mesh: Mesh, rules: Rules) -> Any:
    """Device-put every leaf with its resolved NamedSharding."""
    return jax.device_put(params, tree_shardings(params, mesh, rules))


def batch_spec(mesh: Mesh) -> P:
    """Input batch sharding: batch dim over (data, fsdp)."""
    return P(("data", "fsdp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh))
