"""Workload-side distributed bootstrap: consume the env the TPUJob controller
injects and form the JAX process group.

Upstream analogue (UNVERIFIED, SURVEY.md §3.1): the workload-side
``tf.distribute`` / ``torch.distributed.init_process_group`` calls that read
``TF_CONFIG`` / ``MASTER_ADDR``.  TPU-native: one call wires
``jax.distributed`` — after that, ICI collectives are compiled into XLA
programs and the platform never manages a communicator again.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ProcessEnv:
    coordinator_address: Optional[str]
    num_processes: int
    process_id: int
    num_slices: int
    slice_id: int

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def read_env(environ=None) -> ProcessEnv:
    env = environ if environ is not None else os.environ
    return ProcessEnv(
        coordinator_address=env.get("JAX_COORDINATOR_ADDRESS"),
        num_processes=int(env.get("JAX_NUM_PROCESSES", "1")),
        process_id=int(env.get("JAX_PROCESS_ID", "0")),
        num_slices=int(env.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(env.get("MEGASCALE_SLICE_ID", "0")),
    )


def initialize(local_device_count: Optional[int] = None) -> ProcessEnv:
    """Join the job's process group (no-op for single-process jobs).

    ``local_device_count`` forces N virtual CPU devices per process — the
    simulator's stand-in for a TPU host's chips (tests use 1–2; a real v5e
    host exposes 4 without any flag).
    """
    penv = read_env()
    import jax

    if local_device_count is not None:
        # config (not env): some sandboxes pre-set jax_platforms at interpreter
        # start via sitecustomize, which masks JAX_PLATFORMS/XLA_FLAGS env vars.
        # Best-effort: raises only inside jax.config if backends already
        # initialized — in that case keep the existing device set.
        try:
            if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
                jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_num_cpu_devices", local_device_count)
        except RuntimeError:
            pass  # backends already initialized; device count is fixed
        # export for child processes (kubelet pods copy os.environ)
        os.environ.setdefault("JAX_NUM_CPU_DEVICES", str(local_device_count))

    if penv.is_distributed:
        jax.distributed.initialize(
            coordinator_address=penv.coordinator_address,
            num_processes=penv.num_processes,
            process_id=penv.process_id,
        )
    return penv
