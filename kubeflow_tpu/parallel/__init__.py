"""Parallelism layer (SURVEY.md §2c): mesh, shardings, distributed init,
named presets (DP/FSDP/TP/SP/CP/EP) consumed by JAXJob workloads."""

from .mesh import MeshConfig, build_mesh  # noqa: F401
from .presets import Preset, get_preset, preset_from_env  # noqa: F401
