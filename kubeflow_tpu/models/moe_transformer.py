"""Composed-parallelism flagship: MoE transformer using EVERY mesh axis.

Role: VERDICT r1 weak #3 — the multi-chip proof must compose the long-context
layer into one training step, not test axes in isolation.  This model runs

  * ``stages``  — GPipe over the block stack (parallel/pipeline.py),
  * ``seq``     — GSPMD sequence parallelism: activations sharded on the
                  sequence dim between blocks (XLA inserts the K/V
                  all-gathers inside attention),
  * ``expert``  — MoE FFN with expert-sharded weights (ops/moe.py),
  * ``fsdp``/``tensor``/``data`` — the vanilla axes, same rules as BERT,

all in ONE jitted fwd+bwd+optimizer step (see __graft_entry__.dryrun_multichip
and tests/test_pipeline.py).  Design is pure GSPMD — no shard_map — so every
combination of axis sizes compiles from the same code.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..ops.attention import multihead_attention
from ..ops.moe import MoEConfig, moe_ffn
from ..parallel.pipeline import gpipe, stack_stages


@dataclass(frozen=True)
class MoeTransformerConfig:
    vocab_size: int = 512
    d_model: int = 64
    n_layers: int = 2
    n_heads: int = 4
    num_experts: int = 4
    top_k: int = 1
    capacity_factor: float = 2.0
    max_position: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    pipeline_stages: int = 1
    pipeline_microbatches: int = 2

    @property
    def d_ff(self) -> int:
        return self.d_model * 4

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def moe(self) -> MoEConfig:
        return MoEConfig(num_experts=self.num_experts, top_k=self.top_k,
                         capacity_factor=self.capacity_factor,
                         d_model=self.d_model, d_ff=self.d_ff)


SHARDING_RULES = (
    (r"^embed$", P(None, "fsdp")),
    (r"^unembed$", P("fsdp", "tensor")),
    # layer-stacked [L, ...]: leading dim rides `stages`
    (r"layers/wqkv", P("stages", None, None, "tensor", None)),     # [L,d,3,nh,hd]
    (r"layers/wo$", P("stages", "tensor", None, None)),            # [L,nh,hd,d]
    (r"layers/router", P("stages", None, None)),                   # [L,d,E]
    (r"layers/wi_moe", P("stages", "expert", None, "tensor")),     # [L,E,d,f]
    (r"layers/wo_moe", P("stages", "expert", "tensor", None)),     # [L,E,f,d]
    (r".*", P()),
)


def init(key: jax.Array, config: MoeTransformerConfig) -> dict:
    d, nh, hd, l = config.d_model, config.n_heads, config.head_dim, config.n_layers
    E, f = config.num_experts, config.d_ff
    ks = iter(jax.random.split(key, 8))
    s = d ** -0.5
    return {
        "embed": jax.random.normal(next(ks), (config.vocab_size, d), jnp.float32) * 0.02,
        "pos": jax.random.normal(next(ks), (config.max_position, d), jnp.float32) * 0.02,
        "layers": {
            "wqkv": jax.random.normal(next(ks), (l, d, 3, nh, hd), jnp.float32) * s,
            "wo": jax.random.normal(next(ks), (l, nh, hd, d), jnp.float32) * s,
            "ln1": jnp.ones((l, d), jnp.float32),
            "ln2": jnp.ones((l, d), jnp.float32),
            "router": jax.random.normal(next(ks), (l, d, E), jnp.float32) * 0.02,
            "wi_moe": jax.random.normal(next(ks), (l, E, d, f), jnp.float32) * s,
            "wo_moe": jax.random.normal(next(ks), (l, E, f, d), jnp.float32) * (f ** -0.5),
        },
        "unembed": jax.random.normal(next(ks), (d, config.vocab_size), jnp.float32) * s,
    }


def _rms(x, scale):
    x32 = x.astype(jnp.float32)
    n = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + 1e-6)
    return (n * scale).astype(x.dtype)


def _seq_constraint(x):
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or mesh.empty:
            return x  # unsharded reference path (no mesh in context)
    except Exception:
        return x
    return jax.lax.with_sharding_constraint(x, P(("data", "fsdp"), "seq", None))


def _block(config: MoeTransformerConfig, x, lp):
    """One transformer block: causal attention + MoE FFN (shape-preserving)."""
    dt = config.dtype
    xn = _rms(x, lp["ln1"])
    qkv = jnp.einsum("bsd,dknh->bsknh", xn, lp["wqkv"].astype(dt))
    attn = multihead_attention(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal=True)
    x = x + jnp.einsum("bsnh,nhd->bsd", attn, lp["wo"].astype(dt))
    x = _seq_constraint(x)
    xn = _rms(x, lp["ln2"])
    moe_params = {"router": lp["router"], "wi": lp["wi_moe"].astype(dt),
                  "wo": lp["wo_moe"].astype(dt)}
    # shard=False: the expert sharding comes from the weight rules; an inner
    # constraint would see vmap-batched shapes under the pipeline schedule
    out, aux = moe_ffn(moe_params, xn, config.moe, shard=False)
    return _seq_constraint(x + out), aux


def forward(params: dict, config: MoeTransformerConfig, tokens: jax.Array) -> jax.Array:
    """[B, S] ids → [B, S, V] logits (aux losses dropped — dryrun/throughput
    path; single-stage training can thread them via _block directly)."""
    dt = config.dtype
    b, s = tokens.shape
    x = (params["embed"][tokens] + params["pos"][None, :s]).astype(dt)
    x = _seq_constraint(x)

    if config.pipeline_stages > 1:
        staged = stack_stages(params["layers"], config.pipeline_stages)

        def stage(lp, xmb):
            def one(c, lpi):
                y, _ = _block(config, c, lpi)
                return y, None
            y, _ = jax.lax.scan(one, xmb, lp)
            return y

        x = gpipe(stage, staged, x, config.pipeline_microbatches,
                  mb_spec=P(("data", "fsdp"), "seq", None))
    else:
        def one(c, lpi):
            y, _ = _block(config, c, lpi)
            return y, None
        x, _ = jax.lax.scan(one, x, params["layers"])

    return jnp.einsum("bsd,dv->bsv", x, params["unembed"].astype(dt))


def lm_loss(params: dict, config: MoeTransformerConfig, tokens: jax.Array) -> jax.Array:
    logits = forward(params, config, tokens[:, :-1]).astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, tokens[:, 1:]
    ).mean()
