"""Trainable decoder LM family: Llama-3 / Gemma-class (BASELINE configs 3,4).

The architecture (GQA + RoPE + RMSNorm + SwiGLU) is shared with the serving
engine (serving/engine/model.py owns the paged-decode path; this module owns
training): importing the same init/forward keeps the fine-tune→deploy
pipeline honest — the weights trained here serve there unchanged.

``gemma_7b`` uses the EXACT Gemma-1 semantics (r4): GeGLU activation,
sqrt(d_model) input-embedding scaling, decoupled head_dim=256 — the same
config flags hf_convert sets for real Gemma checkpoints, so the Pipelines
Gemma benchmark (BASELINE.json config[4]) fine-tunes the true block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..serving.engine.model import DecoderConfig, forward_full, init  # noqa: F401

# params are layer-stacked ([n_layers, ...] leading dim, engine init layout)
SHARDING_RULES = (
    (r"^embed$", P("tensor", "fsdp")),
    (r"^w[qkv]$", P(None, "fsdp", "tensor")),
    (r"^wo$", P(None, "tensor", "fsdp")),
    (r"^w[13]$", P(None, "fsdp", "tensor")),
    (r"^w2$", P(None, "tensor", "fsdp")),
    (r"^unembed$", P("fsdp", "tensor")),
    (r".*", P()),
)


def gemma_7b() -> DecoderConfig:
    return DecoderConfig(
        vocab_size=256128, d_model=3072, n_layers=28, n_heads=16,
        n_kv_heads=16, d_ff=24576, rope_theta=10000.0,
        head_dim_override=256, act="gelu_tanh", scale_embed=True,
        norm_eps=1e-6,
    )


def tiny(vocab_size: int = 512) -> DecoderConfig:
    """Test/CI-scale config (same family, minutes-not-hours)."""
    return DecoderConfig(vocab_size=vocab_size, d_model=64, n_layers=2,
                         n_heads=4, n_kv_heads=2, d_ff=128)


def lm_loss(params: dict, config: DecoderConfig, tokens: jax.Array) -> jax.Array:
    """Next-token cross-entropy over [B, S] token ids (causal shift inside)."""
    logits = forward_full(params, config, tokens[:, :-1])       # [B, S-1, V]
    targets = tokens[:, 1:]
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    ).mean()


def train_flops(config: DecoderConfig, batch: int, seq_len: int) -> float:
    """6·N·D matmul FLOPs (fwd+bwd) + attention term, for MFU accounting."""
    n = config.param_count() - config.vocab_size * config.d_model  # embed lookup is free
    # per token QK^T+PV over the ATTENTION width (n_heads*head_dim — not
    # d_model: gemma-7b decouples them, 4096 vs 3072)
    attn = config.n_layers * 2 * seq_len * config.n_heads * config.head_dim
    return 6 * batch * seq_len * (n + attn / 2)


def synthetic_lm_batches(vocab_size: int, batch_size: int, seq_len: int, seed: int = 0):
    """Markov-ish synthetic token stream (learnable: next ≈ f(current))."""
    key = jax.random.PRNGKey(seed)
    while True:
        key, k1, k2 = jax.random.split(key, 3)
        start = jax.random.randint(k1, (batch_size, 1), 0, vocab_size)
        steps = jax.random.randint(k2, (batch_size, seq_len - 1), 0, 3)
        toks = jnp.concatenate([start, jnp.cumsum(steps, axis=1) + start], axis=1) % vocab_size
        yield {"tokens": toks.astype(jnp.int32)}
