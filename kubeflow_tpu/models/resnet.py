"""ResNet-50: the PyTorchJob DDP benchmark workload (BASELINE.json config[1]).

TPU-first choices (not a torch port):
  * NHWC + bf16 — XLA's native TPU conv layout, MXU-friendly;
  * GroupNorm instead of BatchNorm: identical quality class for ResNet-50,
    but stateless — no running-stats buffers to all-reduce, no train/eval
    divergence, and the whole step stays a pure function (jit/pjit clean).
    This is the standard JAX rewrite of torchvision's BN ResNet;
  * data parallelism comes from the platform (mesh ``data``/``fsdp`` axes +
    the PyTorchJob-compat operator wiring rendezvous), not from the model.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

STAGES_50 = (3, 4, 6, 3)


@dataclass(frozen=True)
class ResNetConfig:
    stages: tuple = STAGES_50
    width: int = 64
    num_classes: int = 1000
    groups: int = 32  # GroupNorm groups
    dtype: jnp.dtype = jnp.bfloat16

    def flops_per_image(self) -> float:
        """Matmul-equivalent fwd FLOPs for 224×224 (the standard ~4.1 GFLOP)."""
        return 4.1e9


def count_params(params: dict) -> int:
    return sum(int(jnp.size(x)) for x in jax.tree.leaves(params))


SHARDING_RULES = (
    (r"fc_kernel", P("fsdp", "tensor")),
    (r".*conv.*", P(None, None, None, "fsdp")),
    (r".*", P()),
)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (2.0 / fan_in) ** 0.5).astype(dtype)


def init(key: jax.Array, config: ResNetConfig = ResNetConfig()) -> dict:
    keys = iter(jax.random.split(key, 256))
    dt = config.dtype
    w = config.width
    params: dict = {
        "stem_conv": _conv_init(next(keys), 7, 7, 3, w, dt),
        "stem_gn": {"scale": jnp.ones((w,), dt), "bias": jnp.zeros((w,), dt)},
        "blocks": [],
    }
    cin = w
    for stage, n_blocks in enumerate(config.stages):
        mid = w * (2 ** stage)
        cout = mid * 4
        for b in range(n_blocks):
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid, dt),
                "gn1": {"scale": jnp.ones((mid,), dt), "bias": jnp.zeros((mid,), dt)},
                "conv2": _conv_init(next(keys), 3, 3, mid, mid, dt),
                "gn2": {"scale": jnp.ones((mid,), dt), "bias": jnp.zeros((mid,), dt)},
                "conv3": _conv_init(next(keys), 1, 1, mid, cout, dt),
                # zero-init the last norm scale: residual branch starts as
                # identity (the standard ResNet trick for stable large-batch)
                "gn3": {"scale": jnp.zeros((cout,), dt), "bias": jnp.zeros((cout,), dt)},
            }
            if b == 0:
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, dt)
                blk["proj_gn"] = {"scale": jnp.ones((cout,), dt), "bias": jnp.zeros((cout,), dt)}
            params["blocks"].append(blk)
            cin = cout
    params["fc_kernel"] = (jax.random.normal(next(keys), (cin, config.num_classes), jnp.float32) * cin ** -0.5).astype(dt)
    params["fc_bias"] = jnp.zeros((config.num_classes,), dt)
    return params


def _conv(x, kernel, stride):
    return jax.lax.conv_general_dilated(
        x, kernel, (stride, stride), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


def _group_norm(x, gn, groups, eps=1e-5):
    b, h, w, c = x.shape
    g = min(groups, c)
    xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (xf.reshape(b, h, w, c) * gn["scale"].astype(jnp.float32) + gn["bias"].astype(jnp.float32)).astype(x.dtype)


def forward(params: dict, config: ResNetConfig, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] → logits [B, num_classes]."""
    x = images.astype(config.dtype)
    x = _conv(x, params["stem_conv"], 2)
    x = jax.nn.relu(_group_norm(x, params["stem_gn"], config.groups))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    # strides are STATIC structure (from config), never params: jit traces
    # params, and a conv stride must be a compile-time constant
    strides = [
        2 if (b == 0 and stage > 0) else 1
        for stage, n_blocks in enumerate(config.stages)
        for b in range(n_blocks)
    ]
    for blk, stride in zip(params["blocks"], strides):
        residual = x
        y = jax.nn.relu(_group_norm(_conv(x, blk["conv1"], 1), blk["gn1"], config.groups))
        y = jax.nn.relu(_group_norm(_conv(y, blk["conv2"], stride), blk["gn2"], config.groups))
        y = _group_norm(_conv(y, blk["conv3"], 1), blk["gn3"], config.groups)
        if "proj" in blk:
            residual = _group_norm(_conv(x, blk["proj"], stride), blk["proj_gn"], config.groups)
        x = jax.nn.relu(residual + y)
    x = x.mean(axis=(1, 2))
    return (x @ params["fc_kernel"] + params["fc_bias"]).astype(jnp.float32)


def loss(params: dict, config: ResNetConfig, images: jax.Array, labels: jax.Array) -> jax.Array:
    logits = forward(params, config, images)
    onehot = jax.nn.one_hot(labels, config.num_classes)
    return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))


def synthetic_batch(key: jax.Array, batch_size: int, image_size: int = 224, num_classes: int = 1000) -> dict:
    kl, kn = jax.random.split(key)
    labels = jax.random.randint(kl, (batch_size,), 0, num_classes)
    images = jax.random.normal(kn, (batch_size, image_size, image_size, 3))
    return {"images": images, "labels": labels}
