"""TPU-native Kubeflow-capability platform."""
