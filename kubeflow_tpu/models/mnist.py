"""MNIST CNN: the TFJob benchmark workload (BASELINE.json config[0]).

The classic two-conv CNN the reference's TFJob MNIST examples train.
TPU notes: NHWC layout (XLA's native conv layout on TPU), bf16 weights with
f32 loss math, static shapes throughout — the whole step jits to a handful
of fused convolutions on the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class MnistConfig:
    num_classes: int = 10
    conv1_features: int = 32
    conv2_features: int = 64
    dense_features: int = 512
    dtype: jnp.dtype = jnp.bfloat16

    @property
    def num_params(self) -> int:
        c1, c2, d = self.conv1_features, self.conv2_features, self.dense_features
        return (25 * c1 + c1) + (25 * c1 * c2 + c2) + (49 * c2 * d + d) + (d * self.num_classes + self.num_classes)


SHARDING_RULES = (
    (r"dense_kernel", P("fsdp", "tensor")),
    (r"out_kernel", P("tensor", None)),
    (r".*", P()),
)


def init(key: jax.Array, config: MnistConfig = MnistConfig()) -> dict:
    c1, c2, d = config.conv1_features, config.conv2_features, config.dense_features
    k = iter(jax.random.split(key, 4))
    he = lambda k_, shape, fan_in: (jax.random.normal(k_, shape, jnp.float32) * (2.0 / fan_in) ** 0.5).astype(config.dtype)
    return {
        "conv1_kernel": he(next(k), (5, 5, 1, c1), 25),
        "conv1_bias": jnp.zeros((c1,), config.dtype),
        "conv2_kernel": he(next(k), (5, 5, c1, c2), 25 * c1),
        "conv2_bias": jnp.zeros((c2,), config.dtype),
        "dense_kernel": he(next(k), (49 * c2, d), 49 * c2),
        "dense_bias": jnp.zeros((d,), config.dtype),
        "out_kernel": he(next(k), (d, config.num_classes), d),
        "out_bias": jnp.zeros((config.num_classes,), config.dtype),
    }


def _max_pool_2x2(x):
    return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "SAME")


def forward(params: dict, config: MnistConfig, images: jax.Array) -> jax.Array:
    """images [B, 28, 28, 1] → logits [B, 10]."""
    x = images.astype(config.dtype)
    for i in (1, 2):
        x = jax.lax.conv_general_dilated(
            x, params[f"conv{i}_kernel"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params[f"conv{i}_bias"]
        x = jax.nn.relu(x)
        x = _max_pool_2x2(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense_kernel"] + params["dense_bias"])
    return (x @ params["out_kernel"] + params["out_bias"]).astype(jnp.float32)


def loss(params: dict, config: MnistConfig, images: jax.Array, labels: jax.Array) -> jax.Array:
    logits = forward(params, config, images)
    onehot = jax.nn.one_hot(labels, config.num_classes)
    return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))


def accuracy(params: dict, config: MnistConfig, images: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((forward(params, config, images).argmax(-1) == labels).astype(jnp.float32))


def synthetic_batch(key: jax.Array, batch_size: int) -> dict:
    """Deterministic class-structured fake MNIST (labels recoverable → the
    model can actually fit it, which the loss-decreases tests rely on)."""
    kl, kn = jax.random.split(key)
    labels = jax.random.randint(kl, (batch_size,), 0, 10)
    base = jax.nn.one_hot(labels, 28)[:, :, None] * jnp.ones((1, 1, 28))
    noise = 0.3 * jax.random.normal(kn, (batch_size, 28, 28))
    return {"images": (base + noise)[..., None], "labels": labels}
