"""BERT flagship: pure-functional JAX encoder with MLM head.

The platform's north-star training workload (BASELINE.json: "train BERT-base
on a v5e-16 slice at >=90% reference MFU").  TPU-first choices:

  * layers stacked on a leading axis + ``lax.scan`` — one traced layer,
    O(1) compile time, remat-friendly;
  * params fp32 masters, compute in bf16 (MXU-native);
  * sharding via path rules (parallel/sharding.py): fsdp shards the embed
    dim, tensor shards heads/ffn, so the same model runs 1-chip or v5e-16
    by changing only the MeshConfig;
  * embedding tied to the MLM output projection.

Upstream parity note: the reference platform carries no model code at all
(SURVEY.md §0 — Kubeflow schedules other people's training code); this model
family is the workload layer the TPU rebuild must add (SURVEY.md §5
"long-context ... workload-layer feature we must add").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..ops.attention import multihead_attention, padding_mask
from ..ops.flash_attention import flash_attention


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.bfloat16
    # rematerialize each encoder layer in backward (trade extra FLOPs for
    # O(L) → O(1) activation memory; lets batch 1024 fit one v5e chip)
    remat: bool = False
    # remat policy: "nothing" = full recompute (max memory savings, ~1/3 extra
    # encoder FLOPs); "dots" = save matmul outputs that lack batch dims (the
    # projections: qkv/out/mlp), recompute only elementwise + attention — the
    # standard transformer sweet spot (recompute is cheap, memory stays O(1)
    # in depth for the big [B,S,F] tensors)
    # "nothing" | "dots" | "save_qkv" | "save_attn" (checkpoint_name-based:
    # keep the named projection outputs, recompute the rest)
    remat_policy: str = "nothing"
    # attention impl in the encoder: "dense" materializes [B,H,S,T] logits;
    # "flash" uses the Pallas kernel (ops/flash_attention.py) whose custom
    # VJP recomputes P blockwise — no [B,H,S,T] tensor ever hits HBM. Both
    # honor the key-side padding mask (flash masks padded keys in-kernel),
    # so variable-length batches run through either path.
    attention: str = "dense"
    # pipeline parallelism (SURVEY.md §2c PP row): >1 runs the encoder stack
    # as a GPipe schedule over the `stages` mesh axis (parallel/pipeline.py);
    # num_layers must divide into stages, batch into microbatches
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_params(self) -> int:
        h, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        per_layer = 4 * h * h + 2 * h * f + 9 * h + f  # qkv/o + ffn kernels, biases, 2 lns
        embed = (v + self.max_position + self.type_vocab_size) * h + 2 * h
        head = h * h + h + 2 * h + v  # transform + ln + bias (embedding tied)
        return self.num_layers * per_layer + embed + head

    def flops_per_token(self, seq_len: int) -> float:
        """Fwd+bwd matmul FLOPs per token (6ND + attention term), full head."""
        h, f, l = self.hidden_size, self.intermediate_size, self.num_layers
        matmul_params = l * (4 * h * h + 2 * h * f) + self.hidden_size * self.vocab_size
        attn = l * 2 * 2 * seq_len * h  # QK^T + PV per token
        return 6 * (matmul_params + attn / 2)

    def train_flops(self, batch: int, seq_len: int, num_predictions: Optional[int] = None) -> float:
        """Fwd+bwd matmul FLOPs for one batch; MLM head on P positions only."""
        h, f, l, v = self.hidden_size, self.intermediate_size, self.num_layers, self.vocab_size
        p = seq_len if num_predictions is None else num_predictions
        encoder = l * (4 * h * h + 2 * h * f) * seq_len
        attn = l * 2 * seq_len * seq_len * h
        head = (h * h + h * v) * p
        return 6 * batch * (encoder + attn + head)


# ----------------------------------------------------------------- sharding

SHARDING_RULES = (
    # embeddings: vocab on tensor, embed on fsdp
    (r"embeddings/(word|position|type)", P("tensor", "fsdp")),
    (r"embeddings/ln_", P()),
    # attention: qkv fused kernel [h, 3, nh, hd] → heads on tensor
    (r"layers/attn_qkv_kernel", P("fsdp", None, "tensor", None)),
    (r"layers/attn_qkv_bias", P(None, "tensor", None)),
    (r"layers/attn_out_kernel", P("tensor", None, "fsdp")),
    # mlp: ffn dim on tensor
    (r"layers/mlp_in_kernel", P("fsdp", "tensor")),
    (r"layers/mlp_in_bias", P("tensor")),
    (r"layers/mlp_out_kernel", P("tensor", "fsdp")),
    # everything else (lns, small biases): replicated
    (r".*", P()),
)


def pp_sharding_rules() -> tuple:
    """SHARDING_RULES variant for pipeline parallelism: the layer-stack dim
    (leading dim of every layers/* leaf) is pinned to the `stages` mesh axis,
    so each stage's device block holds only its own layers."""
    out = []
    for pat, spec in SHARDING_RULES:
        if pat.startswith("layers/"):
            out.append((pat, P("stages", *tuple(spec))))
        else:
            out.append((pat, spec))
    return tuple(out)


# --------------------------------------------------------------------- init

def init(key: jax.Array, config: BertConfig) -> dict:
    h, f = config.hidden_size, config.intermediate_size
    l, nh, hd = config.num_layers, config.num_heads, config.head_dim
    k = iter(jax.random.split(key, 16))

    def dense(key, shape, scale=0.02):
        return (jax.random.normal(key, shape) * scale).astype(jnp.float32)

    return {
        "embeddings": {
            "word": dense(next(k), (config.vocab_size, h)),
            "position": dense(next(k), (config.max_position, h)),
            "type": dense(next(k), (config.type_vocab_size, h)),
            "ln_scale": jnp.ones((h,), jnp.float32),
            "ln_bias": jnp.zeros((h,), jnp.float32),
        },
        # layer-stacked params: leading dim = num_layers (for lax.scan)
        "layers": {
            "attn_qkv_kernel": dense(next(k), (l, h, 3, nh, hd)),
            "attn_qkv_bias": jnp.zeros((l, 3, nh, hd), jnp.float32),
            "attn_out_kernel": dense(next(k), (l, nh, hd, h)),
            "attn_out_bias": jnp.zeros((l, h), jnp.float32),
            "ln1_scale": jnp.ones((l, h), jnp.float32),
            "ln1_bias": jnp.zeros((l, h), jnp.float32),
            "mlp_in_kernel": dense(next(k), (l, h, f)),
            "mlp_in_bias": jnp.zeros((l, f), jnp.float32),
            "mlp_out_kernel": dense(next(k), (l, f, h)),
            "mlp_out_bias": jnp.zeros((l, h), jnp.float32),
            "ln2_scale": jnp.ones((l, h), jnp.float32),
            "ln2_bias": jnp.zeros((l, h), jnp.float32),
        },
        "mlm": {
            "transform_kernel": dense(next(k), (h, h)),
            "transform_bias": jnp.zeros((h,), jnp.float32),
            "ln_scale": jnp.ones((h,), jnp.float32),
            "ln_bias": jnp.zeros((h,), jnp.float32),
            "output_bias": jnp.zeros((config.vocab_size,), jnp.float32),
        },
    }


# ------------------------------------------------------------------ forward

def _layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias
    return out.astype(x.dtype)


def encode(params: dict, config: BertConfig, input_ids: jax.Array,
           attention_mask: Optional[jax.Array] = None,
           token_type_ids: Optional[jax.Array] = None) -> jax.Array:
    """[B, S] ids → [B, S, H] hidden states."""
    dt = config.dtype
    emb = params["embeddings"]
    b, s = input_ids.shape
    x = emb["word"][input_ids]
    x = x + emb["position"][None, :s]
    if token_type_ids is not None:
        x = x + emb["type"][token_type_ids]
    else:
        x = x + emb["type"][0]
    x = _layer_norm(x.astype(dt), emb["ln_scale"], emb["ln_bias"], config.layer_norm_eps)

    mask = padding_mask(attention_mask) if attention_mask is not None else None

    def layer(x, lp):
        xn = x
        qkv = jnp.einsum("bsh,hknd->bsknd", xn, lp["attn_qkv_kernel"].astype(dt))
        qkv = qkv + lp["attn_qkv_bias"].astype(dt)
        qkv = checkpoint_name(qkv, "qkv")
        q, k_, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if config.attention == "flash":
            # kv_mask: key-side padding exclusion inside the kernel — real
            # variable-length MLM batches run through flash (VERDICT r2 #5)
            attn = flash_attention(q, k_, v, causal=False,
                                   kv_mask=attention_mask)
        else:
            attn = multihead_attention(q, k_, v, mask=mask)
        attn = checkpoint_name(attn, "attn_out")
        attn = jnp.einsum("bsnd,ndh->bsh", attn, lp["attn_out_kernel"].astype(dt))
        attn = attn + lp["attn_out_bias"].astype(dt)
        attn = checkpoint_name(attn, "attn_proj")
        x = _layer_norm(x + attn, lp["ln1_scale"], lp["ln1_bias"], config.layer_norm_eps)

        hmid = jnp.einsum("bsh,hf->bsf", x, lp["mlp_in_kernel"].astype(dt))
        hmid = checkpoint_name(hmid, "ffn1")
        hmid = jax.nn.gelu(hmid + lp["mlp_in_bias"].astype(dt))
        hout = jnp.einsum("bsf,fh->bsh", hmid, lp["mlp_out_kernel"].astype(dt))
        hout = hout + lp["mlp_out_bias"].astype(dt)
        hout = checkpoint_name(hout, "ffn2")
        x = _layer_norm(x + hout, lp["ln2_scale"], lp["ln2_bias"], config.layer_norm_eps)
        return x, None

    if config.pipeline_stages > 1:
        # GPipe over the `stages` mesh axis: each stage scans its local
        # layer slice; gpipe handles microbatching + remat per stage tick
        from ..parallel.pipeline import gpipe, stack_stages

        if mask is not None:
            raise ValueError(
                "pipeline_stages > 1 requires attention_mask=None (the mask "
                "is full-batch shaped; microbatches would mis-slice it) — "
                "use packed/full-length sequences under pipeline parallelism"
            )
        staged = stack_stages(params["layers"], config.pipeline_stages)

        def stage(lp, xmb):
            y, _ = jax.lax.scan(layer, xmb, lp)
            return y

        return gpipe(stage, staged, x, config.pipeline_microbatches,
                     mb_spec=P(("data", "fsdp"), None, None), remat=config.remat,
                     remat_policy=_remat_policy(config))

    if config.remat:
        layer = jax.checkpoint(layer, policy=_remat_policy(config))
    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x


def _remat_policy(config: BertConfig):
    cp = jax.checkpoint_policies
    return {
        "nothing": cp.nothing_saveable,
        "dots": cp.dots_with_no_batch_dims_saveable,
        "save_qkv": cp.save_only_these_names("qkv"),
        "save_attn": cp.save_only_these_names("qkv", "attn_out"),
        # every matmul output saved explicitly — backward recomputes only
        # elementwise ops (layernorm/gelu/softmax) and the two attention
        # einsums (~3% of step FLOPs at seq 128), so the remat tax all but
        # vanishes while peak memory stays ~10·B·S·H/layer (fits batch 256
        # on one v5e chip).  Same saved set dots_with_no_batch_dims_saveable
        # converges to, but the explicit name list sidesteps that policy's
        # compile-time churn (observed >280s on the chip tunnel).
        "save_mlp": cp.save_only_these_names(
            "qkv", "attn_out", "attn_proj", "ffn1", "ffn2"),
    }[config.remat_policy]


def mlm_logits(params: dict, config: BertConfig, hidden: jax.Array) -> jax.Array:
    """MLM head with tied embeddings: [B, S, H] → [B, S, V]."""
    dt = config.dtype
    mlm = params["mlm"]
    h = jnp.einsum("bsh,hk->bsk", hidden, mlm["transform_kernel"].astype(dt))
    h = jax.nn.gelu(h + mlm["transform_bias"].astype(dt))
    h = _layer_norm(h, mlm["ln_scale"], mlm["ln_bias"], config.layer_norm_eps)
    logits = jnp.einsum("bsh,vh->bsv", h, params["embeddings"]["word"].astype(dt))
    return logits + mlm["output_bias"].astype(dt)


def forward(params: dict, config: BertConfig, input_ids: jax.Array,
            attention_mask: Optional[jax.Array] = None) -> jax.Array:
    return mlm_logits(params, config, encode(params, config, input_ids, attention_mask))


def mlm_loss(params: dict, config: BertConfig, input_ids: jax.Array,
             labels: jax.Array, attention_mask: Optional[jax.Array] = None,
             max_predictions: Optional[int] = None) -> jax.Array:
    """Masked-LM cross entropy; positions with label == -100 are ignored.

    ``max_predictions``: gather only (up to) P masked positions per sequence
    before the vocab projection — the [B, S, V] logits tensor becomes
    [B, P, V] (~6x less HBM and vocab-matmul FLOPs at 15% masking; standard
    BERT pretraining uses P=20 for seq 128).
    """
    hidden = encode(params, config, input_ids, attention_mask)
    valid = labels != -100
    if max_predictions is not None:
        # indices of masked positions, padded with unmasked (weight-0) slots
        weights, idx = jax.lax.top_k(valid.astype(jnp.int32), max_predictions)
        hidden = jnp.take_along_axis(hidden, idx[..., None], axis=1)
        labels = jnp.take_along_axis(labels, idx, axis=1)
        valid = weights.astype(bool)
    logits = mlm_logits(params, config, hidden).astype(jnp.float32)
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    token_loss = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(valid.sum(), 1)
    return (token_loss * valid).sum() / denom
