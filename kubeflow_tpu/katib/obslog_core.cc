// Katib observation-log store core (db-manager equivalent).
//
// Role in the stack (SURVEY.md §2a "Katib: db-manager + UI" row): upstream
// Katib runs a Go gRPC façade (ReportObservationLog / GetObservationLog)
// over MySQL — a native store — so intermediate metric time series survive
// trial pod GC and back both early stopping and the UI.  This is the
// TPU-native rebuild's equivalent native core: per-(trial, metric) series
// with an append-only WAL for crash-safe persistence, bound from Python via
// ctypes (obslog.py).  Same WAL framing as metadata_core.cc: u8 op |
// u32 payload_len | payload; truncated tails are dropped at replay.
//
// WAL payload (op OP_REPORT): lp(trial) | lp(metric) | i64 step | f64 value
// where lp(s) = u32 length + bytes, little-endian.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Point {
  int64_t step;
  double value;
};

void put_u32(std::string* out, uint32_t v) { out->append(reinterpret_cast<char*>(&v), 4); }
void put_i64(std::string* out, int64_t v) { out->append(reinterpret_cast<char*>(&v), 8); }
void put_f64(std::string* out, double v) { out->append(reinterpret_cast<char*>(&v), 8); }
void put_lp(std::string* out, const std::string& s) {
  put_u32(out, static_cast<uint32_t>(s.size()));
  out->append(s);
}

struct Reader {
  const char* p;
  const char* end;
  bool ok = true;
  uint32_t u32() {
    if (p + 4 > end) { ok = false; return 0; }
    uint32_t v; std::memcpy(&v, p, 4); p += 4; return v;
  }
  int64_t i64() {
    if (p + 8 > end) { ok = false; return 0; }
    int64_t v; std::memcpy(&v, p, 8); p += 8; return v;
  }
  double f64() {
    if (p + 8 > end) { ok = false; return 0.0; }
    double v; std::memcpy(&v, p, 8); p += 8; return v;
  }
  std::string lp() {
    uint32_t n = u32();
    if (!ok || p + n > end) { ok = false; return ""; }
    std::string s(p, n); p += n; return s;
  }
};

struct Store {
  std::mutex mu;
  std::string wal_path;  // empty → in-memory only
  FILE* wal = nullptr;

  // trial + '\0' + metric → ordered series
  std::unordered_map<std::string, std::vector<Point>> series;
  // insertion-ordered trial list and per-trial metric list (UI listings are
  // deterministic; std::map keeps metric names sorted per trial)
  std::vector<std::string> trials;
  std::unordered_map<std::string, std::map<std::string, int>> trial_metrics;

  std::string scratch;  // last query result, drained by obs_read_buffer
};

enum Op : uint8_t { OP_REPORT = 1 };

void apply(Store* st, uint8_t op, const std::string& payload) {
  if (op != OP_REPORT) return;
  Reader r{payload.data(), payload.data() + payload.size()};
  std::string trial = r.lp();
  std::string metric = r.lp();
  int64_t step = r.i64();
  double value = r.f64();
  if (!r.ok) return;
  if (!st->trial_metrics.count(trial)) st->trials.push_back(trial);
  st->trial_metrics[trial][metric] += 1;
  st->series[trial + '\0' + metric].push_back(Point{step, value});
}

void wal_append(Store* st, uint8_t op, const std::string& payload) {
  if (!st->wal) return;
  uint32_t n = static_cast<uint32_t>(payload.size());
  fwrite(&op, 1, 1, st->wal);
  fwrite(&n, 4, 1, st->wal);
  fwrite(payload.data(), 1, n, st->wal);
  fflush(st->wal);
}

void replay(Store* st) {
  FILE* f = fopen(st->wal_path.c_str(), "rb");
  if (!f) return;
  std::string payload;
  for (;;) {
    uint8_t op;
    uint32_t n;
    if (fread(&op, 1, 1, f) != 1) break;
    if (fread(&n, 4, 1, f) != 1) break;
    payload.resize(n);
    if (n && fread(&payload[0], 1, n, f) != n) break;
    apply(st, op, payload);
  }
  fclose(f);
}

std::string cstr(const char* s) { return s ? std::string(s) : std::string(); }

}  // namespace

extern "C" {

void* obs_open(const char* path) {
  auto* st = new Store();
  st->wal_path = cstr(path);
  if (!st->wal_path.empty()) {
    replay(st);
    st->wal = fopen(st->wal_path.c_str(), "ab");
    if (!st->wal) { delete st; return nullptr; }
  }
  return st;
}

void obs_close(void* h) {
  auto* st = static_cast<Store*>(h);
  if (st->wal) fclose(st->wal);
  delete st;
}

int32_t obs_report(void* h, const char* trial, const char* metric, int64_t step, double value) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  std::string payload;
  put_lp(&payload, cstr(trial));
  put_lp(&payload, cstr(metric));
  put_i64(&payload, step);
  put_f64(&payload, value);
  apply(st, OP_REPORT, payload);
  wal_append(st, OP_REPORT, payload);
  return 0;
}

int64_t obs_count(void* h, const char* trial, const char* metric) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->series.find(cstr(trial) + '\0' + cstr(metric));
  return it == st->series.end() ? 0 : static_cast<int64_t>(it->second.size());
}

// Series query from `start`: scratch = repeated (i64 step | f64 value).
int64_t obs_get_log(void* h, const char* trial, const char* metric, int64_t start) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  st->scratch.clear();
  auto it = st->series.find(cstr(trial) + '\0' + cstr(metric));
  if (it != st->series.end()) {
    for (size_t i = start < 0 ? 0 : static_cast<size_t>(start); i < it->second.size(); ++i) {
      put_i64(&st->scratch, it->second[i].step);
      put_f64(&st->scratch, it->second[i].value);
    }
  }
  return static_cast<int64_t>(st->scratch.size());
}

int32_t obs_latest(void* h, const char* trial, const char* metric, double* out) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  auto it = st->series.find(cstr(trial) + '\0' + cstr(metric));
  if (it == st->series.end() || it->second.empty()) return 0;
  *out = it->second.back().value;
  return 1;
}

// Newline-joined trial names (insertion order) into scratch.
int64_t obs_trials(void* h) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  st->scratch.clear();
  for (const auto& t : st->trials) {
    st->scratch.append(t);
    st->scratch.push_back('\n');
  }
  return static_cast<int64_t>(st->scratch.size());
}

// Newline-joined metric names for one trial (sorted) into scratch.
int64_t obs_metrics(void* h, const char* trial) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  st->scratch.clear();
  auto it = st->trial_metrics.find(cstr(trial));
  if (it != st->trial_metrics.end()) {
    for (const auto& kv : it->second) {
      st->scratch.append(kv.first);
      st->scratch.push_back('\n');
    }
  }
  return static_cast<int64_t>(st->scratch.size());
}

int64_t obs_read_buffer(void* h, char* out, int64_t cap) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> lk(st->mu);
  int64_t n = static_cast<int64_t>(st->scratch.size());
  if (n > cap) n = cap;
  std::memcpy(out, st->scratch.data(), static_cast<size_t>(n));
  return n;
}

}  // extern "C"
