"""Katib Python SDK (upstream analogue: kubeflow-katib KatibClient)."""

from __future__ import annotations

from typing import Optional

from ..core.api import Obj
from ..core.cluster import Cluster
from ..core.conditions import has_condition
from . import api as kapi


class KatibClient:
    def __init__(self, cluster: Cluster, namespace: str = "default"):
        self.cluster = cluster
        self.namespace = namespace

    def create_experiment(self, exp: Obj) -> Obj:
        exp.setdefault("metadata", {}).setdefault("namespace", self.namespace)
        return self.cluster.api.create(exp)

    def get_experiment(self, name: str) -> Optional[Obj]:
        return self.cluster.api.try_get("Experiment", name, self.namespace)

    def wait_for_experiment(self, name: str, timeout: float = 600.0) -> str:
        def done() -> bool:
            e = self.get_experiment(name)
            return e is not None and (
                has_condition(e.get("status", {}), kapi.SUCCEEDED)
                or has_condition(e.get("status", {}), kapi.FAILED)
            )

        self.cluster.wait_for(done, timeout=timeout)
        e = self.get_experiment(name)
        status = e.get("status", {}) if e else {}
        if has_condition(status, kapi.SUCCEEDED):
            return kapi.SUCCEEDED
        if has_condition(status, kapi.FAILED):
            return kapi.FAILED
        raise TimeoutError(f"experiment {name} not terminal after {timeout}s")

    def get_optimal_trial(self, name: str) -> Optional[dict]:
        e = self.get_experiment(name)
        return (e or {}).get("status", {}).get("currentOptimalTrial")

    def list_trials(self, name: str) -> list[Obj]:
        return self.cluster.api.list(
            "Trial", namespace=self.namespace,
            label_selector={kapi.LABEL_EXPERIMENT: name},
        )
