"""Katib db-manager service: the push-mode observation-log endpoint.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Katib: db-manager + UI" row):
``[U:katib/cmd/db-manager]`` — a gRPC façade (ReportObservationLog /
GetObservationLog) over MySQL that the webhook-injected metrics-collector
sidecars push to.  Here it is a threaded HTTP façade over the C++ WAL
ObservationStore (obslog.py), bound to loopback on an ephemeral port; the
collector sidecar (collector_main.py) POSTs each parsed observation.  The
store's C core holds the mutex, so server threads and the trial
controller's reads interleave safely.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .obslog import ObservationStore


class DBManagerServer:
    """ReportObservationLog/GetObservationLog over loopback HTTP."""

    def __init__(self, store: ObservationStore, port: int = 0):
        self.store = store
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                if urlparse(self.path).path != "/report":
                    self.send_error(404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n))
                    outer.store.report(
                        str(body["trial"]), str(body["metric"]),
                        float(body["value"]),
                        step=int(body["step"]) if body.get("step") is not None else None,
                    )
                except (ValueError, KeyError, TypeError) as e:
                    self.send_error(400, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def do_GET(self):
                url = urlparse(self.path)
                if url.path != "/log":
                    self.send_error(404)
                    return
                q = parse_qs(url.query)
                series = outer.store.get_log(
                    q.get("trial", [""])[0], q.get("metric", [""])[0],
                    start=int(q.get("start", ["0"])[0]),
                )
                payload = json.dumps(series).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
