"""Katib metrics-collector sidecar: tail the main container's log, push
observations to the db-manager.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Katib: metrics collectors"):
the webhook-injected sidecar ``[U:katib/pkg/metricscollector/v1beta1/]`` that
tails stdout and calls ReportObservationLog.  Injected by the katib pod
webhook (controllers.py) as a second container when the trial's
metricsCollectorSpec asks for push mode; the kubelet runs it alongside the
main container, exports ``POD_LOG_PATH``, and SIGTERMs it after the main
exits — the handler does one final tail-and-push pass before exiting, and
the kubelet only marks the pod terminal once that flush finished.

Env contract: POD_LOG_PATH (kubelet), KATIB_DB_MANAGER host:port,
KATIB_TRIAL trial name, KATIB_METRICS comma-joined metric names.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time
import urllib.request

from .metrics import parse_metrics


def _push(addr: str, trial: str, metric: str, value: float) -> None:
    body = json.dumps({"trial": trial, "metric": metric, "value": value}).encode()
    req = urllib.request.Request(
        f"http://{addr}/report", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    # timeout well under the kubelet's drain grace: the final flush must get
    # retry attempts in before the pod is force-killed
    urllib.request.urlopen(req, timeout=2).read()


def main() -> int:
    log_path = os.environ["POD_LOG_PATH"]
    stop_file = os.environ.get("POD_STOP_FILE", log_path + ".stop")
    addr = os.environ["KATIB_DB_MANAGER"]
    trial = os.environ["KATIB_TRIAL"]
    metric_names = [m for m in os.environ["KATIB_METRICS"].split(",") if m]
    stop = {"now": False}
    signal.signal(signal.SIGTERM, lambda *_: stop.update(now=True))

    offset = 0

    def drain(final: bool) -> bool:
        """One tail-parse-push pass; returns False if any push failed (the
        offset is then NOT advanced, so the next pass retries)."""
        nonlocal offset
        try:
            with open(log_path, "rb") as f:
                f.seek(offset)
                chunk = f.read()
        except OSError:
            return True
        if not chunk:
            return True
        if not final:
            # hold back a trailing partial line until newline-terminated
            # (byte-level cut so the offset stays exact under any encoding)
            cut = chunk.rfind(b"\n")
            if cut < 0:
                return True
            chunk = chunk[:cut + 1]
        text = chunk.decode(errors="replace")
        # at-least-once: advance the offset only after EVERY push in the
        # chunk succeeded; a transient db-manager failure re-drains (and may
        # re-push — the store tolerates duplicate observations, losing the
        # trial's only objective line would fail it)
        ok = True
        for metric, values in parse_metrics(text, metric_names).items():
            for v in values:
                try:
                    _push(addr, trial, metric, v)
                except OSError as e:
                    print(f"collector: push failed (will retry): {e}", flush=True)
                    ok = False
        if ok:
            offset += len(chunk)
        return ok

    def stopping() -> bool:
        # SIGTERM can land before the handler above is installed (interpreter
        # startup); the kubelet also creates the stop file, which a
        # late-starting collector cannot miss
        return stop["now"] or os.path.exists(stop_file)

    while not stopping():
        drain(final=False)
        time.sleep(0.2)
    # the pre-terminal flush the kubelet's drain window waits for: retry a
    # failed pass a couple of times — one transient push failure must not
    # cost the trial its only objective line
    for _ in range(3):
        if drain(final=True):
            break
        time.sleep(0.2)
    print("collector: final flush done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
