"""Python client for the C++ observation-log store core (obslog_core.cc).

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Katib: db-manager + UI" row):
the ``ReportObservationLog`` / ``GetObservationLog`` gRPC surface of Katib's
db-manager.  Intermediate metric time series live here — NOT on Trial status
and NOT in pod logs — so they survive pod GC and back both medianstop early
stopping and the UI data endpoints (service.py) without re-parsing logs.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from typing import Optional

from ..utils.native_build import load_native

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "obslog_core.cc")
_LIB = None
_BIND_LOCK = threading.Lock()


def _load() -> ctypes.CDLL:
    global _LIB
    with _BIND_LOCK:
        if _LIB is None:
            lib = load_native(_SRC, "obslog")
            i32, i64, p, c, d = (ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p,
                                 ctypes.c_char_p, ctypes.c_double)
            lib.obs_open.restype = p
            lib.obs_open.argtypes = [c]
            lib.obs_close.argtypes = [p]
            lib.obs_report.restype = i32
            lib.obs_report.argtypes = [p, c, c, i64, d]
            lib.obs_count.restype = i64
            lib.obs_count.argtypes = [p, c, c]
            lib.obs_get_log.restype = i64
            lib.obs_get_log.argtypes = [p, c, c, i64]
            lib.obs_latest.restype = i32
            lib.obs_latest.argtypes = [p, c, c, ctypes.POINTER(d)]
            lib.obs_trials.restype = i64
            lib.obs_trials.argtypes = [p]
            lib.obs_metrics.restype = i64
            lib.obs_metrics.argtypes = [p, c]
            lib.obs_read_buffer.restype = i64
            lib.obs_read_buffer.argtypes = [p, ctypes.c_char_p, i64]
            _LIB = lib
    return _LIB


class ObservationStore:
    """Per-(trial, metric) time series with WAL durability."""

    def __init__(self, path: Optional[str] = None):
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.lib = _load()
        self._h = self.lib.obs_open(path.encode() if path else None)
        if not self._h:
            raise OSError(f"cannot open observation WAL at {path!r}")
        self._lock = threading.Lock()  # query + read_buffer must pair

    def close(self) -> None:
        if self._h:
            self.lib.obs_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - defensive
        try:
            self.close()
        except Exception:
            pass

    def _read(self, n: int) -> bytes:
        buf = ctypes.create_string_buffer(int(n))
        got = self.lib.obs_read_buffer(self._h, buf, n)
        return buf.raw[:got]

    # ------------------------------------------------------------- writes

    def report(self, trial: str, metric: str, value: float, step: Optional[int] = None) -> int:
        """Append one observation; step defaults to the series index."""
        with self._lock:
            if step is None:
                step = self.lib.obs_count(self._h, trial.encode(), metric.encode())
            self.lib.obs_report(self._h, trial.encode(), metric.encode(), int(step), float(value))
            return int(step)

    # -------------------------------------------------------------- reads

    def count(self, trial: str, metric: str) -> int:
        with self._lock:
            return int(self.lib.obs_count(self._h, trial.encode(), metric.encode()))

    def get_log(self, trial: str, metric: str, start: int = 0) -> list[tuple[int, float]]:
        """The series from index ``start``: [(step, value), ...]."""
        with self._lock:
            n = self.lib.obs_get_log(self._h, trial.encode(), metric.encode(), int(start))
            raw = self._read(n)
        out = []
        for off in range(0, len(raw), 16):
            step, value = struct.unpack_from("<qd", raw, off)
            out.append((step, value))
        return out

    def latest(self, trial: str, metric: str) -> Optional[float]:
        out = ctypes.c_double()
        with self._lock:
            rc = self.lib.obs_latest(self._h, trial.encode(), metric.encode(), ctypes.byref(out))
        return out.value if rc else None

    def trials(self) -> list[str]:
        with self._lock:
            n = self.lib.obs_trials(self._h)
            raw = self._read(n)
        return [t for t in raw.decode().split("\n") if t]

    def metrics(self, trial: str) -> list[str]:
        with self._lock:
            n = self.lib.obs_metrics(self._h, trial.encode())
            raw = self._read(n)
        return [m for m in raw.decode().split("\n") if m]

    def observation(self, trial: str, metric_names) -> dict:
        """Trial ``.status.observation`` built from the stored series."""
        metrics = []
        for name in metric_names:
            series = [v for _, v in self.get_log(trial, name)]
            if series:
                metrics.append({"name": name, "latest": series[-1],
                                "min": min(series), "max": max(series)})
        return {"metrics": metrics}
