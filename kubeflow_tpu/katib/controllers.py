"""Katib controllers: Experiment → Suggestion → Trial → training job.

Upstream analogue (UNVERIFIED, SURVEY.md §3.3): the experiment controller
creates a Suggestion and Trials until goal/maxTrials; the trial controller
renders the trialTemplate into a real job (full §3.1 stack nested) and reads
metrics; the suggestion controller serves parameter assignments.

Deviations from upstream, by design of the simulator:
  * suggestion algorithms run in-process at reconcile time instead of in a
    per-algorithm gRPC service pod (same request/response contract).

Metrics collection supports BOTH upstream shapes: the default pull path
(trial controller reads kubelet logs at reconcile — see metrics.py) and the
upstream sidecar architecture (``metricsCollectorSpec.collector.kind:
"Push"`` — a pod webhook injects collector_main.py as a sidecar container
that tails the log and pushes to the db-manager HTTP service, dbmanager.py).
"""

from __future__ import annotations

import copy
import os
import re
import sys
from typing import Callable, Optional

from ..core.api import AlreadyExists, APIServer, Obj, owner_reference
from ..core.conditions import has_condition, set_condition
from ..core.controller import Request, Result
from ..core.events import EventRecorder
from ..training import api as tapi
from ..utils.render import deep_map_strings
from . import api as kapi
from .metrics import parse_metrics, parse_tfevent_dir
from .obslog import ObservationStore
from .suggest import get_suggester

_PLACEHOLDER = re.compile(r"\$\{trialParameters\.([\w\-]+)\}")


def render_trial_spec(template: dict, assignments: dict) -> dict:
    """Substitute ``${trialParameters.x}`` through the whole spec tree."""
    trial_params = {p["name"]: p["reference"] for p in template.get("trialParameters", [])}

    def repl(m):
        pname = m.group(1)
        ref = trial_params.get(pname, pname)
        if ref not in assignments:
            raise KeyError(f"trial parameter {pname!r} (ref {ref!r}) has no assignment")
        return str(assignments[ref])

    return deep_map_strings(
        copy.deepcopy(template["trialSpec"]), lambda s: _PLACEHOLDER.sub(repl, s)
    )


class ExperimentController:
    kind = "Experiment"

    def __init__(self, api: APIServer):
        self.api = api
        self.recorder = EventRecorder(api, "katib-experiment-controller")

    def _trials(self, exp: Obj) -> list[Obj]:
        return self.api.list(
            "Trial",
            namespace=exp["metadata"].get("namespace", "default"),
            label_selector={kapi.LABEL_EXPERIMENT: exp["metadata"]["name"]},
        )

    def _optimal(self, exp: Obj, trials: list[Obj]) -> Optional[dict]:
        metric = exp["spec"]["objective"]["objectiveMetricName"]
        sign = 1.0 if exp["spec"]["objective"]["type"] == "maximize" else -1.0
        best, best_val = None, None
        for t in trials:
            if not has_condition(t.get("status", {}), kapi.SUCCEEDED):
                continue
            for m in t.get("status", {}).get("observation", {}).get("metrics", []):
                if m["name"] == metric:
                    v = sign * float(m["latest"])
                    if best_val is None or v > best_val:
                        best_val = v
                        best = {
                            "bestTrialName": t["metadata"]["name"],
                            "parameterAssignments": t["spec"].get("parameterAssignments", []),
                            "observation": t["status"]["observation"],
                        }
        return best

    def reconcile(self, req: Request) -> Optional[Result]:
        exp = self.api.try_get("Experiment", req.name, req.namespace)
        if exp is None:
            return None
        status = exp.setdefault("status", {})
        if has_condition(status, kapi.SUCCEEDED) or has_condition(status, kapi.FAILED):
            return None
        if not has_condition(status, kapi.CREATED):
            set_condition(status, kapi.CREATED, "True", "ExperimentCreated", "")
            self.recorder.normal(exp, "Created", "experiment accepted")

        spec = exp["spec"]
        trials = self._trials(exp)
        succeeded = [t for t in trials if has_condition(t.get("status", {}), kapi.SUCCEEDED)]
        failed = [t for t in trials if has_condition(t.get("status", {}), kapi.FAILED)]
        active = [t for t in trials if t not in succeeded and t not in failed]

        status["trials"] = len(trials)
        status["trialsSucceeded"] = len(succeeded)
        status["trialsFailed"] = len(failed)
        status["trialsRunning"] = len(active)
        optimal = self._optimal(exp, trials)
        if optimal:
            status["currentOptimalTrial"] = optimal

        # terminal conditions
        goal = spec["objective"].get("goal")
        metric_reached = False
        if goal is not None and optimal:
            sign = 1.0 if spec["objective"]["type"] == "maximize" else -1.0
            for m in optimal["observation"]["metrics"]:
                if m["name"] == spec["objective"]["objectiveMetricName"]:
                    metric_reached = sign * float(m["latest"]) >= sign * float(goal)
        if len(failed) > spec["maxFailedTrialCount"]:
            set_condition(status, kapi.FAILED, "True", "TooManyFailedTrials",
                          f"{len(failed)} trials failed")
            self.recorder.warning(exp, "Failed", "too many failed trials")
            self.api.update_status(exp)
            return None
        sug = self.api.try_get("Suggestion", req.name, req.namespace)
        # a suggester that cannot produce more points (e.g. grid fully
        # enumerated) ends the experiment once every issued trial finished —
        # upstream's "SuggestionEndReached" terminal reason
        exhausted = (
            sug is not None
            and sug.get("status", {}).get("exhausted", False)
            and not active
            and len(trials) >= sug.get("status", {}).get("suggestionCount", 0)
        )
        if metric_reached or len(succeeded) >= spec["maxTrialCount"] or exhausted:
            reason = (
                "GoalReached" if metric_reached
                else "MaxTrialsReached" if len(succeeded) >= spec["maxTrialCount"]
                else "SuggestionEndReached"
            )
            set_condition(status, kapi.SUCCEEDED, "True", reason, "")
            set_condition(status, kapi.RUNNING, "False", reason, "")
            self.recorder.normal(exp, "Succeeded", reason)
            self.api.update_status(exp)
            return None

        # ensure suggestion object, sized to keep parallelTrialCount running
        free_slots = max(0, spec["parallelTrialCount"] - len(active))
        budget_left = spec["maxTrialCount"] - len(succeeded) - len(active)
        want = len(trials) + min(free_slots, max(0, budget_left))
        if sug is None:
            sug = self.api.create(
                {
                    "apiVersion": kapi.API_VERSION,
                    "kind": "Suggestion",
                    "metadata": {
                        "name": req.name,
                        "namespace": req.namespace,
                        "labels": {kapi.LABEL_EXPERIMENT: req.name},
                        "ownerReferences": [owner_reference(exp)],
                    },
                    "spec": {
                        "algorithm": spec["algorithm"],
                        "requests": want,
                    },
                }
            )
        elif sug["spec"].get("requests", 0) < want:
            sug["spec"]["requests"] = want
            sug = self.api.update(sug)

        # create trials for issued-but-unconsumed assignments
        issued = sug.get("status", {}).get("suggestions", [])
        for idx in range(len(trials), min(len(issued), want)):
            assignments = issued[idx]["assignments"]
            run_spec = render_trial_spec(
                spec["trialTemplate"],
                {a["name"]: a["value"] for a in assignments},
            )
            trial_name = f"{req.name}-{idx:03d}"
            try:
                self.api.create(
                    {
                        "apiVersion": kapi.API_VERSION,
                        "kind": "Trial",
                        "metadata": {
                            "name": trial_name,
                            "namespace": req.namespace,
                            "labels": {kapi.LABEL_EXPERIMENT: req.name},
                            "ownerReferences": [owner_reference(exp)],
                        },
                        "spec": {
                            "parameterAssignments": assignments,
                            "objective": spec["objective"],
                            "primaryContainerName": spec["trialTemplate"].get(
                                "primaryContainerName", "main"
                            ),
                            "runSpec": run_spec,
                            "metricsCollectorSpec": copy.deepcopy(
                                spec.get("metricsCollectorSpec",
                                         {"collector": {"kind": "StdOut"}})),
                            **({"earlyStopping": spec["earlyStopping"]}
                               if spec.get("earlyStopping") else {}),
                        },
                    }
                )
                self.recorder.normal(exp, "TrialCreated", trial_name)
            except AlreadyExists:
                pass

        if active and not has_condition(status, kapi.RUNNING):
            set_condition(status, kapi.RUNNING, "True", "ExperimentRunning", "")
        self.api.update_status(exp)
        return None


class SuggestionController:
    kind = "Suggestion"

    def __init__(self, api: APIServer):
        self.api = api

    def reconcile(self, req: Request) -> Optional[Result]:
        sug = self.api.try_get("Suggestion", req.name, req.namespace)
        if sug is None:
            return None
        exp = self.api.try_get("Experiment", req.name, req.namespace)
        if exp is None:
            return None
        status = sug.setdefault("status", {})
        issued = status.get("suggestions", [])
        want = sug["spec"].get("requests", 0)
        if len(issued) >= want:
            return None
        trials = self.api.list(
            "Trial", namespace=req.namespace,
            label_selector={kapi.LABEL_EXPERIMENT: req.name},
        )
        algo = sug["spec"]["algorithm"]["algorithmName"]
        suggester = get_suggester(algo)
        new = suggester.suggest(exp, trials, want - len(issued))
        for assignments in new:
            issued.append(
                {"assignments": [{"name": k, "value": v} for k, v in assignments.items()]}
            )
        status["suggestions"] = issued
        status["suggestionCount"] = len(issued)
        # fewer than requested = the search space is exhausted (grid etc.);
        # the experiment controller turns this into SuggestionEndReached
        status["exhausted"] = len(issued) < want
        self.api.update_status(sug)
        return None


class TrialController:
    kind = "Trial"

    def __init__(self, api: APIServer, log_reader: Callable[[str, str], str],
                 store: Optional[ObservationStore] = None):
        self.api = api
        self.log_reader = log_reader
        # db-manager equivalent: intermediate series persist here (WAL-backed
        # when kfadm passes a path), not on Trial status / in pod logs
        self.store = store if store is not None else ObservationStore()
        self.recorder = EventRecorder(api, "katib-trial-controller")
        # per-(trial, pod) high-water marks: collection parses only NEW log
        # bytes each reconcile instead of re-parsing from byte 0 (the round-1
        # workaround the store removes)
        self._log_offsets: dict[tuple[str, str], int] = {}

    def _metric_names(self, trial: Obj) -> list[str]:
        return [trial["spec"]["objective"]["objectiveMetricName"]] + list(
            trial["spec"]["objective"].get("additionalMetricNames", [])
        )

    def _collect(self, trial: Obj, req: Request, final: bool = False) -> None:
        """Pull-based metrics collection into the observation store.

        The simulator's analogue of the injected metrics-collector sidecar
        (SURVEY.md §2a metrics-collectors row): stdout/JSON lines from pod
        logs, or TFEvent files when the trial carries a TFEvent
        metricsCollectorSpec.  Incremental: only bytes past the per-pod
        high-water mark are parsed; a trailing partial line is held back
        until newline-terminated (unless ``final``).
        """
        name = trial["metadata"]["name"]
        metric_names = self._metric_names(trial)
        collector = (trial["spec"].get("metricsCollectorSpec") or {})
        if collector.get("collector", {}).get("kind") == "Push":
            # the injected sidecar owns reporting (push architecture); the
            # kubelet guarantees its final flush lands before the pod goes
            # terminal, so there is nothing to pull here
            return
        if collector.get("collector", {}).get("kind") == "TFEvent":
            path = collector.get("source", {}).get("fileSystemPath", {}).get("path", "")
            for metric, series in parse_tfevent_dir(path, metric_names).items():
                have = self.store.count(name, metric)
                for step, value in series[have:]:
                    self.store.report(name, metric, value, step=step)
            return
        pods = self.api.list(
            "Pod", namespace=req.namespace,
            label_selector={tapi.LABEL_JOB_NAME: req.name},
        )
        if trial["spec"]["runSpec"].get("kind", "TPUJob") == "Pod":
            # bare-Pod trial: the workload IS one pod named after the trial
            # — no job-name label to select on.  Gated on the runSpec kind,
            # never on "no labeled pods found": a job trial with no pods yet
            # must not read an unrelated same-named pod's logs as metrics
            solo = self.api.try_get("Pod", req.name, req.namespace)
            pods = [solo] if solo is not None else []
        for p in pods:
            pod = p["metadata"]["name"]
            log = self.log_reader(pod, req.namespace)
            off = self._log_offsets.get((name, pod), 0)
            new = log[off:]
            if not final:
                cut = new.rfind("\n")
                if cut < 0:
                    continue
                new = new[:cut]
                self._log_offsets[(name, pod)] = off + cut + 1
            else:
                self._log_offsets[(name, pod)] = off + len(new)
            for metric, values in parse_metrics(new, metric_names).items():
                for v in values:
                    self.store.report(name, metric, v)

    def reconcile(self, req: Request) -> Optional[Result]:
        trial = self.api.try_get("Trial", req.name, req.namespace)
        if trial is None:
            return None
        status = trial.setdefault("status", {})
        if has_condition(status, kapi.SUCCEEDED) or has_condition(status, kapi.FAILED):
            return None

        run_spec = trial["spec"]["runSpec"]
        kind = run_spec.get("kind", "TPUJob")
        job = self.api.try_get(kind, req.name, req.namespace)
        if job is None:
            job_obj = copy.deepcopy(run_spec)
            job_obj.setdefault("metadata", {})
            job_obj["metadata"]["name"] = req.name
            job_obj["metadata"]["namespace"] = req.namespace
            job_obj["metadata"].setdefault("labels", {})[kapi.LABEL_EXPERIMENT] = (
                trial["metadata"].get("labels", {}).get(kapi.LABEL_EXPERIMENT, "")
            )
            job_obj["metadata"]["ownerReferences"] = [owner_reference(trial)]
            self.api.create(job_obj)
            set_condition(status, kapi.RUNNING, "True", "TrialRunning", "")
            self.api.update_status(trial)
            return None

        job_status = job.get("status", {})
        if kind == "Pod":
            # bare-Pod trials (upstream's plain batch-job/pod trialTemplate):
            # completion is the pod phase — pods have no job conditions
            job_failed = job_status.get("phase") == "Failed"
            job_succeeded = job_status.get("phase") == "Succeeded"
        else:
            job_failed = has_condition(job_status, tapi.FAILED)
            job_succeeded = has_condition(job_status, tapi.SUCCEEDED)
        if job_failed:
            set_condition(status, kapi.FAILED, "True", "TrialFailed", "job failed")
            set_condition(status, kapi.RUNNING, "False", "TrialFailed", "")
            self.recorder.warning(trial, "TrialFailed", "underlying job failed")
            self.api.update_status(trial)
            return None
        if not job_succeeded:
            self._collect(trial, req)
            return self._maybe_early_stop(trial, status, req)

        # job done: one final collection pass, then build the observation
        # from the store (the series outlives the pods — db-manager parity)
        metric_names = self._metric_names(trial)
        self._collect(trial, req, final=True)
        obs = self.store.observation(req.name, metric_names)
        have = {m["name"] for m in obs["metrics"]}
        if trial["spec"]["objective"]["objectiveMetricName"] not in have:
            set_condition(status, kapi.FAILED, "True", "MetricsUnavailable",
                          f"objective metric not found in logs (looked for {metric_names})")
            self.api.update_status(trial)
            return None
        status["observation"] = obs
        set_condition(status, kapi.SUCCEEDED, "True", "TrialSucceeded", "")
        set_condition(status, kapi.RUNNING, "False", "TrialSucceeded", "")
        self.recorder.normal(trial, "TrialSucceeded", str(obs["metrics"]))
        self.api.update_status(trial)
        return None

    # --------------------------------------------------- early stopping

    def _maybe_early_stop(self, trial: Obj, status: dict, req: Request) -> Optional[Result]:
        """medianstop (upstream katib earlystopping): stop a running trial
        whose current objective is worse than the median of completed
        siblings' final objectives.  Queries the observation store (reconcile
        already collected any new log lines into it) — no log re-parsing."""
        es = trial["spec"].get("earlyStopping") or {}
        if es.get("algorithmName") != "medianstop":
            return None
        settings = {s["name"]: s["value"] for s in es.get("algorithmSettings", [])}
        min_trials = int(settings.get("min_trials_required", 3))

        exp_name = trial["metadata"].get("labels", {}).get(kapi.LABEL_EXPERIMENT, "")
        siblings = self.api.list(
            "Trial", namespace=req.namespace,
            label_selector={kapi.LABEL_EXPERIMENT: exp_name},
        )
        metric = trial["spec"]["objective"]["objectiveMetricName"]
        sign = 1.0 if trial["spec"]["objective"]["type"] == "maximize" else -1.0
        finals = []
        for t in siblings:
            if t["metadata"]["name"] == trial["metadata"]["name"]:
                continue
            if not has_condition(t.get("status", {}), kapi.SUCCEEDED):
                continue
            for m in t["status"].get("observation", {}).get("metrics", []):
                if m["name"] == metric:
                    finals.append(sign * float(m["latest"]))
        if len(finals) < min_trials:
            return Result(requeue_after=0.3)

        latest = self.store.latest(req.name, metric)
        if latest is None:
            return Result(requeue_after=0.3)
        current = sign * latest
        finals.sort()
        median = finals[len(finals) // 2]
        if current >= median:
            return Result(requeue_after=0.3)

        # stop: kill the job (pods cascade), keep the partial observation
        run_kind = trial["spec"]["runSpec"].get("kind", "TPUJob")
        self.api.try_delete(run_kind, req.name, req.namespace)
        status["observation"] = self.store.observation(req.name, self._metric_names(trial))
        set_condition(status, kapi.EARLY_STOPPED, "True", "TrialEarlyStopped",
                      f"{metric}={sign * current} worse than median {sign * median}")
        set_condition(status, kapi.SUCCEEDED, "True", "TrialEarlyStopped", "stopped early")
        set_condition(status, kapi.RUNNING, "False", "TrialEarlyStopped", "")
        self.recorder.normal(trial, "TrialEarlyStopped",
                             f"{metric} {sign * current} < median {sign * median}")
        self.api.update_status(trial)
        return None


def _register_push_collector_webhook(api: APIServer, store: ObservationStore) -> None:
    """The Katib pod webhook (upstream ``[U:katib/pkg/webhook/v1beta1/pod/]``):
    mutate trial-job pods whose Trial asks for ``collector.kind: "Push"`` by
    appending the metrics-collector sidecar container.  The db-manager HTTP
    service starts lazily on the first injection."""
    if getattr(api, "_katib_push_webhook", False):
        return
    api._katib_push_webhook = True
    state: dict = {"server": None}

    def _close() -> None:
        if state["server"] is not None:
            state["server"].close()
            state["server"] = None

    api.add_teardown(_close)

    def _db_address() -> str:
        if state["server"] is None:
            from .dbmanager import DBManagerServer

            state["server"] = DBManagerServer(store)
        return state["server"].address

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    def inject(pod: Obj) -> None:
        labels = pod.get("metadata", {}).get("labels", {})
        jname = labels.get(tapi.LABEL_JOB_NAME)
        if not jname:
            return
        trial = api.try_get("Trial", jname, pod["metadata"].get("namespace", "default"))
        if trial is None:
            return
        spec = trial.get("spec", {})
        collector = (spec.get("metricsCollectorSpec") or {}).get("collector", {})
        if collector.get("kind") != "Push":
            return
        metric_names = [spec["objective"]["objectiveMetricName"]] + list(
            spec["objective"].get("additionalMetricNames", []))
        pod["spec"]["containers"].append({
            "name": "metrics-collector",
            "command": [sys.executable, "-u", "-m",
                        "kubeflow_tpu.katib.collector_main"],
            "env": [
                {"name": "PYTHONPATH",
                 "value": repo_root + os.pathsep + "$(PYTHONPATH)"},
                {"name": "KATIB_DB_MANAGER", "value": _db_address()},
                {"name": "KATIB_TRIAL", "value": jname},
                {"name": "KATIB_METRICS", "value": ",".join(metric_names)},
            ],
        })

    api.register_mutating_webhook("Pod", inject)


def install(api: APIServer, manager, log_reader: Callable[[str, str], str],
            store: Optional[ObservationStore] = None,
            store_path: Optional[str] = None):
    """Register Katib CRDs + controllers on a Manager."""
    kapi.register(api)
    if store is None:
        store = ObservationStore(store_path)
    _register_push_collector_webhook(api, store)
    exp = ExperimentController(api)
    sug = SuggestionController(api)
    trial = TrialController(api, log_reader, store)
    manager.add(exp, owns=("Trial", "Suggestion"))
    manager.add(sug, watches=((
        "Trial",
        lambda obj: Request(
            obj["metadata"].get("labels", {}).get(kapi.LABEL_EXPERIMENT, ""),
            obj["metadata"].get("namespace", "default"),
        ) if obj["metadata"].get("labels", {}).get(kapi.LABEL_EXPERIMENT) else None,
    ),))
    # "Pod" covers bare-Pod trials (runSpec kind Pod): the pod carries the
    # trial's ownerReference, so its phase flips requeue the trial the same
    # way a training job's condition flips do
    manager.add(trial, owns=tuple(tapi.JOB_KINDS) + ("Pod",))
    return exp, sug, trial
