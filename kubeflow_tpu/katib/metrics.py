"""Metrics collection from trial logs.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Katib: metrics collectors"):
a sidecar injected by webhook tails stdout/TFEvent files and pushes
observation logs to the db-manager.  Architectural deviation (documented):
the simulator's trial controller PULLS pod logs from the kubelet at reconcile
time instead of running a push sidecar — same parse rules, same observation
schema on Trial status.

StdOut format (katib default): lines containing ``metric=value`` pairs, e.g.
``epoch 3: accuracy=0.91 loss=0.32`` or ``{"accuracy": 0.91}`` JSON lines.
"""

from __future__ import annotations

import json
import re
from typing import Iterable

_PAIR = r"(?P<name>[A-Za-z][\w\-./]*)\s*=\s*(?P<value>[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)"


def parse_metrics(log: str, metric_names: Iterable[str]) -> dict[str, list[float]]:
    """Extract all observations of each metric, in log order."""
    wanted = set(metric_names)
    out: dict[str, list[float]] = {m: [] for m in wanted}
    for line in log.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
                for k, v in d.items():
                    if k in wanted and isinstance(v, (int, float)):
                        out[k].append(float(v))
                continue
            except (json.JSONDecodeError, TypeError):
                pass
        for m in re.finditer(_PAIR, line):
            name = m.group("name")
            if name in wanted:
                out[name].append(float(m.group("value")))
    return out


def observation(log: str, metric_names: Iterable[str]) -> dict:
    """Trial .status.observation from a log blob."""
    parsed = parse_metrics(log, metric_names)
    metrics = []
    for name, values in parsed.items():
        if values:
            metrics.append(
                {"name": name, "latest": values[-1], "min": min(values), "max": max(values)}
            )
    return {"metrics": metrics}
