"""Metrics collection from trial logs.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Katib: metrics collectors"):
a sidecar injected by webhook tails stdout/TFEvent files and pushes
observation logs to the db-manager.  Architectural deviation (documented):
the simulator's trial controller PULLS pod logs from the kubelet at reconcile
time instead of running a push sidecar — same parse rules, same observation
schema on Trial status.

StdOut format (katib default): lines containing ``metric=value`` pairs, e.g.
``epoch 3: accuracy=0.91 loss=0.32`` or ``{"accuracy": 0.91}`` JSON lines.
"""

from __future__ import annotations

import json
import re
from typing import Iterable

_PAIR = r"(?P<name>[A-Za-z][\w\-./]*)\s*=\s*(?P<value>[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)"


def parse_metrics(log: str, metric_names: Iterable[str]) -> dict[str, list[float]]:
    """Extract all observations of each metric, in log order."""
    wanted = set(metric_names)
    out: dict[str, list[float]] = {m: [] for m in wanted}
    for line in log.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
                for k, v in d.items():
                    if k in wanted and isinstance(v, (int, float)):
                        out[k].append(float(v))
                continue
            except (json.JSONDecodeError, TypeError):
                pass
        for m in re.finditer(_PAIR, line):
            name = m.group("name")
            if name in wanted:
                out[name].append(float(m.group("value")))
    return out


def observation(log: str, metric_names: Iterable[str]) -> dict:
    """Trial .status.observation from a log blob."""
    parsed = parse_metrics(log, metric_names)
    metrics = []
    for name, values in parsed.items():
        if values:
            metrics.append(
                {"name": name, "latest": values[-1], "min": min(values), "max": max(values)}
            )
    return {"metrics": metrics}


# ----------------------------------------------------------------- TFEvent
#
# Upstream analogue (UNVERIFIED, SURVEY.md §2a metrics-collectors row): the
# ``tfevent-metricscollector`` sidecar parses TensorBoard event files.  The
# rebuild reads the TFRecord/Event wire format directly (no TensorFlow
# import — a multi-second dependency for two proto fields) and ships a
# writer so TPU workloads can emit collector-readable scalars.
#
# TFRecord framing: u64 len | u32 masked_crc(len) | data | u32 masked_crc.
# Event proto: field 2 = step (varint), field 5 = Summary; Summary field 1 =
# repeated Value; Value field 1 = tag, field 2 = simple_value (fixed32).

import glob
import os
import struct


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), table-driven — TFRecord's checksum."""
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


_CRC_TABLE = None


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(buf: bytes, off: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _proto_fields(buf: bytes):
    """Yield (field_number, wire_type, value) triples of one message."""
    off = 0
    while off < len(buf):
        key, off = _varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            value, off = _varint(buf, off)
        elif wire == 1:  # fixed64
            value = buf[off:off + 8]
            off += 8
        elif wire == 2:  # length-delimited
            n, off = _varint(buf, off)
            value = buf[off:off + n]
            off += n
        elif wire == 5:  # fixed32
            value = buf[off:off + 4]
            off += 4
        else:  # groups (3/4): not emitted by TF writers
            return
        yield field, wire, value


def _parse_event(data: bytes) -> tuple[int, dict[str, float]]:
    """One Event proto → (step, {tag: scalar})."""
    step = 0
    scalars: dict[str, float] = {}
    for field, wire, value in _proto_fields(data):
        if field == 2 and wire == 0:
            step = value
        elif field == 5 and wire == 2:  # Summary
            for f2, w2, v2 in _proto_fields(value):
                if f2 == 1 and w2 == 2:  # Summary.Value
                    tag, simple = None, None
                    for f3, w3, v3 in _proto_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode("utf-8", "replace")
                        elif f3 == 2 and w3 == 5:
                            simple = struct.unpack("<f", v3)[0]
                        elif f3 == 8 and w3 == 2:  # TensorProto (TF2 scalars)
                            for f4, w4, v4 in _proto_fields(v3):
                                if f4 == 5 and w4 == 2 and len(v4) >= 4:  # packed float_val
                                    simple = struct.unpack("<f", v4[:4])[0]
                                elif f4 == 5 and w4 == 5:
                                    simple = struct.unpack("<f", v4)[0]
                                elif f4 == 4 and w4 == 2 and len(v4) == 4:  # tensor_content
                                    simple = struct.unpack("<f", v4)[0]
                    if tag is not None and simple is not None:
                        scalars[tag] = simple
    return step, scalars


def parse_tfevent_file(path: str, metric_names: Iterable[str]) -> dict[str, list[tuple[int, float]]]:
    """Event file → {metric: [(step, value), ...]} in record order."""
    wanted = set(metric_names)
    out: dict[str, list[tuple[int, float]]] = {m: [] for m in wanted}
    with open(path, "rb") as f:
        buf = f.read()
    off = 0
    while off + 12 <= len(buf):
        (n,) = struct.unpack_from("<Q", buf, off)
        off += 12  # len + len-crc (not validated on read)
        data = buf[off:off + n]
        if len(data) < n:
            break  # truncated tail (crash mid-write): drop
        off += n + 4  # data + data-crc
        step, scalars = _parse_event(data)
        for tag, value in scalars.items():
            if tag in wanted:
                out[tag].append((step, value))
    return out


def parse_tfevent_dir(path: str, metric_names: Iterable[str]) -> dict[str, list[tuple[int, float]]]:
    """All ``events.out.tfevents.*`` files under ``path`` (sorted), merged."""
    merged: dict[str, list[tuple[int, float]]] = {m: [] for m in metric_names}
    if not path or not os.path.isdir(path):
        return merged
    for f in sorted(glob.glob(os.path.join(path, "events.out.tfevents.*"))):
        for metric, series in parse_tfevent_file(f, metric_names).items():
            merged[metric].extend(series)
    return merged


class TFEventWriter:
    """Minimal TensorBoard-compatible scalar writer for TPU workloads.

    Writes real TFRecord framing (masked CRC-32C) with Event protos carrying
    ``simple_value`` summaries, so both this collector and actual TensorBoard
    can read the output.
    """

    def __init__(self, logdir: str, suffix: str = "0"):
        os.makedirs(logdir, exist_ok=True)
        self.path = os.path.join(logdir, f"events.out.tfevents.{suffix}")
        self._f = open(self.path, "ab")

    @staticmethod
    def _encode_varint(v: int) -> bytes:
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            out.append(b | (0x80 if v else 0))
            if not v:
                return bytes(out)

    def scalar(self, tag: str, value: float, step: int) -> None:
        enc = self._encode_varint
        tag_b = tag.encode()
        val = (b"\x0a" + enc(len(tag_b)) + tag_b           # Value.tag (field 1)
               + b"\x15" + struct.pack("<f", value))       # Value.simple_value (field 2)
        summary = b"\x0a" + enc(len(val)) + val            # Summary.value (field 1)
        event = (b"\x10" + enc(step)                       # Event.step (field 2)
                 + b"\x2a" + enc(len(summary)) + summary)  # Event.summary (field 5)
        header = struct.pack("<Q", len(event))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(event)
        self._f.write(struct.pack("<I", _masked_crc(event)))
        self._f.flush()

    def close(self) -> None:
        self._f.close()
