"""Katib UI data API: the endpoints the katib-ui frontend binds to.

Upstream analogue (UNVERIFIED, SURVEY.md §2a "Katib: db-manager + UI" row):
the Katib UI's backend REST layer (experiment/trial listings and detail
views) plus db-manager's ``GetObservationLog``.  Scope per SURVEY.md §7:
capabilities, not pixels — this is the data layer a UI would render.
"""

from __future__ import annotations

from typing import Optional

from ..core.api import APIServer
from ..core.conditions import has_condition
from . import api as kapi
from .obslog import ObservationStore


def _phase(status: dict) -> str:
    for cond, phase in ((kapi.EARLY_STOPPED, "EarlyStopped"), (kapi.SUCCEEDED, "Succeeded"),
                        (kapi.FAILED, "Failed"), (kapi.RUNNING, "Running"),
                        (kapi.CREATED, "Created")):
        if has_condition(status, cond):
            return phase
    return "Pending"


class KatibService:
    """Read-side aggregation over the API store + observation store."""

    def __init__(self, api: APIServer, store: ObservationStore):
        self.api = api
        self.store = store

    # -------------------------------------------------------- experiments

    def list_experiments(self, namespace: Optional[str] = None) -> list[dict]:
        out = []
        for exp in self.api.list("Experiment", namespace=namespace):
            status = exp.get("status", {})
            out.append({
                "name": exp["metadata"]["name"],
                "namespace": exp["metadata"].get("namespace", "default"),
                "status": _phase(status),
                "algorithm": exp["spec"]["algorithm"]["algorithmName"],
                "objective": exp["spec"]["objective"]["objectiveMetricName"],
                "trials": status.get("trials", 0),
                "trialsSucceeded": status.get("trialsSucceeded", 0),
                "trialsFailed": status.get("trialsFailed", 0),
                "trialsRunning": status.get("trialsRunning", 0),
            })
        return out

    def get_experiment(self, name: str, namespace: str = "default") -> Optional[dict]:
        exp = self.api.try_get("Experiment", name, namespace)
        if exp is None:
            return None
        status = exp.get("status", {})
        return {
            "name": name,
            "namespace": namespace,
            "status": _phase(status),
            "spec": exp["spec"],
            "conditions": status.get("conditions", []),
            "currentOptimalTrial": status.get("currentOptimalTrial"),
            "trials": self.list_trials(name, namespace),
        }

    # ------------------------------------------------------------- trials

    def list_trials(self, experiment: str, namespace: str = "default") -> list[dict]:
        out = []
        for t in self.api.list("Trial", namespace=namespace,
                               label_selector={kapi.LABEL_EXPERIMENT: experiment}):
            status = t.get("status", {})
            out.append({
                "name": t["metadata"]["name"],
                "status": _phase(status),
                "parameterAssignments": t["spec"].get("parameterAssignments", []),
                "observation": status.get("observation", {"metrics": []}),
            })
        return out

    def get_trial(self, name: str, namespace: str = "default") -> Optional[dict]:
        t = self.api.try_get("Trial", name, namespace)
        if t is None:
            return None
        status = t.get("status", {})
        metrics = self.store.metrics(name)
        return {
            "name": name,
            "namespace": namespace,
            "status": _phase(status),
            "parameterAssignments": t["spec"].get("parameterAssignments", []),
            "observation": status.get("observation", {"metrics": []}),
            "conditions": status.get("conditions", []),
            # full intermediate series per metric — the GetObservationLog view
            "observationLog": {m: self.get_observation_log(name, m) for m in metrics},
        }

    def get_observation_log(self, trial: str, metric: str,
                            start: int = 0) -> list[dict]:
        return [{"step": s, "value": v} for s, v in self.store.get_log(trial, metric, start)]
