"""Katib CRD types: Experiment / Suggestion / Trial.

Upstream analogue (UNVERIFIED, SURVEY.md §2a Katib rows): the
``kubeflow.org/v1beta1`` Katib API — objective/algorithm/parameters/
trialTemplate on Experiment, parameter assignments on Suggestion/Trial,
observation metrics on Trial status.  Trial templates embed any training job
kind (TPUJob-first here) with ``${trialParameters.x}`` substitution.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..core.api import APIServer, CRD, Invalid, Obj

GROUP = "kubeflow.org"
VERSION = "v1beta1"
API_VERSION = f"{GROUP}/{VERSION}"

PARAMETER_TYPES = ("double", "int", "categorical", "discrete")

# condition types
CREATED = "Created"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"
EARLY_STOPPED = "EarlyStopped"

LABEL_EXPERIMENT = "katib.kubeflow.org/experiment"


def _validate_experiment(obj: Obj) -> None:
    spec = obj.get("spec", {})
    if not spec.get("parameters"):
        raise Invalid("Experiment: spec.parameters required")
    for p in spec["parameters"]:
        if p.get("parameterType") not in PARAMETER_TYPES:
            raise Invalid(f"Experiment: bad parameterType {p.get('parameterType')!r}")
        fs = p.get("feasibleSpace", {})
        if p["parameterType"] in ("double", "int") and ("min" not in fs or "max" not in fs):
            raise Invalid(f"Experiment: parameter {p.get('name')}: feasibleSpace.min/max required")
        if p["parameterType"] in ("categorical", "discrete") and not fs.get("list"):
            raise Invalid(f"Experiment: parameter {p.get('name')}: feasibleSpace.list required")
    obj_spec = spec.get("objective", {})
    if obj_spec.get("type") not in ("maximize", "minimize"):
        raise Invalid("Experiment: objective.type must be maximize|minimize")
    if not obj_spec.get("objectiveMetricName"):
        raise Invalid("Experiment: objective.objectiveMetricName required")
    if not spec.get("trialTemplate", {}).get("trialSpec"):
        raise Invalid("Experiment: trialTemplate.trialSpec required")
    algo = spec.get("algorithm", {}).get("algorithmName", "random")
    from .suggest import algorithm_names

    if algo not in algorithm_names():
        raise Invalid(f"Experiment: unknown algorithm {algo!r}; have {algorithm_names()}")


def _default_experiment(obj: Obj) -> None:
    spec = obj.setdefault("spec", {})
    spec.setdefault("maxTrialCount", 10)
    spec.setdefault("parallelTrialCount", 3)
    spec.setdefault("maxFailedTrialCount", 3)
    spec.setdefault("algorithm", {}).setdefault("algorithmName", "random")
    spec.setdefault("metricsCollectorSpec", {"collector": {"kind": "StdOut"}})
    # NAS experiments (upstream nasConfig): expand the cell description into
    # one categorical parameter per layer — the shape the enas suggester and
    # the ${trialParameters.*} rendering already understand
    nas = spec.get("nasConfig")
    if nas and not spec.get("parameters"):
        ops = [o.get("operationType", str(i)) for i, o in enumerate(nas.get("operations", []))]
        layers = int(nas.get("graphConfig", {}).get("numLayers", 1))
        spec["parameters"] = [
            {"name": f"layer_{i}_op", "parameterType": "categorical",
             "feasibleSpace": {"list": ops}}
            for i in range(layers)
        ]


def register(api: APIServer) -> None:
    api.register_crd(CRD(GROUP, VERSION, "Experiment", "experiments",
                         validator=_validate_experiment, defaulter=_default_experiment))
    api.register_crd(CRD(GROUP, VERSION, "Suggestion", "suggestions"))
    api.register_crd(CRD(GROUP, VERSION, "Trial", "trials"))


# ------------------------------------------------------------ typed builders

@dataclass
class Parameter:
    name: str
    parameter_type: str  # double|int|categorical|discrete
    min: Optional[float] = None
    max: Optional[float] = None
    step: Optional[float] = None
    list: Optional[list] = None

    def to_obj(self) -> dict:
        fs: dict = {}
        if self.min is not None:
            fs["min"] = self.min
        if self.max is not None:
            fs["max"] = self.max
        if self.step is not None:
            fs["step"] = self.step
        if self.list is not None:
            fs["list"] = list(self.list)
        return {"name": self.name, "parameterType": self.parameter_type, "feasibleSpace": fs}


def experiment(
    name: str,
    parameters: list[Parameter],
    trial_spec: Obj,
    objective_metric: str,
    objective_type: str = "maximize",
    goal: Optional[float] = None,
    algorithm: str = "random",
    algorithm_settings: Optional[dict] = None,
    max_trials: int = 10,
    parallel_trials: int = 3,
    max_failed: int = 3,
    trial_parameters: Optional[list[dict]] = None,
    namespace: str = "default",
    metrics_collector: Optional[dict] = None,
) -> Obj:
    objective = {"type": objective_type, "objectiveMetricName": objective_metric}
    if goal is not None:
        objective["goal"] = goal
    spec_extra = (
        {"metricsCollectorSpec": metrics_collector} if metrics_collector else {})
    return {
        "apiVersion": API_VERSION,
        "kind": "Experiment",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "objective": objective,
            "algorithm": {
                "algorithmName": algorithm,
                "algorithmSettings": [
                    {"name": k, "value": str(v)} for k, v in (algorithm_settings or {}).items()
                ],
            },
            "parameters": [p.to_obj() for p in parameters],
            "maxTrialCount": max_trials,
            "parallelTrialCount": parallel_trials,
            "maxFailedTrialCount": max_failed,
            "trialTemplate": {
                "primaryContainerName": "main",
                "trialParameters": trial_parameters
                or [{"name": p.name, "reference": p.name} for p in parameters],
                "trialSpec": trial_spec,
            },
            **spec_extra,
        },
    }
