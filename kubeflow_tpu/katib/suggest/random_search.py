"""Random search (upstream: katib random suggestion service)."""

from __future__ import annotations

import numpy as np

from . import register
from .space import param_specs, sample_one, settings_dict


@register("random")
class RandomSuggester:
    def suggest(self, experiment, trials, count):
        seed = int(settings_dict(experiment).get("random_state", 0)) or None
        # fold in the number of existing trials so repeated calls differ
        rng = np.random.default_rng(None if seed is None else seed + len(trials))
        return [
            {p["name"]: sample_one(rng, p) for p in param_specs(experiment)}
            for _ in range(count)
        ]
