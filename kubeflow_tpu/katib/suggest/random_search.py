"""Random search (upstream: katib random suggestion service)."""

from __future__ import annotations

import numpy as np

from . import register
from .space import param_specs, sample_one, settings_dict


@register("random")
class RandomSuggester:
    def suggest(self, experiment, trials, count):
        raw = settings_dict(experiment).get("random_state")
        # fold in the number of existing trials so repeated calls differ
        # (0 is a valid, deterministic seed — only absence means entropy)
        rng = np.random.default_rng(None if raw is None else int(raw) + len(trials))
        return [
            {p["name"]: sample_one(rng, p) for p in param_specs(experiment)}
            for _ in range(count)
        ]
