"""Population Based Training (upstream: katib `pbt` suggestion service).

Exploit/explore over a population: each new suggestion picks a parent from
the top quantile of finished trials and perturbs it — numeric parameters are
scaled by a random factor around 1 (clipped to the feasible space), while
categorical parameters resample with a small probability.  The population
walks toward good regions while keeping diversity, which beats independent
sampling when the objective drifts with training time.

Deviation from upstream, documented: Katib's PBT service also rewires trial
CHECKPOINT lineage (children warm-start from the parent's weights via
annotations). Here suggestions carry hyperparameters only — the platform's
checkpoint auto-resume (`spec.checkpoint`) is per-trial; weight inheritance
across trials is left to the workload.
"""

from __future__ import annotations

import numpy as np

from . import register
from .space import observed, param_specs, sample_one, settings_dict


@register("pbt")
class PBTSuggester:
    def suggest(self, experiment, trials, count):
        specs = param_specs(experiment)
        settings = settings_dict(experiment)
        quantile = float(settings.get("truncation_threshold", 0.25))
        resample_p = float(settings.get("resample_probability", 0.25))
        raw = settings.get("random_state")
        rng = np.random.default_rng(None if raw is None else int(raw) + len(trials))

        _, ys, raw_assignments = observed(experiment, trials)
        if len(ys) == 0:  # first generation: pure exploration
            return [{p["name"]: sample_one(rng, p) for p in specs}
                    for _ in range(count)]

        order = np.argsort(ys)[::-1]  # best first (observed() negates minimize)
        n_top = max(1, int(np.ceil(len(ys) * quantile)))
        top = [raw_assignments[i] for i in order[:n_top]]

        out = []
        for _ in range(count):
            parent = top[int(rng.integers(len(top)))]
            child = {}
            for p in specs:
                name = p["name"]
                if p["parameterType"] in ("double", "int"):
                    fs = p["feasibleSpace"]
                    lo, hi = float(fs["min"]), float(fs["max"])
                    # classic PBT jitter: scale the VALUE by ~[0.8, 1.2] (a
                    # parent at the lower bound still explores upward), plus
                    # a small absolute kick so exact-zero values can move
                    v = float(parent[name]) * float(rng.uniform(0.8, 1.2))
                    v += float(rng.normal(0, 0.02)) * (hi - lo)
                    v = min(max(v, lo), hi)
                    child[name] = int(round(v)) if p["parameterType"] == "int" else v
                else:
                    child[name] = (sample_one(rng, p)
                                   if rng.random() < resample_p else parent[name])
            out.append(child)
        return out
