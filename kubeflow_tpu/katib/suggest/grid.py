"""Grid search (upstream: katib grid suggestion service)."""

from __future__ import annotations

import itertools

import numpy as np

from . import register
from .space import param_specs, settings_dict


def _axis(p: dict, default_steps: int) -> list:
    fs = p["feasibleSpace"]
    t = p["parameterType"]
    if t in ("categorical", "discrete"):
        return list(fs["list"])
    lo, hi = float(fs["min"]), float(fs["max"])
    if t == "int":
        step = int(float(fs.get("step", 1)) or 1)
        return list(range(int(lo), int(hi) + 1, step))
    if "step" in fs:
        n = int(round((hi - lo) / float(fs["step"]))) + 1
        return [lo + i * float(fs["step"]) for i in range(n)]
    return list(np.linspace(lo, hi, default_steps))


@register("grid")
class GridSuggester:
    def suggest(self, experiment, trials, count):
        default_steps = int(settings_dict(experiment).get("default_steps", 4))
        axes = [_axis(p, default_steps) for p in param_specs(experiment)]
        names = [p["name"] for p in param_specs(experiment)]
        full = [dict(zip(names, combo)) for combo in itertools.product(*axes)]
        seen = len(trials)  # grid is deterministic: skip already-issued points
        return full[seen : seen + count]
