"""Shared search-space utilities for suggestion algorithms."""

from __future__ import annotations

import numpy as np

from ...core.api import Obj


def param_specs(experiment: Obj) -> list[dict]:
    return experiment["spec"]["parameters"]


def sample_one(rng: np.random.Generator, p: dict):
    fs = p["feasibleSpace"]
    t = p["parameterType"]
    if t == "double":
        return float(rng.uniform(float(fs["min"]), float(fs["max"])))
    if t == "int":
        return int(rng.integers(int(fs["min"]), int(fs["max"]) + 1))
    return rng.choice(list(fs["list"]))


def to_unit(p: dict, value) -> float:
    """Map a parameter value into [0, 1] for surrogate models."""
    fs = p["feasibleSpace"]
    t = p["parameterType"]
    if t in ("double", "int"):
        lo, hi = float(fs["min"]), float(fs["max"])
        return (float(value) - lo) / max(hi - lo, 1e-12)
    values = list(fs["list"])
    return values.index(value) / max(len(values) - 1, 1)


def from_unit(p: dict, u: float):
    fs = p["feasibleSpace"]
    t = p["parameterType"]
    u = float(np.clip(u, 0.0, 1.0))
    if t == "double":
        lo, hi = float(fs["min"]), float(fs["max"])
        return lo + u * (hi - lo)
    if t == "int":
        lo, hi = int(fs["min"]), int(fs["max"])
        return int(round(lo + u * (hi - lo)))
    values = list(fs["list"])
    return values[int(round(u * (len(values) - 1)))]


def observed(experiment: Obj, trials: list[Obj]) -> tuple[np.ndarray, np.ndarray, list[dict]]:
    """(X in unit cube, y objective values, raw assignment dicts) of succeeded
    trials. y is negated for 'minimize' so larger is always better."""
    specs = param_specs(experiment)
    metric = experiment["spec"]["objective"]["objectiveMetricName"]
    sign = 1.0 if experiment["spec"]["objective"]["type"] == "maximize" else -1.0
    xs, ys, raw = [], [], []
    for t in trials:
        obs = t.get("status", {}).get("observation", {})
        val = None
        for m in obs.get("metrics", []):
            if m["name"] == metric and m.get("latest") is not None:
                val = float(m["latest"])
        if val is None:
            continue
        assign = {a["name"]: a["value"] for a in t["spec"].get("parameterAssignments", [])}
        if not all(p["name"] in assign for p in specs):
            continue
        xs.append([to_unit(p, assign[p["name"]]) for p in specs])
        ys.append(sign * val)
        raw.append(assign)
    if not xs:
        return np.zeros((0, len(specs))), np.zeros((0,)), []
    return np.asarray(xs, float), np.asarray(ys, float), raw


def settings_dict(experiment: Obj) -> dict:
    return {
        s["name"]: s["value"]
        for s in experiment["spec"].get("algorithm", {}).get("algorithmSettings", [])
    }
