"""ENAS-style neural-architecture-search suggester.

Upstream analogue (UNVERIFIED, SURVEY.md §2a suggestion-services row): Katib's
ENAS suggestion service — an RL controller proposing architectures, updated
with REINFORCE from trial rewards.  Numpy-only reimplementation, same shape
as the other suggesters (no TF/torch controller — SURVEY.md §7 environment
reality): every categorical parameter is an edge in the cell, its feasible
list the candidate operations, and a per-(edge, op) logit table is the
controller policy.

Statelessness contract: suggesters are constructed per call, so the policy is
*replayed* deterministically from the completed-trial history — logits start
at zero and one REINFORCE step (moving-average baseline) is applied per
completed trial in creation order.  Sampling is seeded by ``random_state`` +
trial count, so repeated reconciles are idempotent.

Experiments may alternatively carry an upstream-style ``spec.nasConfig``
(``graphConfig.numLayers`` + ``operations``); the defaulter in katib/api.py
expands it into the equivalent categorical parameters.
"""

from __future__ import annotations

import numpy as np

from . import register
from .space import param_specs, settings_dict


def _reward(trial: dict, metric: str, sign: float):
    for m in trial.get("status", {}).get("observation", {}).get("metrics", []):
        if m["name"] == metric:
            return sign * float(m["latest"])
    return None


@register("enas")
class EnasSuggester:
    def suggest(self, experiment, trials, count):
        settings = settings_dict(experiment)
        lr = float(settings.get("learning_rate", 2.0))
        temp = float(settings.get("temperature", 1.0))
        seed = int(settings.get("random_state", 0))

        edges = [p for p in param_specs(experiment) if p["parameterType"] == "categorical"]
        if not edges:
            raise ValueError("enas needs categorical parameters (the cell edges)")
        ops = {p["name"]: list(p["feasibleSpace"]["list"]) for p in edges}
        logits = {p["name"]: np.zeros(len(ops[p["name"]])) for p in edges}

        metric = experiment["spec"]["objective"]["objectiveMetricName"]
        sign = 1.0 if experiment["spec"]["objective"]["type"] == "maximize" else -1.0

        # replay: one REINFORCE step per completed trial, in creation order
        baseline = None
        for t in trials:
            r = _reward(t, metric, sign)
            if r is None:
                continue
            advantage = r if baseline is None else r - baseline
            baseline = r if baseline is None else 0.7 * baseline + 0.3 * r
            assignments = {
                a["name"]: a["value"]
                for a in t.get("spec", {}).get("parameterAssignments", [])
            }
            for name, choices in ops.items():
                if assignments.get(name) not in choices:
                    continue
                chosen = choices.index(assignments[name])
                p = _softmax(logits[name] / temp)
                # d/dlogits log softmax[chosen] = onehot - p
                grad = -p
                grad[chosen] += 1.0
                logits[name] += lr * advantage * grad

        rng = np.random.default_rng(seed + len(trials))
        out = []
        for _ in range(count):
            arch = {}
            for name, choices in ops.items():
                p = _softmax(logits[name] / temp)
                arch[name] = choices[int(rng.choice(len(choices), p=p))]
            # non-edge parameters (e.g. lr) ride along with random samples
            for spec in param_specs(experiment):
                if spec["name"] not in arch:
                    from .space import sample_one

                    arch[spec["name"]] = sample_one(rng, spec)
            out.append(arch)
        return out


def _softmax(z: np.ndarray) -> np.ndarray:
    z = z - z.max()
    e = np.exp(z)
    return e / e.sum()
