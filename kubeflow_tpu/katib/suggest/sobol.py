"""Sobol quasi-random search (upstream: katib `sobol` via goptuna).

A digital (t, s)-sequence in base 2: successive points fill the unit cube
far more evenly than i.i.d. random draws, so low-budget sweeps cover the
search space without the clumping/gaps random search produces.  Numpy-only
construction (no scipy.qmc in the image): Gray-code Sobol with Joe–Kuo-style
direction numbers for the first 21 dimensions, plus a seeded digital shift
(per-dimension XOR mask) so different ``random_state`` settings give
different — still low-discrepancy — sequences.
"""

from __future__ import annotations

import numpy as np

from . import register
from .space import from_unit, param_specs, settings_dict

_BITS = 30

# (s, a, m) primitive-polynomial parameters per dimension (dimension 1 is the
# van der Corput sequence).  Any valid set (odd m_i < 2^i) yields a digital
# sequence with the base-2 stratification property the tests pin down.
_JOE_KUO = (
    (1, 0, (1,)),
    (2, 1, (1, 3)),
    (3, 1, (1, 3, 1)),
    (3, 2, (1, 1, 1)),
    (4, 1, (1, 1, 3, 3)),
    (4, 4, (1, 3, 5, 13)),
    (5, 2, (1, 1, 5, 5, 17)),
    (5, 4, (1, 1, 5, 5, 5)),
    (5, 7, (1, 1, 7, 11, 19)),
    (5, 11, (1, 1, 5, 1, 1)),
    (5, 13, (1, 1, 1, 3, 11)),
    (5, 14, (1, 3, 5, 5, 31)),
    (6, 1, (1, 3, 3, 9, 7, 49)),
    (6, 13, (1, 1, 1, 15, 21, 21)),
    (6, 16, (1, 3, 1, 13, 27, 49)),
    (6, 19, (1, 1, 1, 15, 7, 5)),
    (6, 22, (1, 3, 1, 15, 13, 25)),
    (6, 25, (1, 1, 5, 5, 19, 61)),
    (7, 1, (1, 3, 7, 11, 23, 15, 103)),
    (7, 4, (1, 3, 7, 13, 13, 45, 109)),
)
MAX_DIMS = 1 + len(_JOE_KUO)

# the stratification property needs every m_i odd and < 2^i — guard the
# table itself so a bad edit fails at import, not as out-of-range samples
for _s, _a, _m in _JOE_KUO:
    for _i, _mi in enumerate(_m, start=1):
        assert _mi % 2 == 1 and _mi < (1 << _i), (_s, _a, _m)


def _direction_numbers(dim: int) -> np.ndarray:
    """V[i] (i < _BITS) for 0-based dimension ``dim``."""
    v = np.zeros(_BITS, dtype=np.int64)
    if dim == 0:  # van der Corput
        for i in range(_BITS):
            v[i] = 1 << (_BITS - 1 - i)
        return v
    s, a, m = _JOE_KUO[dim - 1]
    for i in range(min(s, _BITS)):
        v[i] = m[i] << (_BITS - 1 - i)
    for i in range(s, _BITS):
        x = v[i - s] ^ (v[i - s] >> s)
        for k in range(1, s):
            if (a >> (s - 1 - k)) & 1:
                x ^= v[i - k]
        v[i] = x
    return v


def sobol_points(start: int, count: int, dims: int, shift: np.ndarray) -> np.ndarray:
    """Points ``start .. start+count-1`` of the shifted sequence, [count, dims]
    in [0, 1).  Gray-code order: point n XORs V[j] for the set bits of
    gray(n) = n ^ (n >> 1)."""
    if dims > MAX_DIMS:
        raise ValueError(f"sobol supports up to {MAX_DIMS} parameters, got {dims}")
    vs = [_direction_numbers(d) for d in range(dims)]
    out = np.empty((count, dims))
    for row, n in enumerate(range(start, start + count)):
        gray = n ^ (n >> 1)
        for d in range(dims):
            x = int(shift[d])
            g = gray
            j = 0
            while g:
                if g & 1:
                    x ^= int(vs[d][j])
                g >>= 1
                j += 1
            out[row, d] = x / float(1 << _BITS)
    return out


@register("sobol")
class SobolSuggester:
    def suggest(self, experiment, trials, count):
        specs = param_specs(experiment)
        raw = settings_dict(experiment).get("random_state")
        if raw is None:
            shift = np.zeros(len(specs), dtype=np.int64)  # the pure sequence
        else:
            rng = np.random.default_rng(int(raw))
            shift = rng.integers(0, 1 << _BITS, size=len(specs), dtype=np.int64)
        # resume where the experiment left off; skip index 0 (the origin)
        start = len(trials) + 1
        pts = sobol_points(start, count, len(specs), shift)
        return [
            {p["name"]: from_unit(p, u) for p, u in zip(specs, row)}
            for row in pts
        ]
